"""Table handlers mirroring the reference `multiverso/tables.py`
(SURVEY.md §3.5): ``ArrayTableHandler(size, init_value)`` and
``MatrixTableHandler(num_rows, num_cols, init_value)`` with numpy in/out
``get()/add(data, sync=)`` — plus the row-subset variants of the matrix
handler (``get(row_ids)``, ``add(data, row_ids)``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from multiverso_tpu.tables import ArrayTable, MatrixTable
from multiverso_tpu.updaters import AddOption


class TableHandler:
    """Base, matching the reference's abstract TableHandler."""

    def get(self):
        raise NotImplementedError

    def add(self, data, sync: bool = False):
        raise NotImplementedError


class ArrayTableHandler(TableHandler):
    def __init__(self, size: int, init_value: Any = None,
                 dtype: Any = "float32", updater: str = "default",
                 name: str = "array_handler") -> None:
        self._table = ArrayTable(
            size, dtype, init_value=0 if init_value is None else init_value,
            updater=updater, name=name)

    @property
    def size(self) -> int:
        return self._table.size

    def get(self) -> np.ndarray:
        return self._table.get()

    def add(self, data, sync: bool = False,
            option: Optional[AddOption] = None) -> None:
        self._table.add(np.asarray(data, dtype=self._table.dtype.name),
                        option=option, sync=sync)


class MatrixTableHandler(TableHandler):
    def __init__(self, num_rows: int, num_cols: int, init_value: Any = None,
                 dtype: Any = "float32", updater: str = "default",
                 name: str = "matrix_handler") -> None:
        self._table = MatrixTable(
            num_rows, num_cols, dtype,
            init_value=0 if init_value is None else init_value,
            updater=updater, name=name)

    @property
    def num_rows(self) -> int:
        return self._table.num_rows

    @property
    def num_cols(self) -> int:
        return self._table.num_cols

    def get(self, row_ids: Optional[Sequence[int]] = None) -> np.ndarray:
        """Whole matrix, or a row subset when ``row_ids`` given (reference:
        ``GetMatrixTableAll/ByRows``)."""
        if row_ids is None:
            return self._table.get()
        return self._table.get_rows(row_ids)

    def add(self, data, row_ids: Optional[Sequence[int]] = None,
            sync: bool = False, option: Optional[AddOption] = None) -> None:
        data = np.asarray(data, dtype=self._table.dtype.name)
        if row_ids is None:
            self._table.add(data, option=option, sync=sync)
        else:
            self._table.add_rows(row_ids, data, option=option, sync=sync)
