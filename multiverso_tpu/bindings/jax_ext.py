"""JAX analog of the reference's framework extensions.

Reference mapping (upstream layout `binding/python/multiverso/theano_ext/
sharedvar.py` and `.../lasagne_ext/param_manager.py` — SURVEY.md §3.5 /
§4.4):

- ``mv_shared`` was a drop-in for ``theano.shared`` that tracks the
  last-synced snapshot; ``sync()`` ships ``add(current − last_synced)``
  then ``get()``s the merged value back. Workers never overwrite each
  other — they ship *differences*, so concurrent updates merge additively.
  :class:`MVSharedVariable` keeps exactly that delta-sync contract over a
  host-mirrored value.
- ``LasagneParamManager`` registered all params of a network into one
  table with a per-iteration ``sync_all_param()``. :class:`ParamManager`
  does the same for an arbitrary pytree of arrays (flax/haiku params,
  plain dicts) flattened into one ArrayTable.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import jax
import numpy as np

from multiverso_tpu.bindings.table_handlers import ArrayTableHandler

_ALL_SHARED: List["MVSharedVariable"] = []
_ALL_LOCK = threading.Lock()


class MVSharedVariable:
    """Delta-synced shared value backed by an ArrayTable."""

    def __init__(self, value, name: str = "mv_shared") -> None:
        self._value = np.array(value, dtype=np.float32, copy=True)
        self._shape = self._value.shape
        self._table = ArrayTableHandler(int(self._value.size) or 1,
                                        name=name)
        # publish the initial value once: add(initial - 0)
        self._table.add(self._value.ravel(), sync=True)
        self._last_synced = self._table.get().reshape(self._shape).copy()
        self._value = self._last_synced.copy()
        with _ALL_LOCK:
            _ALL_SHARED.append(self)

    def get_value(self) -> np.ndarray:
        return self._value.copy()

    def set_value(self, value) -> None:
        value = np.asarray(value, dtype=np.float32)
        if value.shape != self._shape:
            raise ValueError(f"shape {value.shape} != {self._shape}")
        self._value = value.copy()

    def sync(self) -> None:
        """add(current − last_synced); get() the merged value back."""
        delta = self._value - self._last_synced
        self._table.add(delta.ravel(), sync=True)
        merged = self._table.get().reshape(self._shape)
        self._value = merged.copy()
        self._last_synced = merged.copy()


def mv_shared(value, name: str = "mv_shared") -> MVSharedVariable:
    return MVSharedVariable(value, name=name)


def sync_all_mv_shared_vars() -> None:
    """Reference: ``sharedvar.sync_all_mv_shared_vars()``."""
    with _ALL_LOCK:
        shared = list(_ALL_SHARED)
    for var in shared:
        var.sync()


def reset_shared_vars() -> None:
    with _ALL_LOCK:
        _ALL_SHARED.clear()


class ParamManager:
    """Register a pytree of params into one table; ``sync_all_param()``
    per iteration/epoch (reference ``LasagneParamManager``).

    ``compress="1bit"`` runs each synced delta through the 1-bit
    quantization filter with local error feedback (the reference's
    optional delta compression before send, SURVEY.md §3.7): the table
    receives the DEQUANTIZED delta — what would arrive on the far side
    of a DCN-crossing transfer at 1/32 the float wire bytes — and the
    quantization error carries into the next sync.
    """

    def __init__(self, params: Any, name: str = "param_manager",
                 compress: Optional[str] = None,
                 compress_block: int = 512) -> None:
        leaves, self._treedef = jax.tree.flatten(params)
        self._shapes = [np.shape(l) for l in leaves]
        self._sizes = [int(np.size(l)) for l in leaves]
        self._total = sum(self._sizes)
        self._table = ArrayTableHandler(self._total, name=name)
        if compress is None:
            self._quant = None
        elif compress == "1bit":
            from multiverso_tpu.utils.quantization import OneBitQuantizer
            self._quant = OneBitQuantizer(block=compress_block)
            self._residual = np.zeros(self._total, np.float32)
        else:
            raise ValueError(f"compress must be None or '1bit', "
                             f"got {compress!r}")
        flat = np.concatenate(
            [np.asarray(l, dtype=np.float32).ravel() for l in leaves]) \
            if leaves else np.zeros(0, np.float32)
        self._table.add(flat, sync=True)
        self._last_synced = self._table.get().copy()

    def _flatten(self, params: Any) -> np.ndarray:
        leaves = jax.tree.leaves(params)
        if len(leaves) != len(self._sizes):
            raise ValueError("param tree structure changed since init")
        return np.concatenate(
            [np.asarray(l, dtype=np.float32).ravel() for l in leaves])

    def _unflatten(self, flat: np.ndarray) -> Any:
        out, off = [], 0
        for shape, size in zip(self._shapes, self._sizes):
            out.append(flat[off:off + size].reshape(shape))
            off += size
        return jax.tree.unflatten(self._treedef, out)

    def sync_all_param(self, params: Any) -> Any:
        """Delta-sync the whole tree; returns the merged tree."""
        flat = self._flatten(params)
        delta = flat - self._last_synced
        if self._quant is not None:
            from multiverso_tpu import core
            mesh = self._table._table.mesh
            put = lambda a: core.place(a, mesh=mesh)
            sign, pos, neg, res = self._quant.quantize(
                put(delta), put(self._residual))
            self._residual = np.asarray(res)
            delta = np.asarray(self._quant.dequantize(
                sign, pos, neg, (self._total,)))
        self._table.add(delta, sync=True)
        merged = self._table.get()
        self._last_synced = merged.copy()
        return self._unflatten(merged)
