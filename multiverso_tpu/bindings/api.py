"""Reference `multiverso/api.py` surface (SURVEY.md §3.5): init/shutdown/
barrier and topology queries, names preserved."""

from __future__ import annotations

from typing import Optional, Sequence

from multiverso_tpu import core
from multiverso_tpu.utils import configure


def init(sync: bool = True, argv: Optional[Sequence[str]] = None) -> None:
    """Reference: ``multiverso.init(sync=...)``. On TPU sync DP is the
    native mode; ``sync=False`` is accepted for script compat and recorded
    in the ``sync`` flag (async PS semantics are subsumed by sync DP —
    SURVEY.md §3.8)."""
    configure.set_flag("sync", bool(sync))
    core.init(argv)


def shutdown() -> None:
    core.shutdown()


def barrier() -> None:
    core.barrier()


def workers_num() -> int:
    return core.num_workers()


def worker_id() -> int:
    return core.worker_id()


def server_id() -> int:
    return core.server_id()


def is_master_worker() -> bool:
    """Reference semantics: exactly one worker is 'master' (does data
    splitting / logging). Process 0 of the job."""
    return core.rank() == 0
