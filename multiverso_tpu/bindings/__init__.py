"""Binding-compat Python API.

Mirrors the reference Python binding surface (upstream layout
`binding/python/multiverso/{api.py,tables.py}` — SURVEY.md §3.5), so
training scripts written against the reference's ctypes binding port with
an import swap::

    import multiverso_tpu.bindings as multiverso
    multiverso.init(sync=True)
    tbl = multiverso.ArrayTableHandler(1000, init_value=0.0)
    tbl.add(delta); vals = tbl.get()
    multiverso.barrier()
    multiverso.shutdown()

The reference's C-ABI/ctypes hop does not exist: handlers sit directly on
the sharded-array tables. The delta-sync data-parallel wrapper
(`theano_ext.sharedvar.mv_shared` / `lasagne_ext.param_manager`) has its
JAX analog in :mod:`multiverso_tpu.bindings.jax_ext`.
"""

from multiverso_tpu.bindings.api import (barrier, init, is_master_worker,
                                         server_id, shutdown, workers_num,
                                         worker_id)
from multiverso_tpu.bindings.table_handlers import (ArrayTableHandler,
                                                    MatrixTableHandler)
from multiverso_tpu.bindings import jax_ext

__all__ = ["ArrayTableHandler", "MatrixTableHandler", "barrier", "init",
           "is_master_worker", "jax_ext", "server_id", "shutdown",
           "worker_id", "workers_num"]
