"""Distributed word2vec — TPU-native rebuild of the reference's
`Applications/WordEmbedding/` (upstream layout; SURVEY.md §3.6/§4.5):
skip-gram & CBOW, negative sampling & hierarchical softmax, embeddings in
two row-sharded MatrixTables.

Reference shape (SURVEY.md §4.5): `Distributed_wordembedding` main +
`WordEmbedding` model math + N `Trainer` threads doing local scalar SGD on
per-block row copies + `ParameterLoader` prefetch + per-block delta
aggregation `Add`ed to the MatrixTables.

TPU design (the whole point — nothing here is a translation):

- The per-pair scalar loop (dot/sigmoid/axpy over one row pair at a time)
  becomes a **batched jitted superstep**: ``lax.scan`` over S minibatches
  of B pairs, each step = gather rows → one einsum against the MXU →
  analytic sigmoid gradients → duplicate-safe scatter-add. One dispatch
  trains S*B pairs.
- The reference's Trainer-thread Hogwild + per-block aggregation becomes
  the batched scatter-add: duplicate rows within a minibatch accumulate
  additively (`.at[].add`), exactly the reference's Aggregator semantics.
- Negative sampling runs **on device**: by default a precomputed unigram
  table (the reference word2vec's own ``InitUnigramTable`` — one uniform
  + ONE gather per draw), or the exact Vose alias method
  (``ns_sampler="alias"``); no host RNG in the hot loop
  (`jax.random.fold_in`-per-step keys keep it reproducible across chips).
- Data parallelism: the pair stream is sharded over the mesh ``"data"``
  axis; the embedding tables keep their row sharding, so XLA inserts the
  cross-chip reduction of the scatter contributions (psum over ICI) —
  the Get/Add round-trip of SURVEY.md §4.2/§4.3 collapsed into one
  compiled program.
- Hierarchical softmax uses the Huffman (codes, points) arrays from the
  data layer, padded to fixed length with a masked scratch row — static
  shapes for XLA.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import client, core, telemetry
from multiverso_tpu.data.corpus import Corpus
from multiverso_tpu.tables import MatrixTable, make_superstep
from multiverso_tpu.utils import log


@dataclasses.dataclass
class W2VConfig:
    """The reference app's argv config (word2vec-style flags)."""
    embedding_dim: int = 100
    window: int = 5
    negative: int = 5           # negatives per positive (NS objective)
    model: str = "skipgram"     # "skipgram" | "cbow"
    objective: str = "ns"       # "ns" (negative sampling) | "hs" (Huffman)
    batch_size: int = 1024      # pairs per scan step
    steps_per_call: int = 16    # scan length: pairs/dispatch = B * S
    learning_rate: float = 0.025
    min_lr_frac: float = 1e-4   # linear decay floor (lr * frac)
    epochs: int = 1
    subsample: Optional[float] = None   # None -> keep the corpus's setting
    unigram_power: float = 0.75
    ns_sampler: str = "table"   # "table" — the reference word2vec's own
    # unigram-table draw (one uniform + ONE gather from a precomputed id
    # table; measured ~130us/step cheaper than alias on the chip) |
    # "alias" — exact Vose alias draw (two gathers; use when the vocab
    # is too skewed for table quantization, see ns_table_size)
    ns_table_size: int = 1 << 20    # table quantization: each table slot
    # is 2^-20 of the noise mass (the reference used a 1e8-entry table
    # for the same purpose; 1M slots bounds per-word probability error
    # at ~1e-6 of mass, negligible for NS)
    max_code_len: int = 40      # HS: Huffman code pad length
    local_data: bool = False    # multi-process: each process generates
    # ONLY its devices' share of every batch from ITS OWN corpus shard
    # (seed folded with the rank so streams differ) — the reference's
    # workers-each-stream-their-own-corpus model. batch_size stays the
    # GLOBAL batch; processes must own disjoint data lanes (validated).
    # Call counts are agreed collectively from the shards' sizes; each
    # process cycles its local corpus to fill the agreed schedule.
    checkpoint_prefix: str = ""     # periodic mid-train checkpoints
    checkpoint_interval: int = 0    # store every N superstep calls
    # (0 = end-of-training dumps only — the reference worker's [H]
    # behavior; the periodic trigger mirrors SURVEY §6.4's flag-driven
    # periodic server dump)
    seed: int = 0
    dtype: str = "float32"


def _normalized_rows(emb: np.ndarray) -> np.ndarray:
    """Rows scaled to unit norm (zero rows guarded)."""
    return emb / np.maximum(
        np.linalg.norm(emb, axis=1, keepdims=True), 1e-12)


def _topk_excluding(norm: np.ndarray, q: np.ndarray,
                    exclude, k: int) -> np.ndarray:
    """Top-k row ids of ``norm`` by dot with ``q``, excluding ids
    (shared by nearest() and the compute-accuracy analogy rule)."""
    sims = norm @ q
    sims[list(exclude)] = -np.inf
    return np.argsort(-sims)[:k]


def build_alias(probs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vose alias-table construction, O(V).

    Returns (prob f32[V], alias int32[V]): sample j ~ U[0,V), u ~ U[0,1);
    result = j if u < prob[j] else alias[j].
    """
    v = len(probs)
    prob = np.zeros(v, np.float64)
    alias = np.zeros(v, np.int32)
    scaled = probs.astype(np.float64) * v
    small = [i for i in range(v) if scaled[i] < 1.0]
    large = [i for i in range(v) if scaled[i] >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] = scaled[l] - (1.0 - scaled[s])
        (small if scaled[l] < 1.0 else large).append(l)
    for i in large + small:
        prob[i] = 1.0
    return prob.astype(np.float32), alias


def alias_sample(key, prob: jax.Array, alias: jax.Array, shape):
    """Draw ids from the alias table (two gathers, no host round-trip)."""
    kj, ku = jax.random.split(key)
    j = jax.random.randint(kj, shape, 0, prob.shape[0])
    u = jax.random.uniform(ku, shape)
    return jnp.where(u < prob[j], j, alias[j]).astype(jnp.int32)


def build_unigram_table(probs: np.ndarray, size: int) -> np.ndarray:
    """The reference word2vec's ``InitUnigramTable``: an int32[size]
    table where word w fills a run of slots proportional to probs[w];
    a draw is one uniform scaled to a slot index — ONE gather on device
    (vs the alias method's two), at a quantization of 1/size of the
    total mass per slot."""
    cum = np.cumsum(probs.astype(np.float64))
    cum /= cum[-1]
    # slot i covers mass ((i+0.5)/size); searchsorted maps it to a word
    return np.searchsorted(
        cum, (np.arange(size) + 0.5) / size).astype(np.int32)


def table_sample(key, table: jax.Array, shape):
    """Draw ids from the unigram table: uniform -> slot -> id."""
    u = jax.random.uniform(key, shape)
    idx = (u * table.shape[0]).astype(jnp.int32)
    return jnp.take(table, idx, axis=0)


class WordEmbedding:
    """The app: two MatrixTables + the fused scan superstep."""

    def __init__(self, corpus: Corpus, config: W2VConfig, *,
                 mesh=None, name: str = "w2v") -> None:
        self.corpus = corpus
        self.config = config
        self.mesh = mesh if mesh is not None else core.mesh()
        c = config
        # an explicit config subsample (word2vec's -sample) overrides the
        # corpus's; None defers to whatever the corpus was built with
        if c.subsample is not None:
            corpus.set_subsample(c.subsample)
        v, d = corpus.vocab_size, c.embedding_dim
        rng = np.random.default_rng(c.seed)
        # reference init: input embeddings ~ U(-0.5/dim, 0.5/dim), output 0
        w_in_init = rng.uniform(-0.5 / d, 0.5 / d, (v, d)).astype(c.dtype)
        self.w_in = MatrixTable(v, d, c.dtype, init_value=w_in_init,
                                updater="default", mesh=self.mesh,
                                name=f"{name}_in")
        self.w_out = MatrixTable(v, d, c.dtype, init_value=0,
                                 updater="default", mesh=self.mesh,
                                 name=f"{name}_out")
        self._scratch = self.w_in.padded_shape[0] - 1  # masked-lane row
        # MVTPU_STALENESS: embeddings() (logging/eval — nearest,
        # similarity, analogy; never fed back into training) serves from
        # a bounded-staleness cached view; save_text stays exact
        self._emb_view = client.maybe_cached_view(self.w_in)

        # negative-sampling alias table: device-resident constants, placed
        # replicated ON THE MESH (a bare jnp.asarray would land them on the
        # process default device, which may be a different platform)
        rep = partial(core.place, mesh=self.mesh)
        if c.objective == "ns":
            if c.ns_sampler == "table":
                self._ns_table = rep(build_unigram_table(
                    corpus.unigram_probs(c.unigram_power),
                    c.ns_table_size))
            elif c.ns_sampler == "alias":
                p, a = build_alias(corpus.unigram_probs(c.unigram_power))
                self._alias_prob = rep(p)
                self._alias_idx = rep(a)
            else:
                raise ValueError(f"ns_sampler must be 'table' or "
                                 f"'alias', got {c.ns_sampler!r}")
        elif c.objective == "hs":
            codes, points, lengths = corpus.huffman(c.max_code_len)
            L = c.max_code_len
            # mask beyond each word's code length; park masked lanes on the
            # scratch row so the scatter is shape-static
            msk = np.arange(L)[None, :] < lengths[:, None]
            pts = np.where(msk, points[:, :L], self._scratch)
            self._hs_points = rep(pts.astype(np.int32))
            self._hs_codes = rep(codes[:, :L].astype(np.float32))
            self._hs_mask = rep(msk.astype(np.float32))
        else:
            raise ValueError(f"objective must be 'ns' or 'hs', "
                             f"got {c.objective!r}")
        if c.model not in ("skipgram", "cbow"):
            raise ValueError(f"model must be 'skipgram' or 'cbow', "
                             f"got {c.model!r}")
        self._key = core.prng_key(c.seed, mesh=self.mesh)
        self.run_ckpt = None        # ft.checkpoint.wire_app attaches
        self._step_no = 0
        self._sched_offset = 0      # set by load(): resumed-call count
        self._sched_plan = 0        # set by load(): original planned
        # call count (0 = fresh run; train() re-plans per call as today)
        self._train_plan = 0        # last train()'s effective plan
        self._last_store = ()       # (prefix, step) of the last store
        self.loss_history: list = []
        self._local_chunks = None   # local_data: [(device, b0, b1), ...]
        if c.local_data and jax.process_count() > 1:
            self._setup_local_data()
        self._build_superstep()

    def _setup_local_data(self) -> None:
        """Per-process data lanes: which contiguous B-chunks of the
        global batch this process's devices own (sorted by offset), with
        a single-owner validation across processes and a shared-
        dictionary check (the replicated NS table / Huffman arrays and
        the table shapes are all built from the local corpus — every
        process must hold the SAME dictionary, only the token stream is
        per-process)."""
        import zlib
        from multiverso_tpu.parallel.multihost import (
            allgather_i64, owned_axis_slices, validate_single_owner)
        c = self.config
        B = c.batch_size
        sh = NamedSharding(self.mesh, P(None, core.DATA_AXIS, None))
        self._dev_slices = owned_axis_slices(
            sh, (c.steps_per_call, B, 1), axis=1)
        # distinct chunks (in-process model replicas share one), sorted:
        # the local batch is their concatenation in offset order
        self._local_chunks = sorted({(b0, b1)
                                     for _, b0, b1 in self._dev_slices})
        self._local_batch = sum(b1 - b0 for b0, b1 in self._local_chunks)
        mask = np.zeros(B, np.int32)
        for b0, b1 in self._local_chunks:
            mask[b0:b1] = 1
        validate_single_owner(mask, "local_data")
        counts = np.ascontiguousarray(
            np.asarray(self.corpus.unigram_probs(c.unigram_power),
                       np.float64))
        digest = np.array([self.corpus.vocab_size,
                           zlib.crc32(counts.tobytes())], np.int64)
        gathered = allgather_i64(digest)
        if not np.all(gathered == gathered[0]):
            raise ValueError(
                "local_data requires the SAME dictionary (vocab + "
                "frequencies) on every process — only the token stream "
                f"is per-process; got per-rank (vocab, counts-crc32) = "
                f"{gathered.tolist()}")

    # -- the fused superstep ----------------------------------------------

    def _pos_neg_step(self, w_out, v, tgt, key, lr):
        """Shared NS inner math: v [B,D] input vectors vs target ids [B].
        Returns (w_out', grad wrt v [B,D], mean loss)."""
        c = self.config
        if c.ns_sampler == "table":
            negs = table_sample(key, self._ns_table,
                                (v.shape[0], c.negative))
        else:
            negs = alias_sample(key, self._alias_prob, self._alias_idx,
                                (v.shape[0], c.negative))
        ids = jnp.concatenate([tgt[:, None], negs], axis=1)   # [B, 1+K]
        u = jnp.take(w_out, ids, axis=0)                      # [B, 1+K, D]
        logits = jnp.einsum("bd,bkd->bk", v, u)
        labels = jnp.zeros_like(logits).at[:, 0].set(1.0)
        sig = jax.nn.sigmoid(logits)
        # binary CE on (pos, negs); analytic grad dL/dlogit = sig - label
        loss = -jnp.mean(
            jnp.sum(labels * jax.nn.log_sigmoid(logits)
                    + (1.0 - labels) * jax.nn.log_sigmoid(-logits), axis=1))
        g = (sig - labels) * lr                               # [B, 1+K]
        grad_v = jnp.einsum("bk,bkd->bd", g, u)
        grad_u = g[:, :, None] * v[:, None, :]                # [B,1+K,D]
        w_out = w_out.at[ids.reshape(-1)].add(
            -grad_u.reshape(-1, u.shape[-1]).astype(w_out.dtype))
        return w_out, grad_v, loss

    def _hs_step(self, w_out, v, tgt, lr):
        """Hierarchical-softmax inner math along the Huffman path."""
        pts = jnp.take(self._hs_points, tgt, axis=0)          # [B, L]
        code = jnp.take(self._hs_codes, tgt, axis=0)          # [B, L] 0/1
        msk = jnp.take(self._hs_mask, tgt, axis=0)            # [B, L]
        u = jnp.take(w_out, pts, axis=0)                      # [B, L, D]
        logits = jnp.einsum("bd,bld->bl", v, u)
        sig = jax.nn.sigmoid(logits)
        # label = code bit: P(go-right) modeled by sigmoid
        loss = -jnp.sum(msk * (code * jax.nn.log_sigmoid(logits)
                               + (1 - code) * jax.nn.log_sigmoid(-logits))
                        ) / jnp.maximum(jnp.sum(msk), 1.0)
        g = (sig - code) * msk * lr                           # [B, L]
        grad_v = jnp.einsum("bl,bld->bd", g, u)
        grad_u = g[:, :, None] * v[:, None, :]
        w_out = w_out.at[pts.reshape(-1)].add(
            -grad_u.reshape(-1, u.shape[-1]).astype(w_out.dtype))
        return w_out, grad_v, loss

    def _build_superstep(self) -> None:
        c = self.config
        cbow = c.model == "cbow"

        def scan_body(carry, inp):
            w_in, w_out = carry
            src, tgt, key, lr = inp
            if cbow:
                # src [B, 2w] context ids (scratch row = padding), tgt [B]
                ctx_mask = (src != self._scratch).astype(w_in.dtype)
                n_ctx = jnp.maximum(ctx_mask.sum(axis=1, keepdims=True), 1.0)
                vecs = jnp.take(w_in, src, axis=0)            # [B, 2w, D]
                v = jnp.einsum("bwd,bw->bd", vecs, ctx_mask) / n_ctx
            else:
                v = jnp.take(w_in, src, axis=0)               # [B, D]
            if c.objective == "ns":
                w_out, grad_v, loss = self._pos_neg_step(
                    w_out, v, tgt, key, lr)
            else:
                w_out, grad_v, loss = self._hs_step(w_out, v, tgt, lr)
            if cbow:
                # spread the input-side gradient over the context words
                gctx = (grad_v / n_ctx)[:, None, :] * ctx_mask[:, :, None]
                w_in = w_in.at[src.reshape(-1)].add(
                    -gctx.reshape(-1, gctx.shape[-1]).astype(w_in.dtype))
            else:
                w_in = w_in.at[src].add(-grad_v.astype(w_in.dtype))
            return (w_in, w_out), loss

        def body(params, states, locals_, options, pairs, key, lrs):
            # pairs [S, B, ctx+1]: context ids + target in ONE operand
            # (one H2D placement per call instead of two — the transfer
            # RPC count is the measured e2e bottleneck on tunneled
            # hosts); may arrive int16 (see _place) — widen on device
            pairs = pairs.astype(jnp.int32)
            srcs = pairs[..., :-1] if cbow else pairs[..., 0]
            tgts = pairs[..., -1]
            keys = jax.random.split(key, pairs.shape[0])
            params, losses = lax.scan(
                scan_body, params, (srcs, tgts, keys, lrs))
            return params, states, locals_, losses.mean()

        # the supported fused-update path: donation, out-shardings, and
        # step/generation counting live in the table layer
        self._fused = make_superstep((self.w_in, self.w_out), body,
                                     name="w2v_superstep")

    # -- data placement ----------------------------------------------------

    def _place(self, srcs: np.ndarray, tgts: np.ndarray):
        """Shard the pair stream over the data axis — ONE combined
        [S, B, ctx+1] placement per call (src ids + target packed along
        the trailing axis; the fused body unslices for free). Ids ship
        as int16 when the padded vocab fits — the pair stream is the
        whole H2D byte budget of training, so halving it halves the
        transfer cost on ANY host (and the tunneled chip's thin pipe
        doubly rewards it); the fused body widens back to int32."""
        if srcs.ndim == 2:      # skipgram: [S, B] -> [S, B, 1]
            srcs = srcs[..., None]
        pairs = np.concatenate([srcs, tgts[..., None]], axis=-1)
        if self._scratch < np.iinfo(np.int16).max:
            pairs = pairs.astype(np.int16)
        sh = NamedSharding(self.mesh, P(None, core.DATA_AXIS, None))
        if self._local_chunks is None:
            return jax.device_put(pairs, sh)
        # local_data: ``pairs`` is this process's [S, B_local, C] share;
        # slice it back out per device (replicas get the same chunk) and
        # assemble the global array — no process ships another's lanes
        c = self.config
        off = {}
        acc = 0
        for b0, b1 in self._local_chunks:
            off[b0] = acc
            acc += b1 - b0
        shards = [jax.device_put(
            pairs[:, off[b0]:off[b0] + (b1 - b0)], d)
            for d, b0, b1 in self._dev_slices]
        return jax.make_array_from_single_device_arrays(
            (c.steps_per_call, c.batch_size, pairs.shape[-1]), sh, shards)

    # -- training ----------------------------------------------------------

    def _batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        c = self.config
        if self._local_chunks is not None:
            return self._local_batches()
        if c.model == "skipgram":
            it = self.corpus.skipgram_batches(
                c.batch_size, window=c.window, seed=c.seed, epochs=c.epochs)
            # skip-gram trains (center → context): src = center
            return it
        return self.corpus.cbow_batches(
            c.batch_size, window=c.window, seed=c.seed, epochs=c.epochs,
            pad_id=self._scratch)

    def _local_batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """local_data: this process's [*, B_local] share of every batch
        from ITS corpus shard, rank-folded seed, cycling the shard
        forever (train() bounds the loop with the agreed call count)."""
        c = self.config
        rank = jax.process_index()
        bl = self._local_batch
        epoch = 0
        while True:
            seed = c.seed + 7919 * (rank + 1) + 104729 * epoch
            if c.model == "skipgram":
                it = self.corpus.skipgram_batches(
                    bl, window=c.window, seed=seed, epochs=1)
            else:
                it = self.corpus.cbow_batches(
                    bl, window=c.window, seed=seed, epochs=1,
                    pad_id=self._scratch)
            got = False
            for item in it:
                got = True
                yield item
            if not got:
                # an empty shard must fail LOUDLY: returning here would
                # leave this process with zero dispatches while the
                # others run the agreed collective schedule — deadlock
                raise ValueError(
                    f"local_data: this process's corpus shard yields no "
                    f"{self._local_batch}-pair batches; every process "
                    "must contribute data (or drop local_data)")
            epoch += 1

    def train(self, total_steps: Optional[int] = None) -> float:
        """Run the full training loop; returns the final mean loss."""
        c = self.config
        d = self.mesh.shape[core.DATA_AXIS]
        if c.batch_size % d:
            raise ValueError(f"batch_size {c.batch_size} not divisible by "
                             f"data-axis size {d}")
        # linear lr decay over the whole corpus (reference's alpha decay);
        # skip-gram emits ~2b pairs per center, b ~ U[1, window] -> E = w+1
        tokens = self.corpus.num_tokens
        if self._local_chunks is not None and jax.process_count() > 1:
            # local_data: the schedule must be identical on every
            # process — agree on the GLOBAL token count (int64-safe)
            from multiverso_tpu.parallel.multihost import allgather_i64
            tokens = int(allgather_i64([tokens]).sum())
        est_pairs = tokens * c.epochs * (c.window + 1) \
            if c.model == "skipgram" else tokens * c.epochs
        est_calls = max(int(est_pairs) //
                        (c.batch_size * c.steps_per_call), 1)
        if total_steps is not None:
            est_calls = max(total_steps // c.steps_per_call, 1)
        elif self._local_chunks is not None:
            # the cycling local generator never exhausts — the agreed
            # schedule is the stop condition
            total_steps = est_calls * c.steps_per_call

        # the plan a periodic store persists: the original schedule when
        # resumed, else this run's own estimate
        self._train_plan = self._sched_plan or est_calls
        srcs_buf, tgts_buf = [], []
        losses, call_no = [], 0
        t0 = time.perf_counter()
        # host pair generation overlaps device compute (the reference's
        # ParameterLoader/ASyncBuffer pipelining role, SURVEY.md §4.5)
        from multiverso_tpu.utils.async_buffer import prefetch_iterator
        for src, tgt in prefetch_iterator(self._batches(),
                                          depth=2 * c.steps_per_call):
            srcs_buf.append(src)
            tgts_buf.append(tgt)
            if len(srcs_buf) < c.steps_per_call:
                continue
            loss = self._dispatch(np.stack(srcs_buf), np.stack(tgts_buf),
                                  call_no, est_calls)
            losses.append(loss)
            srcs_buf, tgts_buf = [], []
            call_no += 1
            if telemetry.health.maybe_rollback(self) is not None:
                # divergence rollback: tables + the step cursor are
                # back at the last clean generation (LR decay and the
                # fold_in key sequence re-align through _step_no). The
                # pair stream itself cannot rewind — training resumes
                # on fresh batches from the restored parameters, which
                # for a stochastic stream is equivalent to a replay.
                # Checked BEFORE maybe_save so a diverged state is
                # never committed as a generation.
                continue
            if self.run_ckpt is not None:
                # run-level manager (preferred over the bespoke prefix
                # dump): atomically-committed generations, keep-K
                # retention, overlapped writes; collective — every
                # process reaches the same call_no in lockstep
                self.run_ckpt.maybe_save(
                    self._step_no // c.steps_per_call, self.run_state)
            elif c.checkpoint_interval > 0 and c.checkpoint_prefix \
                    and call_no % c.checkpoint_interval == 0:
                # legacy periodic mid-train dump (SURVEY §6.4's
                # flag-driven trigger); collective
                self.store(c.checkpoint_prefix)
            if total_steps is not None \
                    and call_no * c.steps_per_call >= total_steps:
                break
        if call_no == 0 and srcs_buf:
            # corpus smaller than one superstep: pad by cycling the
            # buffered batches to the static scan length (slight pair
            # over-weighting beats training nothing / a full recompile)
            log.warn("w2v corpus yields < %d batches; cycling %d to fill "
                     "one superstep", c.steps_per_call, len(srcs_buf))
            reps = [srcs_buf[i % len(srcs_buf)]
                    for i in range(c.steps_per_call)]
            rept = [tgts_buf[i % len(tgts_buf)]
                    for i in range(c.steps_per_call)]
            losses.append(self._dispatch(np.stack(reps), np.stack(rept),
                                         0, est_calls))
            call_no = 1
        # trailing partial buffer is otherwise dropped (like per-batch
        # remainders): a shorter scan length would force a full XLA
        # recompile for one leftover call's worth of pairs
        self.w_in.wait()
        dt = time.perf_counter() - t0
        # count the work actually dispatched: with total_steps (or a
        # short corpus) the full-corpus token count would overstate
        # throughput by corpus_batches/steps_run
        pairs_done = call_no * c.steps_per_call * c.batch_size
        est_ppt = (c.window + 1) if c.model == "skipgram" else 1.0
        words = pairs_done / est_ppt
        telemetry.counter("w2v.pairs").inc(pairs_done)
        telemetry.emit("w2v.words_per_sec", words / dt, "words/s")
        # ONE device->host transfer for the whole loss list: per-scalar
        # fetches cost ~100ms each over a tunneled TPU (trace-measured)
        self.loss_history = [float(l) for l in
                             np.asarray(jnp.stack(losses))] \
            if losses else []
        final = float(np.mean(self.loss_history[-10:])) \
            if losses else float("nan")
        log.info("w2v train done: %d calls, loss=%.4f, %.0f words/s",
                 call_no, final, words / dt)
        return final

    def _dispatch(self, srcs: np.ndarray, tgts: np.ndarray,
                  call_no: int, est_calls: int) -> jax.Array:
        c = self.config
        s = srcs.shape[0]
        if self._sched_plan:
            # checkpoint resume: continue the ORIGINAL run's decay and
            # key sequence (past the plan's end the LR floor holds)
            call_no += self._sched_offset
            est_calls = max(self._sched_plan, 1)
        frac = min(call_no / est_calls, 1.0)
        lr_hi = c.learning_rate * (1.0 - frac)
        lr_lo = c.learning_rate * (1.0 - min((call_no + 1) / est_calls, 1.0))
        floor = c.learning_rate * c.min_lr_frac
        lrs = np.maximum(np.linspace(lr_hi, lr_lo, s), floor) \
            .astype(np.float32)
        key = jax.random.fold_in(self._key, call_no)
        pd = self._place(srcs, tgts)
        t_step = time.perf_counter()
        with telemetry.span("w2v.superstep"):
            _, loss = self._fused((), pd, key,
                                  core.place(lrs, mesh=self.mesh))
        telemetry.step_timeline("w2v", call_no, pairs=s * c.batch_size,
                                dispatch_s=time.perf_counter() - t_step)
        telemetry.histogram(
            "app.step.seconds", telemetry.LATENCY_BUCKETS,
            app="w2v").observe(time.perf_counter() - t_step)
        telemetry.beat()    # flight recorder: one heartbeat per dispatch
        self._step_no += s
        return loss

    # -- embeddings out / eval --------------------------------------------

    def embeddings(self) -> np.ndarray:
        """The trained input embeddings [V, D] (the reference saves
        W_in). Under ``MVTPU_STALENESS`` this is a bounded-staleness
        cached read — mid-train eval (nearest/similarity/analogy) stops
        paying a blocking whole-table fetch per call."""
        if self._emb_view is not None:
            return self._emb_view.get()
        return self.w_in.get()

    def nearest(self, word_id: int, k: int = 10) -> np.ndarray:
        """Top-k neighbor ids by cosine similarity (excluding self)."""
        norm = _normalized_rows(self.embeddings())
        return _topk_excluding(norm, norm[word_id], (word_id,), k)

    def similarity(self, a: int, b: int) -> float:
        emb = self.embeddings()
        va, vb = emb[a], emb[b]
        return float(va @ vb / max(np.linalg.norm(va) * np.linalg.norm(vb),
                                   1e-12))

    def analogy(self, a: int, b: int, c: int, k: int = 1) -> np.ndarray:
        """``a : b :: c : ?`` — top-k ids by cosine to (b - a + c), the
        reference word2vec's compute-accuracy evaluation rule (query
        words excluded from the candidates)."""
        norm = _normalized_rows(self.embeddings())
        q = norm[b] - norm[a] + norm[c]
        q = q / max(np.linalg.norm(q), 1e-12)
        return _topk_excluding(norm, q, (a, b, c), k)

    def save_text(self, path: str) -> None:
        """The reference word2vec's text output format: a header line
        ``vocab_size dim`` then one ``word v1 .. vD`` line per word.
        Collective (the embedding fetch is); only process 0 writes."""
        emb = self.w_in.get()   # exact — the persisted artifact never
        # serves from the staleness-bounded view
        if core.rank() != 0:
            return
        words = self.corpus.words
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{len(words)} {emb.shape[1]}\n")
            for w, row in zip(words, emb):
                f.write(w + " " + " ".join(f"{x:.6g}" for x in row) + "\n")

    META_MAGIC = "mvtpu.w2v.meta.v1"

    def store(self, uri_prefix: str) -> None:
        """Checkpoint both tables + a meta manifest. The meta is
        written LAST and records each table's step, so load() can
        detect a torn set (crash between the three per-file-atomic
        writes) instead of silently training mismatched tables."""
        from multiverso_tpu.tables.base import savez_stream
        self.w_in.store(f"{uri_prefix}.in.npz")
        self.w_out.store(f"{uri_prefix}.out.npz")
        savez_stream(f"{uri_prefix}.meta.npz",
                     {"magic": self.META_MAGIC,
                      "step_no": self._step_no,
                      "steps_per_call": self.config.steps_per_call,
                      "w_in_step": self.w_in.default_option.step,
                      "w_out_step": self.w_out.default_option.step,
                      "sched_plan": self._sched_plan
                      or self._train_plan}, {})
        self._last_store = (uri_prefix, self._step_no)

    def load(self, uri_prefix: str) -> None:
        self.w_in.load(f"{uri_prefix}.in.npz")
        self.w_out.load(f"{uri_prefix}.out.npz")
        from multiverso_tpu.tables.base import loadz_stream
        try:
            manifest, _ = loadz_stream(f"{uri_prefix}.meta.npz",
                                       self.META_MAGIC)
        except FileNotFoundError:
            return          # pre-meta checkpoint: tables only
        # any OTHER failure (corrupt meta, wrong magic, transient read
        # error) must RAISE: silently skipping resume here would leave
        # this process with a different step counter than its peers —
        # lockstep collective training then diverges without an error
        for table, key in ((self.w_in, "w_in_step"),
                           (self.w_out, "w_out_step")):
            if key in manifest and \
                    table.default_option.step != int(manifest[key]):
                raise ValueError(
                    f"w2v checkpoint {uri_prefix!r} is torn: "
                    f"{key}={manifest[key]} in the meta but the loaded "
                    f"table is at step {table.default_option.step} — a "
                    "crash interrupted the three-file store; use an "
                    "older complete checkpoint")
        spc = int(manifest.get("steps_per_call",
                               self.config.steps_per_call))
        if spc != self.config.steps_per_call:
            raise ValueError(
                f"w2v checkpoint {uri_prefix!r} was written with "
                f"steps_per_call={spc}, this app uses "
                f"{self.config.steps_per_call}: the resume offset and "
                "fold_in key sequence are call-indexed, so resuming "
                "under a different call size would replay RNG — "
                "construct the app with the original steps_per_call")
        self._step_no = int(manifest["step_no"])
        # resume CONTINUES the stored run's schedule: the original
        # planned call count rides the meta, so the LR decay picks up
        # exactly where the stored run left off (training past the
        # plan's end stays at the floor LR), and the fold_in key
        # sequence advances instead of replaying. In-session repeated
        # train() calls keep their restart-the-schedule behavior —
        # only load() sets these (and only from a checkpoint whose run
        # actually had a plan).
        self._sched_plan = int(manifest.get("sched_plan", 0))
        if self._sched_plan:
            self._sched_offset = \
                self._step_no // self.config.steps_per_call

    # -- fault tolerance (ft.checkpoint contract) --------------------------

    def run_state(self) -> dict:
        """Train-state for the run manager: the step cursor and the
        ORIGINAL planned call count, so a resumed run continues the
        stored run's LR decay and ``fold_in`` key sequence instead of
        restarting them (same semantics as the meta-file resume)."""
        return {"step_no": self._step_no,
                "steps_per_call": self.config.steps_per_call,
                "sched_plan": self._sched_plan or self._train_plan}

    def restore_run_state(self, restored) -> None:
        spc = int(restored.get("steps_per_call",
                               self.config.steps_per_call))
        if spc != self.config.steps_per_call:
            raise ValueError(
                f"run checkpoint was written with steps_per_call={spc}, "
                f"this app uses {self.config.steps_per_call}: the "
                "resume offset and fold_in key sequence are "
                "call-indexed — construct the app with the original "
                "steps_per_call")
        self._step_no = int(restored.get("step_no", 0))
        self._sched_plan = int(restored.get("sched_plan", 0))
        if self._sched_plan:
            self._sched_offset = \
                self._step_no // self.config.steps_per_call


def main(argv=None) -> None:
    """CLI mirroring the reference's word2vec-style argv."""
    from multiverso_tpu.utils import configure
    configure.define_string("train_file", "", "corpus text file", overwrite=True)
    configure.define_int("size", 100, "embedding dimension", overwrite=True)
    configure.define_int("window", 5, "context window", overwrite=True)
    configure.define_int("negative", 5, "negative samples (0 -> HS)", overwrite=True)
    configure.define_bool("cbow", False, "CBOW instead of skip-gram", overwrite=True)
    configure.define_int("epoch", 1, "epochs", overwrite=True)
    configure.define_int("batch_size", 1024, "pairs per step", overwrite=True)
    configure.define_float("alpha", 0.025, "initial learning rate", overwrite=True)
    configure.define_float("sample", 1e-3, "subsampling threshold", overwrite=True)
    configure.define_int("min_count", 5, "vocab min count", overwrite=True)
    configure.define_string("output_file", "", "embedding checkpoint prefix", overwrite=True)
    configure.define_string("output_text", "", "text-format embedding dump (the reference's output format)", overwrite=True)
    configure.define_int("checkpoint_interval", 0,
                         "store -output_file every N superstep calls "
                         "(0 = only at end)", overwrite=True)
    from multiverso_tpu.ft.checkpoint import define_run_flags, wire_app
    define_run_flags()
    core.init(argv)
    train_file = configure.get_flag("train_file")
    if not train_file:
        raise SystemExit("-train_file is required")
    corpus = Corpus.from_file(train_file,
                              min_count=configure.get_flag("min_count"),
                              subsample=configure.get_flag("sample"))
    neg = configure.get_flag("negative")
    cfg = W2VConfig(
        embedding_dim=configure.get_flag("size"),
        window=configure.get_flag("window"),
        negative=max(neg, 1),
        objective="ns" if neg > 0 else "hs",
        model="cbow" if configure.get_flag("cbow") else "skipgram",
        batch_size=configure.get_flag("batch_size"),
        learning_rate=configure.get_flag("alpha"),
        epochs=configure.get_flag("epoch"),
        subsample=configure.get_flag("sample"),
        checkpoint_prefix=configure.get_flag("output_file"),
        checkpoint_interval=configure.get_flag("checkpoint_interval"),
    )
    app = WordEmbedding(corpus, cfg)
    # fault tolerance: run-level checkpoint/resume, cadence in superstep
    # calls (-ckpt_every / MVTPU_CKPT_EVERY; falls back to the legacy
    # -checkpoint_interval cadence, default 50 calls)
    mgr = wire_app(app, [app.w_in, app.w_out],
                   every_default=cfg.checkpoint_interval or 50)
    # flight recorder: env-gated stall watchdog + device capture (the
    # per-dispatch beat is in _dispatch)
    with telemetry.maybe_watchdog("w2v"), telemetry.profile_window("w2v"):
        app.train()
    if mgr is not None:
        mgr.close()     # drain pending background checkpoint writes
    telemetry.record_device_memory()
    out = configure.get_flag("output_file")
    # skip the end-of-train dump when the last periodic store already
    # wrote this exact state (a second full collective dump is pure
    # waste at scale)
    if out and app._last_store != (out, app._step_no):
        app.store(out)
    out_text = configure.get_flag("output_text")
    if out_text:
        app.save_text(out_text)
    core.barrier()


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
