"""Flagship applications, the TPU-native rebuilds of the reference's
`Applications/` tree (SURVEY.md §3.6):

- :mod:`multiverso_tpu.apps.logreg` — Applications/LogisticRegression
- :mod:`multiverso_tpu.apps.word_embedding` — Applications/WordEmbedding
- :mod:`multiverso_tpu.apps.lightlda` — LightLDA (companion repo)
"""
