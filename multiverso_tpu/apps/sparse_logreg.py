"""Sparse-feature logistic regression on KVTable — the reference's
`Applications/LogisticRegression` sparse path (SURVEY.md §3.6: "dense or
sparse features; weights in ArrayTable (dense) or KVTable (sparse)").

The dense app (:mod:`multiverso_tpu.apps.logreg`) densifies libsvm rows
into an ArrayTable-backed [input_dim, C] weight matrix. Here features
stay sparse end-to-end — weights live in a :class:`KVTable` keyed by the
64-bit hashed feature id, so the feature space is unbounded (hashing
trick); only the features a minibatch touches are ever fetched/updated.

TPU shape of the reference's worker loop (Get rows → local train → Add
deltas, SURVEY.md §4.2/§4.3):

- per minibatch, the UNIQUE feature keys are resolved host-side (the
  KVTable slot plan is host-side anyway) and their weight rows fetched
  in one ``kv.get`` — [U, C] with missing keys at ``default_value``,
- one jitted step computes logits via a gather-einsum over the
  fixed-width padded (feature-position, value) arrays, the softmax/CE
  gradient, and the per-key delta via duplicate-safe scatter-add (the
  client-side Aggregator role, fused on device),
- ``kv.add(uniq_keys, delta)`` folds the delta through the table's
  updater (sgd / adagrad / ftrl — state lives with the table, per key).

Static shapes: samples are padded to ``max_features`` features (extras
raise), unique-key counts are bucketed to powers of two, and padded
lanes point at a zero sentinel row.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu import client, core, telemetry
from multiverso_tpu.apps.logreg import _parse_libsvm
from multiverso_tpu.tables import KVTable
from multiverso_tpu.tables.matrix_table import _bucket
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log

BIAS_KEY = np.uint64(0xB1A5B1A5B1A5B1A5)


@dataclasses.dataclass
class SparseLRConfig:
    num_classes: int = 2
    max_features: int = 64        # per-sample nnz pad width (bias incl.)
    capacity: int = 1 << 20       # KVTable capacity (keys)
    slots_per_bucket: int = 16    # hash-bucket width (overflow headroom)
    minibatch_size: int = 4096
    learning_rate: float = 0.1
    regular_lambda: float = 0.0   # lazy L2 on touched rows
    updater: str = "sgd"          # "sgd" | "adagrad" | "ftrl"
    ftrl_l1: float = 0.0          # updater="ftrl": L1 / L2 / beta — the
    ftrl_l2: float = 0.0          # AddOption lam/rho/momentum fields
    ftrl_beta: float = 1.0        # (see updaters docstring mapping)
    epochs: int = 1
    use_bias: bool = True
    seed: int = 0


def read_libsvm_sparse(path: str) -> Tuple[List[List[Tuple[int, float]]],
                                           np.ndarray]:
    """Parse libsvm rows WITHOUT densifying: ([(idx, val), ...] per
    sample, labels). Indices are used as hash keys directly — no base
    detection needed (0- vs 1-based just shifts key identity)."""
    labels, rows = _parse_libsvm(path)
    y = np.asarray(labels)
    if set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y > 0).astype(np.int32)
    return rows, y.astype(np.int32)


def synthetic_sparse(n: int, dim: int, num_classes: int, nnz: int = 20,
                     seed: int = 0) -> Tuple[List[List[Tuple[int, float]]],
                                             np.ndarray]:
    """Sparse classification data with a planted linear model over a
    ``dim``-sized feature space (exercises >=1e5 hashed dims cheaply)."""
    rng = np.random.default_rng(seed)
    w = rng.normal(0, 1.0, (dim, num_classes))
    rows, ys = [], []
    for _ in range(n):
        idx = rng.choice(dim, size=nnz, replace=False)
        val = rng.normal(0, 1.0, nnz)
        logits = val @ w[idx]
        ys.append(int(np.argmax(logits)))
        rows.append(list(zip(idx.tolist(), val.tolist())))
    return rows, np.asarray(ys, np.int32)


class SparseLogisticRegression:
    """The app: KVTable-backed linear model over hashed sparse features."""

    def __init__(self, config: SparseLRConfig, *, mesh=None,
                 name: str = "sparse_logreg") -> None:
        self.config = config
        self.mesh = mesh if mesh is not None else core.mesh()
        c = config
        if c.num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        opt = AddOption.for_ftrl(c.learning_rate, c.ftrl_l1, c.ftrl_l2,
                                 c.ftrl_beta) if c.updater == "ftrl" \
            else AddOption(learning_rate=c.learning_rate)
        self.table = KVTable(
            c.capacity, value_dim=c.num_classes, dtype="float32",
            slots_per_bucket=c.slots_per_bucket,
            updater=c.updater, mesh=self.mesh, name=name,
            default_option=opt)
        # MVTPU_COALESCE=K: the per-minibatch kv.add coalesces — K
        # minibatch gradients pre-sum by key host-side and flush as ONE
        # fused probe+updater dispatch (the reference's client-side
        # Aggregator). Gets then serve weights up to K minibatches
        # stale, the reference worker's own bounded-staleness semantics.
        self._coalescer = client.maybe_coalescing(self.table)
        self._step_jits: Dict[Tuple[int, int], object] = {}
        # fault tolerance (ft.checkpoint.wire_app): epoch-cursor
        # resume; the restored offset is consumed by the FIRST train()
        # after a resume (in-session train() calls keep restarting)
        self.run_ckpt = None
        self._epoch_done = 0
        self._resume_epochs = 0

    # -- batch packing -----------------------------------------------------

    def _pack(self, rows: Sequence[Sequence[Tuple[int, float]]]):
        """Fixed-shape (keys [B,F] uint64, vals [B,F] f32) + the unique
        key set; padded lanes carry key 0 with value 0 (they map to the
        sentinel row, so the key identity is irrelevant)."""
        c = self.config
        b = len(rows)
        f = c.max_features
        keys = np.zeros((b, f), np.uint64)
        vals = np.zeros((b, f), np.float32)
        for i, row in enumerate(rows):
            feats = list(row)
            if c.use_bias:
                feats.append((None, 1.0))
            if len(feats) > f:
                raise ValueError(
                    f"sample {i} has {len(feats)} features (incl. bias) "
                    f"> max_features={f}")
            for j, (idx, val) in enumerate(feats):
                keys[i, j] = BIAS_KEY if idx is None \
                    else np.uint64(idx) + np.uint64(1)  # avoid key 0 pad
                vals[i, j] = val
        uniq = np.unique(keys[vals != 0.0])
        return keys, vals, uniq

    def _positions(self, keys: np.ndarray, vals: np.ndarray,
                   uniq: np.ndarray, upad: int) -> np.ndarray:
        """Map each (sample, feature) lane to its row in the fetched
        unique-weight block; zero-value pad lanes -> sentinel row upad."""
        if len(uniq) == 0:      # all-zero minibatch: every lane is padding
            return np.full(keys.shape, upad, np.int32)
        pos = np.searchsorted(uniq, keys.ravel()).astype(np.int32)
        pos = np.minimum(pos, len(uniq) - 1)
        hit = uniq[pos] == keys.ravel()
        pos = np.where(hit & (vals.ravel() != 0.0), pos, upad)
        return pos.reshape(keys.shape).astype(np.int32)

    # -- the jitted step ---------------------------------------------------

    def _step_fn(self, b: int, upad: int):
        fn = self._step_jits.get((b, upad))
        if fn is None:
            c = self.config

            @jax.jit
            def step(w, pos, vals, y):
                # w [upad+1, C] (sentinel row zero), pos [B, F], vals
                # [B, F], y [B] -> (loss, dw [upad+1, C])
                def loss_fn(w):
                    rows = jnp.take(w, pos, axis=0)        # [B, F, C]
                    logits = jnp.einsum("bf,bfc->bc", vals, rows)
                    logp = jax.nn.log_softmax(logits)
                    nll = -jnp.mean(
                        jnp.take_along_axis(logp, y[:, None], axis=1))
                    reg = 0.5 * c.regular_lambda * jnp.sum(w[:-1] ** 2)
                    return nll + reg

                loss, dw = jax.value_and_grad(loss_fn)(w)
                return loss, dw

            fn = self._step_jits[(b, upad)] = step
        return fn

    def train_batch(self, rows, y: np.ndarray) -> float:
        """One Get -> fused grad -> Add round (the reference's per-block
        worker loop)."""
        keys, vals, uniq = self._pack(rows)
        upad = _bucket(len(uniq))
        uniq_pad = np.zeros(upad, np.uint64)
        uniq_pad[: len(uniq)] = uniq
        uniq_pad[len(uniq):] = BIAS_KEY ^ np.uint64(1)  # unused real key
        w, _found = self.table.get(uniq_pad)             # [upad, C]
        w_ext = np.concatenate(
            [w, np.zeros((1, self.config.num_classes), np.float32)])
        pos = self._positions(keys, vals, uniq, upad)
        step = self._step_fn(len(rows), upad)
        put = lambda a: core.place(np.asarray(a), mesh=self.mesh)
        loss, dw = step(put(w_ext.astype(np.float32)), put(pos),
                        put(vals), put(y.astype(np.int32)))
        dw = np.asarray(dw)[:len(uniq)]                  # drop pad+sentinel
        if len(uniq):           # all-zero minibatch has nothing to update
            if self._coalescer is not None:
                self._coalescer.add_kv(uniq, dw)
            else:
                self.table.add(uniq, dw)
        return float(loss)

    def train(self, rows, y: np.ndarray) -> float:
        c = self.config
        n = len(rows)
        loss = float("nan")
        t0 = time.perf_counter()
        step_no = 0
        # resume (applied ONCE): table state restored exactly at an
        # epoch boundary and each epoch's permutation seed derives from
        # its index, so the remaining epochs replay identically
        e = min(self._resume_epochs, c.epochs)
        self._resume_epochs = 0
        while e < c.epochs:
            # divergence rollback (MVTPU_HEALTH_ACTION=rollback):
            # restore_run_state just moved the cursor — replay from the
            # last clean generation (epoch RNG derives from the index,
            # so the replay is deterministic)
            if telemetry.health.maybe_rollback(self) is not None:
                e = min(self._resume_epochs, c.epochs)
                self._resume_epochs = 0
                continue
            order = np.random.default_rng(c.seed + e).permutation(n)
            losses = []
            for s in range(0, n, c.minibatch_size):
                idx = order[s:s + c.minibatch_size]
                t_step = time.perf_counter()
                with telemetry.span("sparse_logreg.step"):
                    losses.append(self.train_batch(
                        [rows[i] for i in idx], y[idx]))
                telemetry.step_timeline(
                    "sparse_logreg", step_no, samples=len(idx),
                    dispatch_s=time.perf_counter() - t_step)
                telemetry.histogram(
                    "app.step.seconds", telemetry.LATENCY_BUCKETS,
                    app="sparse_logreg").observe(
                    time.perf_counter() - t_step)
                telemetry.beat()
                step_no += 1
            loss = float(np.mean(losses))
            log.info("sparse_logreg epoch %d: loss=%.4f", e, loss)
            self._epoch_done = e + 1
            if self.run_ckpt is not None:
                # export_checkpoint_async flushes the coalescer, so the
                # checkpoint observes every buffered delta
                self.run_ckpt.maybe_save(self._epoch_done, self.run_state)
            e += 1
        if self._coalescer is not None:
            # the tail partial group must land before eval/checkpoint
            self._coalescer.flush()
        dt = time.perf_counter() - t0
        telemetry.counter("sparse_logreg.samples").inc(n * c.epochs)
        telemetry.emit("sparse_logreg.samples_per_sec",
                       n * c.epochs / dt, "samples/s")
        return loss

    # -- fault tolerance (ft.checkpoint contract) --------------------------

    def run_state(self) -> dict:
        """Epoch cursor: the KVTable (weights + updater state + key
        layout) rides the manager's table export; minibatch RNG derives
        from the epoch index."""
        return {"epoch_done": self._epoch_done}

    def restore_run_state(self, restored) -> None:
        self._epoch_done = int(restored.get("epoch_done", 0))
        self._resume_epochs = self._epoch_done

    # -- inference ---------------------------------------------------------

    def predict(self, rows) -> np.ndarray:
        if self._coalescer is not None:
            self._coalescer.flush()     # eval reads are exact
        keys, vals, uniq = self._pack(rows)
        upad = _bucket(len(uniq))
        uniq_pad = np.zeros(upad, np.uint64)
        uniq_pad[: len(uniq)] = uniq
        uniq_pad[len(uniq):] = BIAS_KEY ^ np.uint64(1)
        w, _ = self.table.get(uniq_pad)
        w_ext = np.concatenate(
            [w, np.zeros((1, self.config.num_classes), np.float32)])
        pos = self._positions(keys, vals, uniq, upad)
        logits = np.einsum("bf,bfc->bc", vals, w_ext[pos])
        return np.argmax(logits, axis=1).astype(np.int32)

    def accuracy(self, rows, y: np.ndarray) -> float:
        return float(np.mean(self.predict(rows) == y))

    # -- checkpoint --------------------------------------------------------

    def store(self, uri: str) -> None:
        self.table.store(uri)

    def load(self, uri: str) -> None:
        self.table.load(uri)


def main(argv=None) -> None:
    """CLI mirroring the reference LR app's sparse configuration."""
    from multiverso_tpu.utils import configure
    configure.define_string("train_file", "", "libsvm training data",
                            overwrite=True)
    configure.define_string("test_file", "", "libsvm eval data",
                            overwrite=True)
    configure.define_int("num_classes", 2, "classes", overwrite=True)
    configure.define_int("max_features", 64, "per-sample nnz pad",
                         overwrite=True)
    configure.define_int("capacity", 1 << 20, "KVTable capacity",
                         overwrite=True)
    configure.define_int("minibatch_size", 4096, "samples per step",
                         overwrite=True)
    configure.define_float("learning_rate", 0.1, "lr", overwrite=True)
    configure.define_float("regular_lambda", 0.0, "L2", overwrite=True)
    configure.define_int("epoch", 1, "epochs", overwrite=True)
    configure.define_string("output_file", "", "checkpoint uri",
                            overwrite=True)
    from multiverso_tpu.ft.checkpoint import define_run_flags, wire_app
    define_run_flags()
    core.init(argv)
    path = configure.get_flag("train_file")
    if not path:
        raise SystemExit("-train_file is required")
    rows, y = read_libsvm_sparse(path)
    cfg = SparseLRConfig(
        num_classes=configure.get_flag("num_classes"),
        max_features=configure.get_flag("max_features"),
        capacity=configure.get_flag("capacity"),
        minibatch_size=configure.get_flag("minibatch_size"),
        learning_rate=configure.get_flag("learning_rate"),
        regular_lambda=configure.get_flag("regular_lambda"),
        epochs=configure.get_flag("epoch"))
    app = SparseLogisticRegression(cfg)
    # fault tolerance: run-level checkpoint/resume, cadence in epochs
    mgr = wire_app(app, [app.table], every_default=1)
    # flight recorder: env-gated stall watchdog + device capture (the
    # per-step beat is in train)
    with telemetry.maybe_watchdog("sparse_logreg"), \
            telemetry.profile_window("sparse_logreg"):
        app.train(rows, y)
    if mgr is not None:
        mgr.close()     # drain pending background checkpoint writes
    telemetry.record_device_memory()
    log.info("train accuracy: %.4f", app.accuracy(rows, y))
    test = configure.get_flag("test_file")
    if test:
        trows, ty = read_libsvm_sparse(test)
        log.info("test accuracy: %.4f", app.accuracy(trows, ty))
    out = configure.get_flag("output_file")
    if out:
        app.store(out)
    core.barrier()


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
