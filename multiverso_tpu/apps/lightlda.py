"""Distributed LDA — TPU-native rebuild of the reference's LightLDA
companion app (SURVEY.md §3.6: `lightlda` main, `Trainer`,
`LightDocSampler` (MH + alias), `AliasTable`, `DataBlock`, `Meta`,
`Eval`): web-scale topic modeling over a word-topic count matrix
(SparseMatrixTable) + topic-summary row (ArrayTable), doc blocks streamed,
local deltas aggregated then sparse-added.

TPU-first redesign (deliberate — NOT a port of the sampler):

LightLDA's O(1)-per-token Metropolis-Hastings-with-alias-tables sampler
exists because O(K) per token is unaffordable on a scalar CPU. On TPU the
economics invert: an O(K) **vectorized collapsed-Gibbs** step — gather the
token's doc-topic and word-topic count rows, form the K posterior weights
on the VPU in linear space, sample by inverse-CDF (cumsum + one uniform
per token) — costs a few microseconds per thousand tokens, is *exact*
(no proposal bias, no MH rejections), and converges in fewer sweeps than
MH. The alias tables, proposal splitting, and
acceptance ratios are CPU machinery with no TPU reason to exist; what is
preserved is the *model contract*: same collapsed posterior
p(z=k | rest) ∝ (N_dk + α)(N_wk + β)/(N_k + Vβ), same count-matrix state
in the same tables, same streamed-block training shape.

Batch-parallel sampling uses batch-stale counts — exactly the AD-LDA
approximation the reference already makes across workers (its workers
sample against a stale model fetched per slice); here the staleness
window is one minibatch instead of one model-slice fetch.

Four sampler configurations, a measured performance ladder (one v5e
chip, benchmarks/README.md has the engineering log; every rung is
invariant- and convergence-tested):

1. ``sampler="gibbs"`` — exact vectorized collapsed Gibbs in plain XLA
   (4.7M doc-tokens/s). Supports model-axis sharding of the tables.
2. ``sampler="mh"`` — the reference's O(1) alias/z-array MH,
   vectorized. Measured SLOWER than dense Gibbs on TPU (scalar gathers
   lose to row gathers); kept as the algorithm-parity mode.
3. ``sampler="tiled"`` — the pallas kernel (ops.gibbs_sample_tiled):
   posterior + two-level inverse-CDF draw fused in VMEM over
   tile-aligned counts (7.5M). ``stale_words=True`` adds the
   reference's own slice-level staleness — word rows gathered from a
   bf16 per-sweep mirror, int16 doc counts, int32 master rebuilt from
   z each sweep (12.6M).
4. ``doc_blocked=True`` — the production mode (19.6M, ~10x the CPU
   baseline): whole-document kernel blocks own exclusive slices of a
   blocked doc-count array, so the doc side (A-row gather + doc-count
   scatters) happens in VMEM via MXU one-hot matmuls, never touching
   XLA gather/scatter. Data-parallel across chips via shard_map
   (per-chip blocks + psum'd summary deltas).

Every sampler runs on dp x mp meshes. For the tiled family the word
table (and the stale modes' bf16 mirror) stays row-sharded over the
model axis — the reference's Meta vocab-slicing role: per-step word-row
gathers are partial-gather + psum over the model axis (exact — each row
lives in one shard) and the per-sweep master rebuild scatters each
chip's data shard into its vocab slice, psum'd over the data axis, so
no chip ever materialises the full [V, K].

Counts live in:
- ``SparseMatrixTable [V, K] int32`` — word-topic counts (row-sharded
  over the mesh model axis like the reference's server shards; the
  tiled samplers store it tile-aligned),
- ``ArrayTable [K] int32`` — topic summary,
- a worker-local doc-topic array (dense ``[D, K]``, or int16 blocked
  ``[NB, MAXD, C, 128]`` in doc_blocked mode — the reference keeps
  doc-topic counts worker-local too),
- ``z [T] int32`` — per-token assignments, device-resident.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import client, core, telemetry
from multiverso_tpu.data.corpus import backend as data_backend
from multiverso_tpu.tables import (ArrayTable, SparseMatrixTable,
                                   make_superstep)
from multiverso_tpu.utils import log


@dataclasses.dataclass
class LDAConfig:
    """The reference app's flag set (lightlda argv)."""
    num_topics: int = 100
    alpha: Optional[float] = None   # doc-topic prior; default 50/K
    beta: float = 0.01              # word-topic prior
    batch_tokens: int = 4096        # tokens per scan step
    steps_per_call: int = 16        # scan length
    num_iterations: int = 10        # full Gibbs sweeps
    eval_every: int = 1             # likelihood eval cadence (sweeps)
    checkpoint_prefix: str = ""     # periodic mid-train checkpoints
    checkpoint_interval: int = 0    # store every N sweeps (0 = off;
    # SURVEY §6.4's flag-driven periodic dump trigger)
    sampler: str = "gibbs"          # "gibbs" (exact O(K)) | "mh" (O(1))
    #                               | "tiled" (pallas kernel, K%128==0)
    stale_words: bool = False       # tiled only: word counts gathered
    # from a bf16 mirror refreshed per sweep (the reference's own model:
    # word-topic rows fetched per slice, updates pushed at block end);
    # deletes the per-step word-count scatters, int32 master rebuilt
    # from z each sweep. Doc counts go int16 (doc len < 32k enforced).
    doc_blocked: bool = False       # tiled only (implies stale_words):
    # doc-sorted stream packed into whole-doc kernel blocks that own an
    # exclusive slice of the blocked doc-topic counts — the doc side
    # (A-row gather + doc-count scatters) moves INTO the pallas kernel
    # (VMEM matmuls), the fastest sampler (see benchmarks/README.md)
    block_tokens: int = 512         # doc_blocked: tokens per kernel block
    block_docs: int = 16            # doc_blocked: max docs per block
    stream_blocks: bool = False     # doc_blocked only: OUT-OF-CORE mode —
    # the packed token stream, z assignments, and doc counts stay
    # HOST-resident (the reference streams doc blocks from disk; SURVEY
    # §3.6 DataBlock role). Each superstep call stages one [S, B] slice
    # of (words, doc-rows, z) to device through a double-buffered
    # prefetch (utils.async_buffer), the blocked doc counts are REBUILT
    # on device from z (they are a pure function of it — cheaper than
    # round-tripping 64B/token of counts), and z comes back per call.
    # The word master updates incrementally from (z_in, z_out) instead
    # of a sweep-end full-stream rebuild (integer-identical). Device HBM
    # use is INDEPENDENT of corpus size: word table + mirror + summary
    # + two in-flight call buffers.
    local_corpus: bool = False      # stream_blocks only: PER-PROCESS
    # corpus shards — each process passes ONLY its own (token_words,
    # token_docs) slice (global doc ids, disjoint doc sets) and packs
    # its docs into exactly the block slots its devices own; host RAM
    # per process scales with the LOCAL shard, the reference's
    # workers-each-read-their-own-DataBlocks model. Geometry (calls per
    # sweep, global doc/token counts) is agreed collectively at init.
    # z init hashes (seed, GLOBAL block slot, position), so a slot's
    # draw doesn't depend on which process owns it — but a doc's slot
    # comes from greedy packing of the LOCAL shard, so changing the
    # doc-to-process split (or process count) still changes
    # trajectories; only a fixed layout is deterministic.
    mh_steps: int = 2               # MH: rounds of (word + doc) proposal
    precision: str = "float32"      # posterior/CDF math dtype; bfloat16
    # is measured equal-speed at large batches (the op mix is not
    # bandwidth-bound there) and drops topics w/ conditional mass below
    # ~0.2% under bf16 CDF resolution — float32 is the safe default
    seed: int = 0

    def resolved_alpha(self) -> float:
        return self.alpha if self.alpha is not None \
            else 50.0 / self.num_topics


def load_docs(path: str) -> Tuple[np.ndarray, np.ndarray, int]:
    """Read 'word:count' bag-of-words docs into a flat token stream.

    Returns (token_words [T], token_docs [T], vocab_size). The reference's
    DataBlock/Document layout flattened: counts expanded to one entry per
    token occurrence (Gibbs assigns a topic per occurrence).
    """
    offsets, word_ids, word_counts = data_backend().lda_read_docs(path)
    doc_of_entry = np.repeat(
        np.arange(len(offsets) - 1, dtype=np.int32),
        np.diff(offsets).astype(np.int64))
    token_words = np.repeat(word_ids.astype(np.int32), word_counts)
    token_docs = np.repeat(doc_of_entry, word_counts)
    vocab = int(word_ids.max()) + 1 if len(word_ids) else 1
    return token_words, token_docs, vocab


def _hash_z(seed: int, gblocks: np.ndarray, tb: int, K: int) -> np.ndarray:
    """Process-independent z init for local_corpus mode: splitmix64 of
    (seed, global block, position) mod K — any process computes the same
    draw for a given slot without materialising the global stream."""
    x = (gblocks.astype(np.uint64)[:, None] * np.uint64(tb)
         + np.arange(tb, dtype=np.uint64)[None, :]
         + (np.uint64(seed & 0xFFFFFFFF) << np.uint64(32)))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(K)).astype(np.int32)


def _predictive_ll(A, W, S, m, alpha, beta, K, vbeta):
    """Per-token predictive log-likelihood under point estimates:
    log sum_k theta_dk * phi_wk (the reference's `Eval` math), shared by
    every sampler's eval path. A/W are the gathered 2-D f32 count rows,
    S the [K] summary, m the f32 token mask."""
    theta = (A + alpha) / (A.sum(1, keepdims=True) + K * alpha)
    phi = (W + beta) / (S + vbeta)
    ll = jnp.log(jnp.maximum((theta * phi).sum(1), 1e-30))
    return (ll * m).sum()


class LightLDA:
    """The app: count tables + the fused Gibbs-sweep superstep."""

    def __init__(self, token_words: np.ndarray, token_docs: np.ndarray,
                 vocab_size: int, config: LDAConfig, *, mesh=None,
                 name: str = "lightlda") -> None:
        self.config = config
        self.mesh = mesh if mesh is not None else core.mesh()
        c = config
        self.V = vocab_size
        self.K = c.num_topics
        self.num_docs = int(token_docs.max()) + 1 if len(token_docs) else 1
        self.num_tokens = len(token_words)
        if c.sampler == "mh" and len(token_docs) \
                and np.any(np.diff(token_docs) < 0):
            # doc_start offsets (MH doc proposal) assume a doc-contiguous
            # stream; an interleaved stream would silently sample the
            # wrong doc's topics (gibbs is order-agnostic)
            raise ValueError("token_docs must be doc-contiguous "
                             "(non-decreasing doc ids) for sampler='mh'")
        if c.precision not in ("float32", "bfloat16"):
            raise ValueError(f"precision must be 'float32' or 'bfloat16', "
                             f"got {c.precision!r}")
        self.alpha = c.resolved_alpha()
        self.beta = c.beta
        # fault tolerance (ft.checkpoint.wire_app): run manager +
        # sweep cursor. _sweep_done counts completed sweeps (what a
        # checkpoint records); _resume_sweeps is the restored offset,
        # consumed by the FIRST train() after a resume — repeated
        # in-session train(n) calls keep their "n more sweeps" meaning
        self.run_ckpt = None
        self._sweep_done = 0
        self._resume_sweeps = 0

        tiled = c.sampler == "tiled"
        if tiled and self.K % 128:
            raise ValueError(f"sampler='tiled' needs num_topics % 128 "
                             f"== 0, got {self.K}")
        if (c.stale_words or c.doc_blocked) and not tiled:
            raise ValueError(
                f"stale_words/doc_blocked are sampler='tiled' modes; "
                f"got sampler={c.sampler!r}")
        if c.stream_blocks and not c.doc_blocked:
            raise ValueError("stream_blocks requires doc_blocked=True")
        if c.local_corpus and not c.stream_blocks:
            raise ValueError("local_corpus requires stream_blocks=True")
        if c.local_corpus and jax.process_count() > 1:
            # per-process corpus shards: agree on the global doc-id
            # space and token count (loglik normalization, count
            # invariants) before any geometry is derived (int64-safe:
            # process_allgather truncates int64 to int32 without x64)
            from multiverso_tpu.parallel.multihost import allgather_i64
            g = allgather_i64([self.num_docs, self.num_tokens])
            self.num_docs = int(g[:, 0].max())
            self.num_tokens = int(g[:, 1].sum())
        # stream_blocks works multi-host: staging assembles each call's
        # operand from per-device slices (every process device_puts only
        # its addressable lanes) and z readback walks addressable shards,
        # so no process ever materialises another host's device data.
        # By default each process keeps the full HOST-side packed corpus
        # (deterministic packing keeps layouts agreed); with
        # local_corpus=True each process passes and packs ONLY its own
        # doc shard, so host RAM also scales 1/P — the reference's
        # workers-each-read-their-own-DataBlocks model.
        # tiled samplers support dp x mp meshes: the word-topic table and
        # its bf16 mirror stay row-sharded over the model axis (each chip
        # holds a [V/mp] vocab slice — the reference's Meta vocab-slicing
        # role); per-step word-row gathers are partial-gather + psum over
        # the model axis (exact: each row lives in exactly one shard) and
        # the per-sweep master rebuild scatters each chip's data shard
        # into its vocab slice, psum'd over the data axis.
        # the pallas kernel needs the Mosaic TPU backend; on a CPU mesh
        # (tests) it runs in interpreter mode
        self._interpret = tiled and \
            self.mesh.devices.flat[0].platform == "cpu"

        # tables (the reference's server-side state); tiled storage puts
        # one word's topic row in exactly one (8,128) int32 tile
        self.word_topic = SparseMatrixTable(
            self.V, self.K, "int32", updater="default", mesh=self.mesh,
            name=f"{name}_word_topic", tiled=tiled)
        self.summary = ArrayTable(self.K, "int32", updater="default",
                                  mesh=self.mesh, name=f"{name}_summary")
        self._scratch_word = self.word_topic.padded_shape[0] - 1
        # MVTPU_STALENESS: serve logging/eval reads of the word-topic
        # model (word_topics/top_words) from a bounded-staleness cached
        # view instead of a blocking whole-table fetch per call;
        # dump_model/store stay exact
        self._wt_view = client.maybe_cached_view(self.word_topic)

        # worker-local doc-topic counts (+1 scratch doc for padded lanes);
        # placed on the mesh, NOT the default device (platform may differ)
        self._scratch_doc = self.num_docs
        self._docblock = tiled and c.doc_blocked
        # doc_blocked construction IS the stale-words model (no per-step
        # word scatters; master rebuilt from z per sweep)
        self._stale = tiled and (c.stale_words or c.doc_blocked)
        ndk_dtype = np.int32
        if self._stale:
            max_len = int(np.bincount(token_docs).max()) \
                if len(token_docs) else 0
            if max_len >= 32767:
                raise ValueError(
                    f"stale_words stores doc counts int16; a document "
                    f"has {max_len} tokens (>= 32767)")
            ndk_dtype = np.int16
        if self._docblock:
            # blocked layout replaces the dense [D+1, K] doc counts and
            # the permuted-stream staging entirely
            self._setup_docblock(token_words, token_docs, ndk_dtype)
            if c.stream_blocks:
                self._build_docblock_stream_superstep()
                self._init_streamed_counts()
            else:
                self._build_docblock_superstep()
            self._key = core.prng_key(c.seed, mesh=self.mesh)
            self._calls_done = 0
            self.ll_history = []
            self._last_store = ()
            return

        ndk_shape = (self.num_docs + 1, self.K // 128, 128) if tiled \
            else (self.num_docs + 1, self.K)
        self._ndk = core.place(np.zeros(ndk_shape, ndk_dtype),
                               mesh=self.mesh)

        # token stream, padded to a whole number of superstep calls
        B, S = c.batch_tokens, c.steps_per_call
        d_axis = self.mesh.shape[core.DATA_AXIS]
        if B % d_axis:
            raise ValueError(f"batch_tokens {B} not divisible by "
                             f"data-axis size {d_axis}")
        call_tokens = B * S
        T_pad = -(-max(self.num_tokens, 1) // call_tokens) * call_tokens
        self._mask = np.zeros(T_pad, bool)
        self._mask[: self.num_tokens] = True
        tw = np.full(T_pad, self._scratch_word, np.int32)
        tw[: self.num_tokens] = token_words
        td = np.full(T_pad, self._scratch_doc, np.int32)
        td[: self.num_tokens] = token_docs
        # shuffle the stream: doc-contiguous order would put a whole doc
        # in one batch, zeroing its doc-topic row under the batch-stale
        # decrement and badly slowing mixing; a fixed permutation spreads
        # each doc/word across the sweep (padded lanes shuffle in too —
        # harmless, they are masked)
        perm = np.random.default_rng(c.seed ^ 0x5EED).permutation(T_pad)
        self._tw, self._td = tw[perm], td[perm]
        self._mask = self._mask[perm]
        self.calls_per_sweep = T_pad // call_tokens
        # pre-place the static token stream on device once (the stream
        # never changes; re-uploading it every sweep would put ~4 host
        # transfers of the whole corpus in the hot loop)
        spec = P(None, core.DATA_AXIS)
        self._calls = []
        for call in range(self.calls_per_sweep):
            lo = call * call_tokens
            sl = slice(lo, lo + call_tokens)
            if tiled:
                # z positions are contiguous per scan step: pass scalar
                # offsets and dynamic-slice z (a [B]-index gather/scatter
                # of z costs ~7-10ms/step, measured — a slice is free)
                offs = np.arange(lo, lo + call_tokens, B, dtype=np.int32)
                self._calls.append((
                    self._place(self._tw[sl].reshape(S, B), spec),
                    self._place(self._td[sl].reshape(S, B), spec),
                    self._place(offs, P()),
                    self._place(self._mask[sl].reshape(S, B)
                                .astype(np.int32), spec)))
            else:
                self._calls.append(tuple(
                    self._place(a[sl].reshape(S, B), spec) for a in
                    (self._tw, self._td,
                     np.arange(T_pad, dtype=np.int32),
                     self._mask.astype(np.int32))))

        if c.sampler == "mh":
            # doc structure for the MH doc-proposal (z-array trick): the
            # stream is doc-contiguous (validated above), so doc d's
            # tokens live at original positions [doc_start[d],
            # doc_start[d]+doc_len[d]); inv_perm maps an original
            # position to its shuffled position (= the z index space).
            # One scratch-doc entry covers padding. Gibbs never touches
            # these — don't spend the [T_pad] device memory there.
            doc_len = np.bincount(token_docs, minlength=self.num_docs) \
                if len(token_docs) else np.zeros(self.num_docs, np.int64)
            doc_len = np.append(doc_len, max(T_pad - self.num_tokens, 1))
            doc_start = np.concatenate([[0], np.cumsum(doc_len)])[:-1]
            self._doc_len = self._place(doc_len.astype(np.int32), P())
            self._doc_start = self._place(doc_start.astype(np.int32), P())
            self._inv_perm = self._place(np.argsort(perm).astype(np.int32),
                                         P())

        # random initial assignments + count build (one jitted scatter)
        rng = np.random.default_rng(c.seed)
        z0 = rng.integers(0, self.K, T_pad).astype(np.int32)
        self._z = self._place(z0, P())
        self._init_counts()
        if tiled:
            self._build_tiled_superstep()
        else:
            self._build_superstep()
        if c.sampler == "mh":
            self._build_mh_superstep()
        elif c.sampler not in ("gibbs", "tiled"):
            raise ValueError(f"sampler must be 'gibbs', 'mh' or 'tiled', "
                             f"got {c.sampler!r}")
        self._key = core.prng_key(c.seed, mesh=self.mesh)
        self._calls_done = 0
        self.ll_history: list = []
        self._last_store = ()

    # -- doc-blocked stream / state ---------------------------------------

    def _setup_docblock(self, token_words, token_docs, ndk_dtype) -> None:
        """Pack the doc-sorted stream into whole-doc kernel blocks and
        build the blocked doc-topic counts (see LDAConfig.doc_blocked)."""
        c = self.config
        TB, MAXD = c.block_tokens, c.block_docs
        B, S = c.batch_tokens, c.steps_per_call
        if TB % 8 or B % TB:
            raise ValueError(f"block_tokens {TB} must be a multiple of 8 "
                             f"dividing batch_tokens {B}")
        order = np.argsort(token_docs, kind="stable")
        tw, td = token_words[order], token_docs[order]
        doc_ids, doc_starts = np.unique(td, return_index=True) \
            if len(td) else (np.zeros(0, np.int64), np.zeros(0, np.int64))
        doc_ends = np.append(doc_starts[1:], len(td)) if len(td) \
            else doc_starts
        lens = doc_ends - doc_starts
        if len(lens) and lens.max() > TB:
            raise ValueError(f"a document has {lens.max()} tokens > "
                             f"block_tokens {TB}")
        # greedy whole-doc block assignment (sequential by nature; a
        # plain scalar loop over doc LENGTHS — the token-level copy
        # below is fully vectorized so web-scale corpora pack in seconds)
        n_real = len(doc_ids)
        blk = np.empty(n_real, np.int64)
        row = np.empty(n_real, np.int64)
        off = np.empty(n_real, np.int64)
        b = 0
        cur_r = cur_tok = 0
        for di, ln in enumerate(lens.tolist()):
            if cur_tok + ln > TB or cur_r >= MAXD:
                b += 1
                cur_r = cur_tok = 0
            blk[di], row[di], off[di] = b, cur_r, cur_tok
            cur_r += 1
            cur_tok += ln
        n_blocks = (b + 1) if n_real else 1
        nbs = B // TB                       # blocks per scan step
        per_call = S * nbs
        self._per_call = per_call
        self._tb, self._maxd = TB, MAXD
        local = c.stream_blocks and c.local_corpus
        if local:
            # per-process corpus shard: this process packs its docs into
            # ONLY the block slots its devices own (the reference's
            # workers-each-own-their-DataBlocks model); the other
            # processes fill the rest of the global block space
            self._own_offs = self._owned_call_offsets()
            self._own_per_call = cap = len(self._own_offs)
            n_calls = -(-n_blocks // cap)
            if jax.process_count() > 1:
                from multiverso_tpu.parallel.multihost import (
                    allgather_i64, validate_single_owner)
                mask = np.zeros(per_call, np.int32)
                mask[self._own_offs] = 1
                validate_single_owner(mask, "local_corpus")
                n_calls = int(allgather_i64([n_calls]).max())
        else:
            cap = per_call
            n_calls = -(-n_blocks // cap)
        nb_alloc = n_calls * cap            # blocks on THIS process
        nb_pad = n_calls * per_call         # GLOBAL padded block count
        self.calls_per_sweep = n_calls
        self._nb_pad = nb_pad

        tw_p = np.full((nb_alloc, TB), self._scratch_word, np.int32)
        drel_p = np.full((nb_alloc, TB), MAXD - 1, np.int32)
        mask_p = np.zeros((nb_alloc, TB), np.int32)
        # -1 = document with zero tokens (never packed into any block);
        # doc_topics()/store() must yield zero rows for those, not some
        # other document's counts
        self._blk_of_doc = np.full(self.num_docs, -1, np.int64)
        self._row_of_doc = np.full(self.num_docs, -1, np.int64)
        if n_real:
            # each doc's tokens land at (blk, off + position-within-doc)
            tok_within = np.arange(len(td), dtype=np.int64) \
                - np.repeat(doc_starts, lens)
            flat = np.repeat(blk * TB + off, lens) + tok_within
            tw_p.reshape(-1)[flat] = tw
            drel_p.reshape(-1)[flat] = np.repeat(row, lens)
            mask_p.reshape(-1)[flat] = 1
            self._blk_of_doc[doc_ids] = blk
            self._row_of_doc[doc_ids] = row
        fill = mask_p.sum() / max(nb_alloc * TB, 1)
        self.packing_fill = float(fill)
        log.info("lda doc_blocked: %d blocks (%d/call, %.0f%% fill)",
                 nb_alloc, cap, 100 * fill)

        # init z — shared by both residency modes so the streamed and
        # in-memory runs are bit-identical for the same seed. local mode
        # instead hashes (seed, GLOBAL block, position) so the draw for
        # a given slot is independent of the process layout
        if local:
            z0 = _hash_z(c.seed, self._global_of_local(
                np.arange(nb_alloc, dtype=np.int64)), TB, self.K)
        else:
            rng = np.random.default_rng(c.seed)
            z0 = rng.integers(0, self.K, (nb_pad, TB)).astype(np.int32)

        if c.stream_blocks:
            # OUT-OF-CORE: stream/z/doc-counts stay host-resident (the
            # reference's disk-streamed DataBlocks); mask is derived on
            # device (tw == scratch_word <=> padded lane)
            self._tw_host = tw_p
            self._drel_host = drel_p
            self._z_host = z0
            self._z_synced = True    # init z is globally consistent
            self._ndk = None
            # inverse packing map for doc_topics(): (block, row) -> doc
            self._doc_of_row = np.full((nb_alloc, MAXD), -1, np.int64)
            valid = self._blk_of_doc >= 0
            self._doc_of_row[self._blk_of_doc[valid],
                             self._row_of_doc[valid]] = \
                np.nonzero(valid)[0]
            return

        # per-call staging: [S, B] lanes + per-step block offsets
        spec = P(None, core.DATA_AXIS)
        rows_flat = (np.arange(nb_pad)[:, None] * MAXD
                     + drel_p).astype(np.int32)
        self._calls = []
        self._loglik_rows = []   # eval-only gather rows (not a fused
        #                          operand: the sweep never needs them)
        for call in range(n_calls):
            lo = call * per_call
            sl = slice(lo, lo + per_call)
            shp = (S, B)
            self._calls.append((
                self._place(tw_p[sl].reshape(shp), spec),
                self._place(drel_p[sl].reshape(shp), spec),
                self._place(mask_p[sl].reshape(shp).astype(np.int32),
                            spec),
                self._place(np.arange(lo, lo + per_call, nbs,
                                      dtype=np.int32), P())))
            self._loglik_rows.append(
                self._place(rows_flat[sl].reshape(shp), spec))

        # full flat stream for the per-sweep word-count rebuild
        self._tw_flat = self._place(tw_p.reshape(-1), P())
        self._mask_flat = self._place(mask_p.reshape(-1), P())

        self._z = self._place(z0, P())
        drel_dev = self._place(drel_p, P())
        tiles = self.K // 128

        @jax.jit
        def build(z, tw_flat, m_flat, drel):
            zf = z.reshape(-1)
            nwk = jnp.zeros(self.word_topic.storage_shape, jnp.int32)
            nwk = nwk.at[tw_flat, zf // 128, zf % 128].add(m_flat)
            rows = (jnp.arange(nb_pad)[:, None] * MAXD + drel).reshape(-1)
            ndk = jnp.zeros((nb_pad * MAXD, tiles, 128), ndk_dtype)
            ndk = ndk.at[rows, zf // 128, zf % 128].add(
                m_flat.astype(ndk_dtype))
            nk = jnp.zeros(self.summary.padded_shape, jnp.int32)
            nk = nk.at[zf].add(m_flat)
            return nwk, ndk.reshape(nb_pad, MAXD, tiles, 128), nk

        nwk, ndk, nk = build(self._z, self._tw_flat, self._mask_flat,
                             drel_dev)
        self.word_topic.put_raw(nwk)
        self._ndk = ndk
        self.summary.put_raw(nk)

    def _build_word_gather(self):
        """``take(mirror, w)`` with the word table row-sharded over the
        model axis: each chip gathers the rows its vocab slice owns and
        the partials psum over ICI — exact (a row lives in exactly one
        shard), no chip ever materialises the full [V, K]. This is the
        TPU shape of the reference's Meta vocab-slicing: a worker fetches
        word rows per slice instead of holding the whole model.
        Works for any [*, C, 128] storage dtype (bf16 mirror, int32
        master for eval). mp == 1 degenerates to a plain gather."""
        mp = self.mesh.shape[core.MODEL_AXIS]
        if mp == 1:
            return lambda mirror, w: jnp.take(mirror, w, axis=0)
        from multiverso_tpu.utils.jax_compat import shard_map
        d, m = core.DATA_AXIS, core.MODEL_AXIS
        vshard = self.word_topic.storage_shape[0] // mp

        def local(ws_local, w):
            lo = lax.axis_index(m) * vshard
            idx = w - lo
            ok = (idx >= 0) & (idx < vshard)
            rows = jnp.take(ws_local, jnp.clip(idx, 0, vshard - 1),
                            axis=0)
            rows = jnp.where(ok[:, None, None], rows,
                             jnp.zeros((), rows.dtype))
            return lax.psum(rows, m)

        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(m, None, None), P(d)),
                         out_specs=P(d, None, None), check_vma=False)

    def _wrap_kernel_dp(self, fn):
        """Multi-chip dispatch for the pallas sampler: a Mosaic custom
        call cannot be auto-partitioned by XLA, so on any multi-device
        mesh each chip runs the kernel on its own token shard via
        ``shard_map`` (token shards over the data axis, operands
        replicated over the model axis) and the topic-summary delta is
        psum'd over ICI."""
        if self.mesh.devices.size == 1:
            return fn
        from multiverso_tpu.utils.jax_compat import shard_map
        d = core.DATA_AXIS
        Pb = P(d)
        Pb3 = P(d, None, None)

        def local(A3, W3, sinv, zi, msk, u1, u2):
            znew, nkd = fn(A3, W3, sinv, zi, msk, u1, u2)
            return znew, lax.psum(nkd, d)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(Pb3, Pb3, P(None, None), Pb, Pb, Pb, Pb),
            out_specs=(Pb, P(None, None)), check_vma=False)

    def _wrap_docblock_dp(self, fn):
        """Doc-blocked analog of :meth:`_wrap_kernel_dp`: kernel blocks
        shard over the data axis (each chip exclusively owns its blocks'
        doc counts — the block layout IS the DP partition)."""
        if self.mesh.devices.size == 1:
            return fn
        from multiverso_tpu.utils.jax_compat import shard_map
        d = core.DATA_AXIS
        Pb = P(d)

        def local(ndk_c, W3, sinv, zi, drel, msk, u1, u2):
            ndk_c, znew, nkd = fn(ndk_c, W3, sinv, zi, drel, msk, u1, u2)
            return ndk_c, znew, lax.psum(nkd, d)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(d, None, None, None), P(d, None, None),
                      P(None, None), Pb, Pb, Pb, Pb, Pb),
            out_specs=(P(d, None, None, None), Pb, P(None, None)),
            check_vma=False)

    def _build_vocab_slice_scatter(self):
        """shard_map'd count scatter for a model-sharded word table:
        each chip scatters its DATA shard's in-range tokens into its
        vocab slice, psum over the data axis. Shared by the per-sweep
        rebuild and the streamed master accumulator (one copy of the
        slice math). Returns f(z_flat, tw, msk) -> [V/mp, C, 128]."""
        from multiverso_tpu.utils.jax_compat import shard_map
        d, maxis = core.DATA_AXIS, core.MODEL_AXIS
        mp = self.mesh.shape[maxis]
        vshard = self.word_topic.storage_shape[0] // mp
        tail = self.word_topic.storage_shape[1:]

        def local(zf, tw, m):
            lo = lax.axis_index(maxis) * vshard
            idx = tw - lo
            ok = (idx >= 0) & (idx < vshard)
            add = jnp.where(ok, m, 0)
            nwk3 = jnp.zeros((vshard,) + tail, jnp.int32)
            nwk3 = nwk3.at[jnp.clip(idx, 0, vshard - 1),
                           zf // 128, zf % 128].add(add)
            return lax.psum(nwk3, d)

        return shard_map(local, mesh=self.mesh,
                         in_specs=(P(d), P(d), P(d)),
                         out_specs=P(maxis, None, None),
                         check_vma=False)

    def _build_stale_helpers(self) -> None:
        """Per-sweep word-count helpers shared by the stale modes: the
        bf16 gather mirror and the int32 master rebuild from z (z may be
        the flat stream or the blocked packing — flattened either way).
        Both keep the word table sharded over the model axis: the mirror
        is an elementwise cast (sharding-preserving) and the rebuild
        scatters each chip's DATA shard of the stream into its own vocab
        slice, psum'd over the data axis — no chip ever holds [V, K]."""
        mp = self.mesh.shape[core.MODEL_AXIS]

        @jax.jit
        def to_stale(nwk3):
            return nwk3.astype(jnp.bfloat16)

        if mp == 1:
            @jax.jit
            def rebuild(z, tw, m):
                zf = z.reshape(-1)
                nwk3 = jnp.zeros(self.word_topic.storage_shape, jnp.int32)
                return nwk3.at[tw, zf // 128, zf % 128].add(m)
        else:
            sharded = self._build_vocab_slice_scatter()

            @jax.jit
            def rebuild(z, tw, m):
                return sharded(z.reshape(-1), tw, m)

        self._to_stale = to_stale
        self._rebuild = rebuild
        self._gather_w = self._build_word_gather()

    def _eval_chunk(self, n: int) -> int:
        """Largest chunk of ~64k tokens that divides ``n`` and keeps the
        data-axis sharding valid: eval gathers materialise [chunk, K]
        f32 intermediates, which must stay bounded no matter how large a
        call is (an unchunked 8M-token call at K=1024 wants 34 GB)."""
        dp = self.mesh.shape[core.DATA_AXIS]
        c = n
        while c > (1 << 16) and c % 2 == 0 and (c // 2) % dp == 0:
            c //= 2
        return c

    def _chunked_ll(self, gather_w):
        """Chunked predictive-likelihood core shared by the in-memory
        and streamed evals (ONE copy of the chunk/gather math): scans
        [chunk, K] gathers so eval intermediates stay bounded no matter
        the call size (see :meth:`_eval_chunk`)."""
        alpha, beta = self.alpha, self.beta
        K = self.K
        vbeta = self.V * beta
        chunk = self._eval_chunk

        def run(nwk3, ndk_flat, Ssum, ws, rows, m):
            c = chunk(ws.shape[0])

            def step(tot, xs):
                wsc, rc, mc = xs
                A = jnp.take(ndk_flat, rc, axis=0).reshape(c, K) \
                    .astype(jnp.float32)
                W = gather_w(nwk3, wsc).reshape(c, K) \
                    .astype(jnp.float32)
                return tot + _predictive_ll(A, W, Ssum, mc, alpha,
                                            beta, K, vbeta), None

            tot, _ = lax.scan(
                step, jnp.zeros((), jnp.float32),
                (ws.reshape(-1, c), rows.reshape(-1, c),
                 m.reshape(-1, c)))
            return tot

        return run

    def _build_blocked_loglik(self) -> None:
        """Eval over tile-aligned doc counts, shared by tiled and
        doc-blocked layouts: ``rows`` index the flattened [*, C, 128]
        doc-count storage (plain doc ids for the dense layout, packed
        block rows for doc_blocked). Word rows come through the sharded
        gather, so eval never materialises the full [V, K] on one chip
        under model parallelism."""
        K = self.K
        tiles = K // 128
        # reuse the training gather when a stale mode built one — eval
        # and training must gather identically
        gather_w = getattr(self, "_gather_w", None) or \
            self._build_word_gather()
        run = self._chunked_ll(gather_w)

        @jax.jit
        def loglik(nwk3, ndk, nk, ws, rows, mask):
            return run(nwk3, ndk.reshape(-1, tiles, 128),
                       nk[:K].astype(jnp.float32), ws.reshape(-1),
                       rows.reshape(-1),
                       mask.reshape(-1).astype(jnp.float32))

        self._loglik = loglik

    def _build_docblock_kernel(self) -> None:
        """The IN-MEMORY doc-blocked superstep's kernel dispatch + scan
        body (the streamed mode builds its own scan body around the
        count-building kernel variant — same draw math, verified
        bit-identical by tests/test_lightlda.py)."""
        c = self.config
        alpha, beta = self.alpha, self.beta
        vbeta = self.V * beta
        K = self.K
        B = c.batch_tokens
        TB = self._tb
        nbs = B // TB
        dp = self.mesh.shape[core.DATA_AXIS]
        if nbs % dp:
            raise ValueError(
                f"doc_blocked: blocks per step {nbs} not divisible by "
                f"data-axis size {dp}")
        tiles = K // 128
        interpret = self._interpret
        from multiverso_tpu.ops import gibbs_sample_docblock
        sampler_call = self._wrap_docblock_dp(
            lambda ndk_c, W3, sinv, zi, drel, msk, u1, u2:
            gibbs_sample_docblock(ndk_c, W3, sinv, zi, drel, msk, u1,
                                  u2, alpha=alpha, beta=beta, tb=TB,
                                  interpret=interpret))
        self._build_stale_helpers()
        gather_w = self._gather_w

        def scan_body(wstale, carry, inp):
            nk, ndk, z = carry
            w, drel, msk, off, key = inp
            ndk_c = lax.dynamic_slice_in_dim(ndk, off, nbs)
            zi = lax.dynamic_slice_in_dim(z, off, nbs).reshape(B)
            W3 = gather_w(wstale, w.reshape(B))
            sinv = 1.0 / (nk[:K].astype(jnp.float32).reshape(tiles, 128)
                          + vbeta)
            k1, k2 = jax.random.split(key)
            u1 = jax.random.uniform(k1, (B,))
            u2 = jax.random.uniform(k2, (B,))
            ndk_c, znew, nkd = sampler_call(
                ndk_c, W3, sinv, zi, drel.reshape(B), msk.reshape(B),
                u1, u2)
            ndk = lax.dynamic_update_slice_in_dim(ndk, ndk_c, off, 0)
            z = lax.dynamic_update_slice_in_dim(
                z, znew.reshape(nbs, TB), off, 0)
            nk = nk.at[:K].add(nkd.reshape(-1))
            return (nk, ndk, z), ()

        self._db_scan_body = scan_body

    def _build_docblock_superstep(self) -> None:
        self._build_docblock_kernel()
        scan_body = self._db_scan_body

        def body(params, states, locals_, options, wstale, ws, drels,
                 msks, offs, key):
            (nk,) = params
            ndk, z = locals_
            keys = jax.random.split(key, ws.shape[0])
            (nk, ndk, z), _ = lax.scan(
                lambda cy, inp: scan_body(wstale, cy, inp),
                (nk, ndk, z), (ws, drels, msks, offs, keys))
            return (nk,), states, (ndk, z), None

        self._fused = make_superstep((self.summary,), body,
                                     name="lda_docblock")

        self._build_blocked_loglik()

    # -- out-of-core (streamed) doc-blocked mode ---------------------------

    def _build_master_accumulate(self):
        """(acc, z, w, mask) -> acc with ``counts(z)`` of the call's
        tokens added. ``acc`` is a donated carry: the single-device path
        scatters IN PLACE (no full-table temporary per call — measured
        ~0.2s/sweep of HBM traffic at V=50k, K=1024). Under model
        parallelism each chip scatters its data shard's in-range tokens
        into a vocab-slice delta, psum'd over the data axis (the
        per-sweep-rebuild pattern)."""
        mp = self.mesh.shape[core.MODEL_AXIS]
        if mp == 1:
            def accumulate(acc, z, tw, msk):
                return acc.at[tw, z // 128, z % 128].add(msk)
            return accumulate
        delta = self._build_vocab_slice_scatter()

        def accumulate(acc, z, tw, msk):
            return acc + delta(z, tw, msk)

        return accumulate

    def _wrap_docblock_build_dp(self, fn):
        """shard_map dispatch for the count-building kernel (no blocked
        count array: z is the only sampler state)."""
        if self.mesh.devices.size == 1:
            return fn
        from multiverso_tpu.utils.jax_compat import shard_map
        d = core.DATA_AXIS
        Pb = P(d)

        def local(W3, sinv, zi, drel, msk, u1, u2):
            znew, nkd = fn(W3, sinv, zi, drel, msk, u1, u2)
            return znew, lax.psum(nkd, d)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(d, None, None), P(None, None), Pb, Pb, Pb, Pb,
                      Pb),
            out_specs=(Pb, P(None, None)), check_vma=False)

    def _build_docblock_stream_superstep(self) -> None:
        c = self.config
        alpha, beta = self.alpha, self.beta
        vbeta = self.V * beta
        K = self.K
        S, B, TB = c.steps_per_call, c.batch_tokens, self._tb
        nbs, MAXD = B // TB, self._maxd
        dp = self.mesh.shape[core.DATA_AXIS]
        if nbs % dp:
            raise ValueError(
                f"doc_blocked: blocks per step {nbs} not divisible by "
                f"data-axis size {dp}")
        tiles = K // 128
        scratch = self._scratch_word
        interpret = self._interpret
        from multiverso_tpu.ops import gibbs_sample_docblock_build
        sampler_call = self._wrap_docblock_build_dp(
            lambda W3, sinv, zi, drel, msk, u1, u2:
            gibbs_sample_docblock_build(
                W3, sinv, zi, drel, msk, u1, u2, alpha=alpha, beta=beta,
                tb=TB, maxd=MAXD, interpret=interpret))
        self._build_stale_helpers()
        gather_w = self._gather_w
        accumulate = self._build_master_accumulate()
        self._stage_sharding = NamedSharding(
            self.mesh, P(None, None, core.DATA_AXIS))

        def unpack(stacked):
            tw, drel, z_in = stacked[0], stacked[1], stacked[2]
            msk = (tw != scratch).astype(jnp.int32)
            j = jnp.arange(S * B, dtype=jnp.int32)
            rows = (j // TB) * MAXD + drel.reshape(-1)
            return tw, drel, z_in, msk, rows

        def scan_body(wstale, carry, inp):
            nk, z = carry
            w, drel, msk, off, key = inp
            zi = lax.dynamic_slice_in_dim(z, off, nbs).reshape(B)
            W3 = gather_w(wstale, w.reshape(B))
            sinv = 1.0 / (nk[:K].astype(jnp.float32).reshape(tiles, 128)
                          + vbeta)
            k1, k2 = jax.random.split(key)
            u1 = jax.random.uniform(k1, (B,))
            u2 = jax.random.uniform(k2, (B,))
            znew, nkd = sampler_call(W3, sinv, zi, drel.reshape(B),
                                     msk.reshape(B), u1, u2)
            z = lax.dynamic_update_slice_in_dim(
                z, znew.reshape(nbs, TB), off, 0)
            nk = nk.at[:K].add(nkd.reshape(-1))
            return (nk, z), ()

        def body(params, states, locals_, options, wstale, stacked, key):
            (nk,) = params
            (acc,) = locals_   # fresh word-count accumulator: over one
            # sweep the per-call +/- master deltas TELESCOPE to
            # counts(z_end) (the subtracted counts(z_start) equal the
            # old master exactly), so one add-only scatter pass per call
            # into a fresh accumulator — swapped in at sweep end —
            # halves the scatter traffic of an incremental +/- update
            tw, drel, z_in, msk, _rows = unpack(stacked)
            z = z_in.reshape(S * nbs, TB)
            offs = jnp.arange(S, dtype=jnp.int32) * nbs
            keys = jax.random.split(key, S)
            (nk, z), _ = lax.scan(
                lambda cy, inp: scan_body(wstale, cy, inp),
                (nk, z), (tw, drel, msk, offs, keys))
            z_out = z.reshape(S, B)
            acc = accumulate(acc, z_out.reshape(-1), tw.reshape(-1),
                             msk.reshape(-1))
            # pin the aux z to the STAGING layout (lanes over the data
            # axis): each process then drains exactly the lanes it will
            # stage next sweep — without the constraint XLA may pick a
            # different aux sharding and a multi-host process would read
            # back lanes it does not own
            z_out = lax.with_sharding_constraint(
                z_out, NamedSharding(self.mesh, P(None, core.DATA_AXIS)))
            return (nk,), states, (acc,), z_out

        self._fused_stream = make_superstep(
            (self.summary,), body,
            local_shardings=(self.word_topic.sharding,),
            name="lda_docblock_stream")

        # streamed eval: stage (tw, drel, z), rebuild the call's doc
        # counts from z (XLA scatter — eval is periodic, not the hot
        # loop), gather word rows through the sharded gather
        def build_ndk(zf, rows, m):
            ndk = jnp.zeros((S * nbs * MAXD, tiles, 128), jnp.int16)
            return ndk.at[rows, zf // 128, zf % 128].add(
                m.astype(jnp.int16))

        run = self._chunked_ll(gather_w)

        @jax.jit
        def loglik_stream(nwk3, nk, stacked):
            tw, _drel, z_in, msk, rows = unpack(stacked)
            ndk = build_ndk(z_in.reshape(-1), rows, msk.reshape(-1))
            return run(nwk3, ndk, nk[:K].astype(jnp.float32),
                       tw.reshape(-1), rows,
                       msk.reshape(-1).astype(jnp.float32))

        self._loglik_stream = loglik_stream

        # per-call count init (the in-memory mode's build(), one staged
        # call at a time so HBM never sees the whole stream)
        @partial(jax.jit, donate_argnums=(0, 1))
        def init_call(master, nk, stacked):
            tw, _drel, z_in, msk, _rows = unpack(stacked)
            zf = z_in.reshape(-1)
            mf = msk.reshape(-1)
            master = accumulate(master, zf, tw.reshape(-1), mf)
            nk = nk.at[zf].add(mf)
            return master, nk

        self._init_call = init_call

    def _owned_call_offsets(self) -> np.ndarray:
        """Sorted per-call block offsets owned by THIS process's devices
        under the staging layout (lanes over the data axis). Model-axis
        replicas collapse to one entry."""
        c = self.config
        S, B = c.steps_per_call, c.batch_tokens
        sh = NamedSharding(self.mesh, P(None, core.DATA_AXIS))
        imap = sh.devices_indices_map((S, B))
        offs = set()
        for d in sh.addressable_devices:
            ssl, bsl = imap[d]
            s0 = 0 if ssl.start is None else ssl.start
            s1 = S if ssl.stop is None else ssl.stop
            b0 = 0 if bsl.start is None else bsl.start
            b1 = B if bsl.stop is None else bsl.stop
            # call-0 block ids ARE the per-call offsets — go through
            # _block_rows so ownership can never desync from staging
            offs.update(
                self._block_rows(0, s0, s1, b0, b1).reshape(-1).tolist())
        return np.sort(np.fromiter(offs, np.int64))

    def _global_of_local(self, l: np.ndarray) -> np.ndarray:
        """local_corpus: host-array block index -> global block id
        (identity otherwise — host arrays ARE globally indexed then)."""
        if not (self.config.stream_blocks and self.config.local_corpus):
            return l
        k, pos = np.divmod(l, self._own_per_call)
        return k * self._per_call + self._own_offs[pos]

    def _local_of_global(self, g: np.ndarray) -> np.ndarray:
        """local_corpus: global block id -> host-array index. Only ever
        called for blocks this process owns (staging/drain walk the
        process's own lanes)."""
        if not (self.config.stream_blocks and self.config.local_corpus):
            return g
        k, off = np.divmod(g, self._per_call)
        return k * self._own_per_call + np.searchsorted(self._own_offs,
                                                        off)

    def _block_rows(self, k: int, s0: int, s1: int, b0: int,
                    b1: int) -> np.ndarray:
        """Host block indices of the [s0:s1, b0:b1] lane rectangle of
        call ``k`` — THE single (step, B-lane) → packed-host-block
        mapping. Staging, z readback, and cross-host sync all go through
        it so they cannot disagree on which blocks a device owns."""
        TB = self._tb
        nbs = self.config.batch_tokens // TB
        return (k * self._per_call + np.arange(s0, s1)[:, None] * nbs
                + b0 // TB + np.arange((b1 - b0) // TB)[None, :])

    def _stream_stage(self, k: int):
        """Host side of staging call ``k``. Single-process: one stacked
        [3, S, B] int32 array (words, doc-rows, z) — a single H2D
        transfer per call. Multi-process: a list of (device, local
        chunk) covering ONLY this process's addressable lanes — the host
        never materialises (or copies) the other hosts' share of the
        call, so per-process host bandwidth scales with 1/P."""
        c = self.config
        S, B = c.steps_per_call, c.batch_tokens
        if jax.process_count() == 1:
            sl = slice(k * self._per_call, (k + 1) * self._per_call)
            return np.stack([self._tw_host[sl].reshape(S, B),
                             self._drel_host[sl].reshape(S, B),
                             self._z_host[sl].reshape(S, B)])
        imap = self._stage_sharding.devices_indices_map((3, S, B))
        parts = []
        for d in self._stage_sharding.addressable_devices:
            _csl, ssl, bsl = imap[d]
            s0 = 0 if ssl.start is None else ssl.start
            s1 = S if ssl.stop is None else ssl.stop
            b0 = 0 if bsl.start is None else bsl.start
            b1 = B if bsl.stop is None else bsl.stop
            bidx = self._local_of_global(
                self._block_rows(k, s0, s1, b0, b1))
            shp = (s1 - s0, b1 - b0)
            parts.append((d, np.stack([
                self._tw_host[bidx].reshape(shp),
                self._drel_host[bidx].reshape(shp),
                self._z_host[bidx].reshape(shp)])))
        return parts

    def _place_stream(self, staged) -> jax.Array:
        """Place one staged call on the mesh. Single-process: one async
        device_put. Multi-process: assemble the global array from the
        per-device chunks ``_stream_stage`` built — each process
        transfers ONLY its addressable lanes (process-local staging; the
        cross-host layout is implied by the sharding, no host ever ships
        another host's shard)."""
        sh = self._stage_sharding
        if jax.process_count() == 1:
            return jax.device_put(staged, sh)
        c = self.config
        shape = (3, c.steps_per_call, c.batch_tokens)
        shards = [jax.device_put(arr, d) for d, arr in staged]
        return jax.make_array_from_single_device_arrays(shape, sh, shards)

    def _stream_calls(self):
        """Double-buffered H2D pipeline: host slices are stacked on a
        prefetch thread (utils.async_buffer) and device_put (async) from
        the consumer, so call k+1's transfer overlaps call k's sweep."""
        from multiverso_tpu.utils.async_buffer import prefetch_iterator

        def gen():
            for k in range(self.calls_per_sweep):
                yield k, self._stream_stage(k)

        for k, stacked in prefetch_iterator(gen(), depth=2):
            yield k, self._place_stream(stacked)

    def _init_streamed_counts(self) -> None:
        master = core.sharded_zeros(self.word_topic.storage_shape,
                                    jnp.int32, self.word_topic.sharding)
        nk = core.sharded_zeros(self.summary.padded_shape, jnp.int32,
                                self.summary.sharding)
        for _k, dev in self._stream_calls():
            master, nk = self._init_call(master, nk, dev)
        self.word_topic.put_raw(master)
        self.summary.put_raw(nk)

    def _sync_z_host(self) -> None:
        """Make the host z copy globally complete (multi-process only).

        Training never needs this: each process stages and drains exactly
        the lanes its devices own. Full-z consumers (doc_topics, store)
        call it lazily — the owned lanes are exchanged with one
        ``process_allgather`` of equal-sized [cap, TB] slabs PER SWEEP
        CALL (uniform sharding ⇒ every process owns the same lane count;
        model-axis replicas write identical data, which is idempotent).
        Chunking by call keeps the peak device/host transfer bounded for
        out-of-core-scale corpora — a single whole-sweep allgather would
        materialise the global z through device memory on every host at
        once (ADVICE r3), exactly what stream_blocks exists to avoid."""
        if jax.process_count() == 1 or self._z_synced \
                or self.config.local_corpus:
            # local_corpus: z is per-process BY DESIGN (each process owns
            # its shard's lanes); there is no global host z to complete
            return
        offs = self._owned_call_offsets()
        from jax.experimental import multihost_utils
        # ownership offsets are call-invariant: gather them ONCE and
        # derive each call's global block ids locally (one collective
        # per chunk instead of two)
        all_offs = np.asarray(multihost_utils.process_allgather(offs))
        for k in range(self.calls_per_sweep):
            blocks = k * self._per_call + offs
            all_vals = np.asarray(multihost_utils.process_allgather(
                self._z_host[blocks]))
            for p in range(all_offs.shape[0]):
                self._z_host[k * self._per_call + all_offs[p]] = \
                    all_vals[p]
        self._z_synced = True

    def _sweep_streamed(self) -> None:
        wstale = self._to_stale(self.word_topic.raw())
        per_call, TB = self._per_call, self._tb
        # fresh accumulator: after the sweep it IS the new master
        # (counts telescope — see the superstep body)
        acc = core.sharded_zeros(self.word_topic.storage_shape, jnp.int32,
                                 self.word_topic.sharding)
        pending: list = []

        def drain(item):
            # write back by addressable shard: each process updates only
            # the z lanes its own devices computed (multi-host safe;
            # model-axis replicas rewrite identical data, which is fine)
            k, z_out = item
            seen = set()
            for shard in z_out.addressable_shards:
                ssl, bsl = shard.index        # rectangular [S, B] chunk;
                # XLA may shard the aux over EITHER axis, so honor both.
                # Model-axis replicas carry identical data — fetch each
                # distinct chunk ONCE, not once per replica (mp x the
                # D2H bytes on the per-call hot path otherwise)
                key = (ssl.start, ssl.stop, bsl.start, bsl.stop)
                if key in seen:
                    continue
                seen.add(key)
                s0 = 0 if ssl.start is None else ssl.start
                b0 = 0 if bsl.start is None else bsl.start
                data = np.asarray(shard.data)  # [S_local, B_local]
                bidx = self._local_of_global(
                    self._block_rows(k, s0, s0 + data.shape[0],
                                     b0, b0 + data.shape[1]))
                self._z_host[bidx.reshape(-1)] = data.reshape(-1, TB)

        for k, dev in self._stream_calls():
            key = jax.random.fold_in(self._key, self._calls_done)
            self._calls_done += 1
            (acc,), z_out = self._fused_stream((acc,), wstale, dev, key)
            try:
                z_out.copy_to_host_async()
            except AttributeError:
                pass
            pending.append((k, z_out))
            if len(pending) > 2:
                drain(pending.pop(0))
        for item in pending:
            drain(item)
        self._z_synced = False   # other processes' lanes are now stale
        self.word_topic.put_raw(acc)

    # -- count init --------------------------------------------------------

    def _init_counts(self) -> None:
        tiled = self.config.sampler == "tiled"
        ndk_dtype = self._ndk.dtype

        @jax.jit
        def build(z, tw, td, m):
            nwk = jnp.zeros(self.word_topic.storage_shape, jnp.int32)
            ndk = jnp.zeros(self._ndk.shape, ndk_dtype)
            if tiled:
                nwk = nwk.at[tw, z // 128, z % 128].add(m)
                ndk = ndk.at[td, z // 128, z % 128].add(
                    m.astype(ndk_dtype))
            else:
                nwk = nwk.at[tw, z].add(m)
                ndk = ndk.at[td, z].add(m.astype(ndk_dtype))
            nk = jnp.zeros(self.summary.padded_shape, jnp.int32)
            nk = nk.at[z].add(m)
            return nwk, ndk, nk

        tw_dev = self._place(self._tw, P())
        m_dev = self._place(self._mask.astype(np.int32), P())
        nwk, ndk, nk = build(self._z, tw_dev,
                             self._place(self._td, P()), m_dev)
        self.word_topic.put_raw(nwk)
        self._ndk = ndk
        self.summary.put_raw(nk)
        if self._stale:
            # the per-sweep master rebuild scatters over the full stream
            self._tw_dev = tw_dev
            self._mask_dev = m_dev

    # -- the Gibbs superstep ----------------------------------------------

    def _build_superstep(self) -> None:
        c = self.config
        alpha, beta = self.alpha, self.beta
        vbeta = self.V * beta
        K = self.K

        def scan_body(carry, inp):
            nwk, ndk, nk, z = carry
            w, d, idx, msk, key = inp
            zi = jnp.take(z, idx)
            # padded lanes must not touch counts: nwk/ndk park them on
            # scratch rows, but nk has no scratch slot — phantom counts
            # would drift between topics across sweeps
            one = msk
            # remove the token's own count (proper collapsed Gibbs);
            # nk's element scatter (B updates into K bins, heavy
            # duplicates) is pathologically slow on TPU — use a masked
            # one-hot reduction instead (measured ~5x whole-step win)
            nwk = nwk.at[w, zi].add(-one)
            ndk = ndk.at[d, zi].add(-one)
            oh_old = jax.nn.one_hot(zi, K, dtype=jnp.int32) * one[:, None]
            nk = nk.at[:K].add(-oh_old.sum(0))
            ft = jnp.bfloat16 if c.precision == "bfloat16" \
                else jnp.float32
            A = jnp.take(ndk, d, axis=0).astype(ft)             # [B, K]
            W = jnp.take(nwk, w, axis=0).astype(ft)             # [B, K]
            S = (nk[:K].astype(jnp.float32) + vbeta).astype(ft)  # [K]
            # linear-space posterior + inverse-CDF sampling: one uniform
            # per token (vs K gumbels), no logs — the RNG was the hot op.
            # Batch-stale decrements can transiently dip below zero; clamp
            # (AD-LDA approximation, see module docstring)
            probs = jnp.maximum((A + ft(alpha)) * (W + ft(beta)),
                                ft(0.0)) / S                    # [B, K]
            cdf = jnp.cumsum(probs, axis=1)
            u = jax.random.uniform(key, (probs.shape[0], 1)) \
                .astype(ft) * cdf[:, -1:]
            znew = jnp.minimum((cdf < u).sum(axis=1),
                               K - 1).astype(jnp.int32)
            nwk = nwk.at[w, znew].add(one)
            ndk = ndk.at[d, znew].add(one)
            oh_new = jax.nn.one_hot(znew, K, dtype=jnp.int32) * one[:, None]
            nk = nk.at[:K].add(oh_new.sum(0))
            z = z.at[idx].set(znew)
            return (nwk, ndk, nk, z), ()

        def body(params, states, locals_, options, ws, ds, idxs, msks, key):
            nwk, nk = params
            ndk, z = locals_
            keys = jax.random.split(key, ws.shape[0])
            (nwk, ndk, nk, z), _ = lax.scan(
                scan_body, (nwk, ndk, nk, z), (ws, ds, idxs, msks, keys))
            return (nwk, nk), states, (ndk, z), None

        # supported fused path: tables = (word_topic, summary); app-local
        # carry = (doc-topic counts, z assignments)
        self._fused = make_superstep((self.word_topic, self.summary), body,
                                     name="lda_gibbs")

        @jax.jit
        def build_wcdf(nwk):
            # stale word-proposal CDF over (N_wk + beta), one row per
            # padded vocab row; rebuilt once per sweep like the
            # reference's per-slice alias tables
            return jnp.cumsum(
                jnp.maximum(nwk.astype(jnp.float32), 0.0) + beta, axis=1)

        self._build_wcdf = build_wcdf

        @jax.jit
        def loglik(nwk, ndk, nk, ws, ds, mask):
            # operands are the pre-placed [S, B] superstep inputs (mask
            # int32) — flatten here rather than re-uploading the corpus
            # from host every eval
            ws, ds = ws.reshape(-1), ds.reshape(-1)
            m = mask.reshape(-1).astype(jnp.float32)
            A = jnp.take(ndk, ds, axis=0).astype(jnp.float32)
            W = jnp.take(nwk, ws, axis=0).astype(jnp.float32)
            S = nk[:K].astype(jnp.float32)
            return _predictive_ll(A, W, S, m, alpha, beta, K, vbeta)

        self._loglik = loglik

    def _build_tiled_superstep(self) -> None:
        """The measured-fastest sampler: tile-aligned counts + the fused
        pallas posterior/sampler (multiverso_tpu.ops.gibbs_sample_tiled).

        Differences from the exact 'gibbs' body (all within the AD-LDA
        approximation family the reference itself lives in — see module
        docstring):
        - own-token removal is in-register on the numerator counts (no
          upfront decrement scatters); the summary denominator keeps the
          own count (+1 in a ~T/K-sized denominator),
        - counts move by NET scatters (-1 old, +1 new), halving scatter
          traffic,
        - the summary delta comes out of the kernel (no [B, K] one-hot
          reductions in HBM).
        """
        c = self.config
        alpha, beta = self.alpha, self.beta
        vbeta = self.V * beta
        K = self.K
        B = c.batch_tokens
        tiles = K // 128
        interpret = self._interpret
        stale = self._stale
        from multiverso_tpu.ops import gibbs_sample_tiled
        sampler_call = self._wrap_kernel_dp(
            lambda A3, W3, sinv, zi, msk, u1, u2: gibbs_sample_tiled(
                A3, W3, sinv, zi, msk, u1, u2, alpha=alpha, beta=beta,
                interpret=interpret))

        def sample_and_update(nk, ndk3, z, W3, w, d, off, msk, key):
            """Shared step core: sample the slice, move doc/summary
            counts. Returns (nk, ndk3, z, zi, znew)."""
            zi = lax.dynamic_slice_in_dim(z, off, B)
            A3 = jnp.take(ndk3, d, axis=0)              # [B, C, 128]
            sinv = 1.0 / (nk[:K].astype(jnp.float32).reshape(tiles, 128)
                          + vbeta)
            k1, k2 = jax.random.split(key)
            u1 = jax.random.uniform(k1, (B,))
            u2 = jax.random.uniform(k2, (B,))
            znew, nkd = sampler_call(A3, W3, sinv, zi, msk, u1, u2)
            one = msk.astype(ndk3.dtype)
            cold, lold = zi // 128, zi % 128
            cnew, lnew = znew // 128, znew % 128
            ndk3 = ndk3.at[d, cold, lold].add(-one)
            ndk3 = ndk3.at[d, cnew, lnew].add(one)
            nk = nk.at[:K].add(nkd.reshape(-1))
            z = lax.dynamic_update_slice_in_dim(z, znew, off, 0)
            return nk, ndk3, z, zi, znew

        if stale:
            # word rows from the per-sweep bf16 mirror (sharded gather —
            # the mirror stays a vocab slice per chip); no per-step
            # word-count scatters (master rebuilt from z at sweep end)
            self._build_stale_helpers()
            gather_w = self._gather_w

            def scan_body(wstale, carry, inp):
                nk, ndk3, z = carry
                w, d, off, msk, key = inp
                W3 = gather_w(wstale, w)
                nk, ndk3, z, _, _ = sample_and_update(
                    nk, ndk3, z, W3, w, d, off, msk, key)
                return (nk, ndk3, z), ()

            def body(params, states, locals_, options, wstale, ws, ds,
                     offs, msks, key):
                (nk,) = params
                ndk3, z = locals_
                keys = jax.random.split(key, ws.shape[0])
                (nk, ndk3, z), _ = lax.scan(
                    lambda cy, inp: scan_body(wstale, cy, inp),
                    (nk, ndk3, z), (ws, ds, offs, msks, keys))
                return (nk,), states, (ndk3, z), None

            self._fused = make_superstep((self.summary,), body,
                                         name="lda_tiled_stale")
        else:
            def scan_body(carry, inp):
                nwk3, nk, ndk3, z = carry
                w, d, off, msk, key = inp
                W3 = jnp.take(nwk3, w, axis=0)
                nk, ndk3, z, zi, znew = sample_and_update(
                    nk, ndk3, z, W3, w, d, off, msk, key)
                one = msk
                nwk3 = nwk3.at[w, zi // 128, zi % 128].add(-one)
                nwk3 = nwk3.at[w, znew // 128, znew % 128].add(one)
                return (nwk3, nk, ndk3, z), ()

            def body(params, states, locals_, options, ws, ds, offs,
                     msks, key):
                nwk3, nk = params
                ndk3, z = locals_
                keys = jax.random.split(key, ws.shape[0])
                (nwk3, nk, ndk3, z), _ = lax.scan(
                    scan_body, (nwk3, nk, ndk3, z),
                    (ws, ds, offs, msks, keys))
                return (nwk3, nk), states, (ndk3, z), None

            self._fused = make_superstep(
                (self.word_topic, self.summary), body, name="lda_tiled")

        self._build_blocked_loglik()

    def _build_mh_superstep(self) -> None:
        """The O(1)-per-token sampler, LightLDA's own sparsity insight
        vectorized for TPU (no [B, K] tensors anywhere):

        - word proposal: inverse-CDF binary search over the per-sweep
          stale CDF table — ceil(log2 K) scalar gathers per token,
        - doc proposal: the z-array trick — sample a random slot of the
          token's doc and copy its live topic (one gather), alpha-smoothed
          uniform with the standard mixture probability,
        - acceptance: full MH ratio with LIVE counts (single-element
          gathers) against the stale proposal densities.
        """
        c = self.config
        alpha, beta = self.alpha, self.beta
        vbeta = self.V * beta
        K = self.K
        n_search = max(1, (K - 1).bit_length())
        doc_len, doc_start = self._doc_len, self._doc_start
        inv_perm = self._inv_perm

        def body(wcdf, nwk_stale, carry, inp):
            nwk, ndk, nk, z = carry
            w, d, idx, msk, key = inp
            zi = jnp.take(z, idx)
            one = msk
            nwk = nwk.at[w, zi].add(-one)
            ndk = ndk.at[d, zi].add(-one)
            # one-hot reduction, not an element scatter (see gibbs body)
            oh_old = jax.nn.one_hot(zi, K, dtype=jnp.int32) * one[:, None]
            nk = nk.at[:K].add(-oh_old.sum(0))

            def p_live(k):
                # collapsed posterior factor from LIVE counts (own token
                # removed); clamp transient negatives (AD-LDA)
                return (jnp.maximum(ndk[d, k].astype(jnp.float32) + alpha,
                                    1e-12)
                        * jnp.maximum(nwk[w, k].astype(jnp.float32) + beta,
                                      1e-12)
                        / jnp.maximum(nk[k].astype(jnp.float32) + vbeta,
                                      1e-12))

            def q_word(k):
                # stale proposal density from the pre-sweep count snapshot
                # (differencing the f32 CDF instead would cancel
                # catastrophically for low-count topics of frequent words)
                return nwk_stale[w, k].astype(jnp.float32) + beta

            cur = zi
            wtot = wcdf[w, K - 1]
            dlen = jnp.take(doc_len, d).astype(jnp.float32)
            dstart = jnp.take(doc_start, d)
            keys = jax.random.split(key, 5 * c.mh_steps)
            for r in range(c.mh_steps):
                k1, k2, k3, k4, k5 = keys[5 * r: 5 * r + 5]
                # --- word proposal ---
                target = jax.random.uniform(k1, w.shape) * wtot
                lo = jnp.zeros_like(cur)
                hi = jnp.full_like(cur, K)
                for _ in range(n_search):
                    mid = (lo + hi) // 2
                    go = wcdf[w, mid] < target
                    lo = jnp.where(go, mid + 1, lo)
                    hi = jnp.where(go, hi, mid)
                prop = jnp.clip(lo, 0, K - 1)
                ratio = (p_live(prop) * q_word(cur)
                         / (p_live(cur) * q_word(prop)))
                acc = jax.random.uniform(k2, w.shape) < ratio
                cur = jnp.where(acc, prop, cur)
                # --- doc proposal (z-array trick) ---
                pa = (K * alpha) / (dlen + K * alpha)
                slot = jnp.minimum(
                    (jax.random.uniform(k3, w.shape) * dlen)
                    .astype(jnp.int32),
                    jnp.maximum(dlen.astype(jnp.int32) - 1, 0))
                zslot = jnp.take(z, jnp.take(inv_perm, dstart + slot))
                unif = jax.random.randint(k4, w.shape, 0, K)
                u = jax.random.uniform(k5, w.shape)
                prop = jnp.where(u < pa, unif, zslot)
                # z-array density includes the own token (z[idx] still
                # holds zi): q_d(k) = ndk^- (d,k) + [k==zi] + alpha
                def q_doc(k):
                    return (ndk[d, k].astype(jnp.float32)
                            + (k == zi).astype(jnp.float32) + alpha)
                ratio = (p_live(prop) * q_doc(cur)
                         / jnp.maximum(p_live(cur) * q_doc(prop), 1e-20))
                acc = jax.random.uniform(
                    jax.random.fold_in(k5, 1), w.shape) < ratio
                cur = jnp.where(acc, prop, cur)

            znew = jnp.where(msk > 0, cur, zi)
            nwk = nwk.at[w, znew].add(one)
            ndk = ndk.at[d, znew].add(one)
            oh_new = jax.nn.one_hot(znew, K, dtype=jnp.int32) \
                * one[:, None]
            nk = nk.at[:K].add(oh_new.sum(0))
            z = z.at[idx].set(znew)
            return (nwk, ndk, nk, z), ()

        def fused_body(params, states, locals_, options, wcdf, nwk_stale,
                       ws, ds, idxs, msks, key):
            nwk, nk = params
            ndk, z = locals_
            keys = jax.random.split(key, ws.shape[0])
            (nwk, ndk, nk, z), _ = lax.scan(
                lambda carry, inp: body(wcdf, nwk_stale, carry, inp),
                (nwk, ndk, nk, z), (ws, ds, idxs, msks, keys))
            return (nwk, nk), states, (ndk, z), None

        self._fused_mh = make_superstep(
            (self.word_topic, self.summary), fused_body, name="lda_mh")

    def _place(self, arr: np.ndarray, spec) -> jax.Array:
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # -- training ----------------------------------------------------------

    def sweep(self) -> None:
        """One full sampling pass over the corpus."""
        if self._docblock and self.config.stream_blocks:
            self._sweep_streamed()
            return
        mh = self.config.sampler == "mh"
        if mh:
            wcdf = self._build_wcdf(self.word_topic.raw())
            # pre-sweep snapshot for the stale proposal density (the live
            # param buffer is donated by the first superstep call)
            nwk_stale = self.word_topic.raw() + 0
        if self._stale:
            wstale = self._to_stale(self.word_topic.raw())
        for call in self._calls:
            key = jax.random.fold_in(self._key, self._calls_done)
            self._calls_done += 1
            if mh:
                ws, ds, idxs, msks = call
                (self._ndk, self._z), _ = self._fused_mh(
                    (self._ndk, self._z), wcdf, nwk_stale,
                    ws, ds, idxs, msks, key)
            elif self._stale:
                (self._ndk, self._z), _ = self._fused(
                    (self._ndk, self._z), wstale, *call, key)
            else:
                (self._ndk, self._z), _ = self._fused(
                    (self._ndk, self._z), *call, key)
        if self._stale:
            # fold the sweep's moves into the int32 master (the
            # reference's block-end Add of accumulated deltas)
            if self._docblock:
                nwk = self._rebuild(self._z, self._tw_flat,
                                    self._mask_flat)
            else:
                nwk = self._rebuild(self._z, self._tw_dev,
                                    self._mask_dev)
            self.word_topic.put_raw(nwk)

    def train(self, num_iterations: Optional[int] = None) -> float:
        """Run Gibbs sweeps; returns the final per-token log-likelihood.
        Eval runs every ``eval_every`` sweeps (and always on the last):
        the predictive-likelihood pass re-gathers count rows for the
        whole corpus, a sweep-sized cost the reference's Eval role also
        pays only periodically."""
        iters = num_iterations if num_iterations is not None \
            else self.config.num_iterations
        every = max(self.config.eval_every, 1)
        t0 = time.perf_counter()
        ck_every = self.config.checkpoint_interval
        # the restored cursor applies ONCE (the resume); later train()
        # calls start from 0 like they always did
        start_sweep = min(self._resume_sweeps, iters)
        self._resume_sweeps = 0
        it = start_sweep
        while it < iters:
            # divergence rollback (MVTPU_HEALTH_ACTION=rollback):
            # restore_run_state moved the sweep cursor back to the last
            # clean generation — replay from there (sweep keys derive
            # from _calls_done, which the restore also rewound)
            if telemetry.health.maybe_rollback(self) is not None:
                it = min(self._resume_sweeps, iters)
                self._resume_sweeps = 0
                continue
            t_sweep = time.perf_counter()
            with telemetry.span("lda.sweep"):
                self.sweep()
            telemetry.step_timeline(
                "lda", it, tokens=self.num_tokens,
                dispatch_s=time.perf_counter() - t_sweep)
            telemetry.histogram(
                "app.step.seconds", telemetry.LATENCY_BUCKETS,
                app="lda").observe(time.perf_counter() - t_sweep)
            telemetry.beat()    # flight recorder: a heartbeat per sweep
            self._sweep_done = it + 1
            if self.run_ckpt is not None:
                # run-level manager (replaces the bespoke
                # checkpoint_interval prefix dump): atomic generations,
                # keep-K retention, overlapped writes; collective
                self.run_ckpt.maybe_save(it + 1, self.run_state)
            elif ck_every > 0 and self.config.checkpoint_prefix \
                    and (it + 1) % ck_every == 0:
                # legacy periodic full-state dump (sampler state
                # included, so a crash resumes mid-training); collective
                self.store(self.config.checkpoint_prefix)
            it += 1
            if it % every and it != iters:
                continue
            ll = self.loglik()
            self.ll_history.append(ll)
            log.info("lightlda iter %d: loglik/token=%.4f", it - 1, ll)
        dt = time.perf_counter() - t0
        tokens = self.num_tokens * max(iters - start_sweep, 0)
        telemetry.counter("lda.tokens").inc(tokens)
        telemetry.emit("lda.doc_tokens_per_sec", tokens / dt,
                       "tokens/s")
        log.info("lightlda done: %d iters, %.0f doc-tokens/s",
                 iters, tokens / dt)
        return self.ll_history[-1] if self.ll_history else float("nan")

    # -- eval / output -----------------------------------------------------

    def loglik(self) -> float:
        """Mean per-token predictive log-likelihood (the reference's
        `Eval` role). Evaluates over the pre-placed device-resident call
        slices — the token stream is static, so no host re-upload."""
        total = 0.0
        if self._docblock and self.config.stream_blocks:
            for _k, dev in self._stream_calls():
                total += float(self._loglik_stream(
                    self.word_topic.raw(), self.summary.raw(), dev))
            return total / max(self.num_tokens, 1)
        for i, call in enumerate(self._calls):
            if self._docblock:
                ws, _drels, msks, _offs = call
                args = (ws, self._loglik_rows[i], msks)
            else:
                ws, ds, _idxs, msks = call
                args = (ws, ds, msks)
            total += float(self._loglik(
                self.word_topic.raw(), self._ndk, self.summary.raw(),
                *args))
        return total / max(self.num_tokens, 1)

    def doc_topics(self) -> np.ndarray:
        """[num_docs, K] doc-topic counts (worker-local state).

        Multi-process ``stream_blocks`` note: this is a COLLECTIVE —
        the lazy z sync all-gathers owned lanes, so every process must
        call it in lockstep (an ``if rank == 0:`` guard deadlocks).
        Under ``local_corpus`` there is no sync: the returned counts
        cover THIS process's docs; other processes' rows are zero."""
        if self._docblock and self.config.stream_blocks:
            self._sync_z_host()
            # host-side scatter over the host-resident z (chunked: the
            # temporaries stay bounded regardless of corpus size)
            out = np.zeros((self.num_docs, self.K), np.int32)
            chunk = max(1, (1 << 22) // self._tb)     # ~4M tokens
            for lo in range(0, len(self._tw_host), chunk):
                sl = slice(lo, lo + chunk)
                tw, drel = self._tw_host[sl], self._drel_host[sl]
                z = self._z_host[sl]
                blocks = np.arange(lo, lo + len(tw))[:, None]
                docs = self._doc_of_row[blocks, drel]
                valid = (tw != self._scratch_word) & (docs >= 0)
                np.add.at(out, (docs[valid], z[valid]), 1)
            return out
        if self._docblock:
            blocked = np.asarray(self._ndk)
            out = np.zeros((self.num_docs, self.K), np.int32)
            valid = self._blk_of_doc >= 0
            out[valid] = blocked[self._blk_of_doc[valid],
                                 self._row_of_doc[valid]].reshape(
                int(valid.sum()), self.K)
            return out
        return np.asarray(self._ndk[: self.num_docs]).reshape(
            self.num_docs, self.K)

    def word_topics(self) -> np.ndarray:
        """[V, K] word-topic counts from the table (a bounded-staleness
        cached view under ``MVTPU_STALENESS`` — logging/eval reads skip
        the per-call blocking fetch)."""
        if self._wt_view is not None:
            return self._wt_view.get()
        return self.word_topic.get()

    def top_words(self, topic: int, k: int = 10) -> np.ndarray:
        return np.argsort(-self.word_topics()[:, topic])[:k]

    def dump_model(self, uri: str, rows_per_fetch: int = 4096) -> None:
        """Write the word-topic model in the reference's sparse text
        format — one line per word, ``word_id topic:count ...`` with only
        the NONZERO entries (the lightlda model dump shape). Fetches go
        through :meth:`SparseMatrixTable.get_rows_sparse`, so only the
        nonzero entries ever cross device→host (a converged topic model
        is ~99% zeros per row)."""
        from multiverso_tpu.io import open_stream
        import contextlib
        # every process runs the (collective) fetches; only rank 0
        # writes — concurrent 'wb' on a shared filesystem would corrupt
        write = jax.process_index() == 0
        stream = open_stream(uri, "wb") if write \
            else contextlib.nullcontext()
        with stream:
            for lo in range(0, self.V, rows_per_fetch):
                ids = np.arange(lo, min(lo + rows_per_fetch, self.V))
                indptr, cols, vals = \
                    self.word_topic.get_rows_sparse(ids)
                if not write:
                    continue
                lines = []
                for i, w in enumerate(ids):
                    ent = " ".join(
                        f"{k}:{v}" for k, v in
                        zip(cols[indptr[i]:indptr[i + 1]],
                            vals[indptr[i]:indptr[i + 1]]))
                    lines.append(f"{w} {ent}".rstrip())
                stream.write(("\n".join(lines) + "\n").encode())

    def _export_sampler_state(self):
        """(manifest scalars, payload arrays) of the sampler state —
        z assignments + doc-topic counts in the layout-appropriate
        encoding. ONE copy of the export logic, shared by the legacy
        prefix :meth:`store` and the run-manager :meth:`run_state`.

        Multi-process ``stream_blocks`` note: COLLECTIVE (like table
        store) — the lazy z sync all-gathers owned lanes, so every
        process must call it in lockstep (an ``if rank == 0:`` guard
        deadlocks)."""
        if self._docblock:
            if self.config.local_corpus:
                # per-process shard: z alone is the sampler state (load
                # for streamed layouts never reads ndk) — a global-size
                # dense ndk per rank would defeat the 1/P host scaling
                dense = np.zeros((0, self.K), np.int16)
                z = self._z_host.reshape(-1)
            else:
                # z is indexed in the packed block layout; ndk exports
                # as the dense [D, K] logical counts (the in-memory
                # loader rebuilds its blocked counts from it)
                ndk_dtype = np.int16 if self.config.stream_blocks \
                    else np.dtype(self._ndk.dtype)
                dense = np.zeros((self.num_docs + 1, self.K), ndk_dtype)
                dense[:self.num_docs] = self.doc_topics()
                if self.config.stream_blocks:
                    self._sync_z_host()
                    z = self._z_host.reshape(-1)
                else:
                    z = np.asarray(self._z).reshape(-1)
            layout = "docblock"
        else:
            dense = np.asarray(self._ndk).reshape(self.num_docs + 1,
                                                  self.K)
            z = np.asarray(self._z)
            layout = "stream"
        manifest = {"magic": "multiverso_tpu.lda_state.v1",
                    "num_tokens": self.num_tokens,
                    # torn-set detection: the state file is written LAST
                    # and records the table's step — a crash between the
                    # per-file-atomic writes is caught at load
                    "word_topic_step":
                        self.word_topic.default_option.step,
                    "perm_seed": self.config.seed,
                    "t_pad": int(z.shape[0]),
                    "layout": layout,
                    "calls_done": self._calls_done}
        if self._docblock:
            # z indexing depends on the exact packing: equal padded
            # lengths with different block geometry must not load
            manifest["block_tokens"] = self.config.block_tokens
            manifest["block_docs"] = self.config.block_docs
        if self.config.local_corpus:
            # per-process sampler-state shard (z and doc counts are
            # process-local under local_corpus); same process layout
            # required to resume
            manifest["layout"] = "docblock_local"
            manifest["processes"] = jax.process_count()
            # per-rank shard identity (ADVICE r3): the process-count and
            # num_tokens checks alone would accept a DIFFERENT doc-to-
            # process split (or device order) of equal sizes, silently
            # binding the loaded z to the wrong documents/blocks
            crc, ntok = self._local_shard_digest()
            manifest["shard_crc32"] = crc
            manifest["local_tokens"] = ntok
        return manifest, {"z": z, "ndk": dense}

    def store(self, uri_prefix: str) -> None:
        """Checkpoint tables AND sampler state (z, doc-topic counts):
        the three must stay consistent or resumed sweeps corrupt counts.
        Collectivity caveats: see :meth:`_export_sampler_state`."""
        from multiverso_tpu.tables.base import savez_stream
        self.word_topic.store(f"{uri_prefix}.word_topic.npz")
        self.summary.store(f"{uri_prefix}.summary.npz")
        manifest, payload = self._export_sampler_state()
        state_path = f"{uri_prefix}.state.npz"
        if self.config.local_corpus:
            state_path = (f"{uri_prefix}.state"
                          f".rank{jax.process_index()}.npz")
        # every rank writes (z is globally complete after the sync above,
        # so the shared-path payloads are identical; per-process targets
        # like mem:// need their own copy); shared-path safety comes from
        # the stream layer's atomic rename
        savez_stream(state_path, manifest, payload)
        self._last_store = (uri_prefix, self._calls_done)

    def _local_shard_digest(self):
        """(crc32, local token count) identifying THIS rank's corpus
        shard AND its packed layout: token words, doc-relative rows, and
        the device-order-derived owned lane offsets all feed the crc, so
        resuming with a different split/ordering of equal sizes is
        rejected instead of corrupting counts."""
        import zlib
        crc = zlib.crc32(self._tw_host.tobytes())
        crc = zlib.crc32(self._drel_host.tobytes(), crc)
        crc = zlib.crc32(np.ascontiguousarray(
            np.asarray(self._own_offs, np.int64)).tobytes(), crc)
        ntok = int((self._tw_host != self._scratch_word).sum())
        return int(crc), ntok

    def load(self, uri_prefix: str) -> None:
        from multiverso_tpu.tables.base import loadz_stream
        self.word_topic.load(f"{uri_prefix}.word_topic.npz")
        self.summary.load(f"{uri_prefix}.summary.npz")
        state_path = f"{uri_prefix}.state.npz"
        if self.config.local_corpus:
            state_path = (f"{uri_prefix}.state"
                          f".rank{jax.process_index()}.npz")
        manifest, data = loadz_stream(state_path,
                                      "multiverso_tpu.lda_state.v1")
        self._import_sampler_state(manifest, data)

    def _import_sampler_state(self, manifest, data) -> None:
        """Validate + install sampler state (z, doc counts) against the
        LIVE tables — ONE copy of the geometry/seed/layout/torn-set
        checks, shared by the legacy prefix :meth:`load` and the
        run-manager :meth:`restore_run_state`. ``data`` is dict-like
        with ``"z"``/``"ndk"`` arrays."""
        if self.config.local_corpus and \
                manifest.get("processes") != jax.process_count():
            raise ValueError(
                f"local_corpus checkpoint was written by "
                f"{manifest.get('processes')} processes, app has "
                f"{jax.process_count()}: z shards are per-process")
        if self.config.local_corpus and "shard_crc32" in manifest:
            crc, ntok = self._local_shard_digest()
            if (manifest["shard_crc32"], manifest["local_tokens"]) \
                    != (crc, ntok):
                raise ValueError(
                    f"local_corpus checkpoint rank shard mismatch "
                    f"(crc32 {manifest['shard_crc32']:#x}/"
                    f"{manifest['local_tokens']} tokens != this app's "
                    f"{crc:#x}/{ntok}): the doc-to-process split and "
                    "device order must match the checkpointing run — "
                    "loading z against a different shard silently "
                    "corrupts counts")
        if manifest["num_tokens"] != self.num_tokens:
            raise ValueError(
                f"checkpoint has {manifest['num_tokens']} tokens, app has "
                f"{self.num_tokens} — same corpus required to resume")
        if "word_topic_step" in manifest and \
                self.word_topic.default_option.step \
                != int(manifest["word_topic_step"]):
            raise ValueError(
                "lda checkpoint is torn: state was "
                f"written at word_topic step "
                f"{manifest['word_topic_step']} but the loaded table "
                f"is at step {self.word_topic.default_option.step} — a "
                "crash interrupted the multi-file store; use an older "
                "complete checkpoint")
        if manifest["perm_seed"] != self.config.seed:
            raise ValueError(
                f"checkpoint was written with seed "
                f"{manifest['perm_seed']}, app has seed "
                f"{self.config.seed}: z is indexed in the seed-derived "
                "stream permutation, so the seeds must match to resume")
        my_layout = "stream" if not self._docblock else \
            ("docblock_local" if self.config.local_corpus else "docblock")
        ck_layout = manifest.get("layout", "stream")
        if ck_layout != my_layout:
            raise ValueError(
                f"checkpoint z layout {ck_layout!r} != app layout "
                f"{my_layout!r}: z indexing is layout-specific")
        if self._docblock:
            want = (self.config.block_tokens, self.config.block_docs)
            got = (manifest.get("block_tokens"),
                   manifest.get("block_docs"))
            if got != want:
                raise ValueError(
                    f"checkpoint block geometry {got} != app {want}: "
                    "z packing must match to resume")
        # T_pad depends on batch_tokens * steps_per_call (and the block
        # packing for doc_blocked): a geometry mismatch would yield a
        # wrong-length z whose out-of-range scatters silently corrupt
        # counts (JAX clamps/drops OOB indices)
        streamed = self._docblock and self.config.stream_blocks
        z_shape = self._z_host.shape if streamed else self._z.shape
        if len(data["z"]) != int(np.prod(z_shape)):
            raise ValueError(
                f"checkpoint z length {len(data['z'])} != app stream "
                f"length {int(np.prod(z_shape))}: batch/block "
                "geometry must match the checkpointing run to resume")
        if streamed:
            # host z is the sampler state; blocked doc counts are derived
            # from it per call, so the stored dense ndk is not needed
            self._z_host = np.asarray(data["z"]).reshape(z_shape) \
                .astype(np.int32)
            self._z_synced = True    # checkpoint z is globally complete
            self._calls_done = int(manifest.get("calls_done", 0))
            return
        self._z = self._place(
            np.asarray(data["z"]).reshape(self._z.shape), P())
        dense = np.asarray(data["ndk"])
        # restore INTO the live array's own sharding (the init-time
        # build jit's output layout) — the fused superstep's donation
        # aliasing was compiled against it, and a replicated P() here
        # hits an XLA aliased-size mismatch on model-parallel meshes
        ndk_sharding = self._ndk.sharding
        if self._docblock:
            blocked = np.zeros(self._ndk.shape,
                               np.dtype(self._ndk.dtype)).reshape(
                self._nb_pad * self._maxd, -1)
            valid = self._blk_of_doc >= 0
            rows = (self._blk_of_doc[valid] * self._maxd
                    + self._row_of_doc[valid])
            blocked[rows] = dense[:self.num_docs][valid].reshape(
                int(valid.sum()), -1)
            self._ndk = jax.device_put(
                blocked.reshape(self._ndk.shape), ndk_sharding)
        else:
            self._ndk = jax.device_put(
                dense.reshape(self._ndk.shape).astype(self._ndk.dtype),
                ndk_sharding)
        # resume the RNG sequence where the checkpoint left off; replaying
        # consumed fold_in keys would correlate sweeps across the resume
        self._calls_done = int(manifest.get("calls_done", 0))

    # -- fault tolerance (ft.checkpoint contract) --------------------------

    def run_state(self) -> dict:
        """Train-state for the run manager: the sampler state (z +
        doc-topic counts, via the shared export) plus the sweep cursor.
        The tables ride the manager's own table export. COLLECTIVE
        under multi-process ``stream_blocks`` (see
        :meth:`_export_sampler_state`)."""
        manifest, payload = self._export_sampler_state()
        # the scalars flatten into the app-state manifest; arrays into
        # the payload — restore_run_state reassembles both
        return {**manifest, **payload, "sweep_done": self._sweep_done}

    def restore_run_state(self, restored) -> None:
        self._import_sampler_state(restored.state, restored.arrays)
        self._sweep_done = int(restored.get("sweep_done", 0))
        self._resume_sweeps = self._sweep_done


def main(argv=None) -> None:
    """CLI mirroring the reference lightlda binary's flags."""
    from multiverso_tpu.utils import configure
    configure.define_string("input_file", "", "docs in word:count format", overwrite=True)
    configure.define_int("num_topics", 100, "topics", overwrite=True)
    configure.define_float("alpha", -1.0, "doc-topic prior (<0 -> 50/K)",
                           overwrite=True)
    configure.define_float("beta", 0.01, "word-topic prior", overwrite=True)
    configure.define_int("num_iterations", 10, "Gibbs sweeps", overwrite=True)
    configure.define_int("eval_every", 1,
                         "likelihood eval cadence in sweeps", overwrite=True)
    configure.define_int("batch_tokens", 4096, "tokens per scan step", overwrite=True)
    configure.define_string("output_file", "", "model checkpoint prefix", overwrite=True)
    configure.define_string("dump_file", "",
                            "sparse text model dump (word k:count ...)",
                            overwrite=True)
    configure.define_string("sampler", "gibbs",
                            "gibbs | mh | tiled (K%128==0; TPU kernel)",
                            overwrite=True)
    configure.define_int("checkpoint_interval", 0,
                         "store -output_file every N sweeps (0 = only "
                         "at end)", overwrite=True)
    from multiverso_tpu.ft.checkpoint import define_run_flags, wire_app
    define_run_flags()
    core.init(argv)
    path = configure.get_flag("input_file")
    if not path:
        raise SystemExit("-input_file is required")
    tw, td, vocab = load_docs(path)
    a = configure.get_flag("alpha")
    cfg = LDAConfig(
        num_topics=configure.get_flag("num_topics"),
        alpha=None if a < 0 else a,
        beta=configure.get_flag("beta"),
        batch_tokens=configure.get_flag("batch_tokens"),
        num_iterations=configure.get_flag("num_iterations"),
        eval_every=configure.get_flag("eval_every"),
        sampler=configure.get_flag("sampler"),
        checkpoint_prefix=configure.get_flag("output_file"),
        checkpoint_interval=configure.get_flag("checkpoint_interval"),
    )
    app = LightLDA(tw, td, vocab, cfg)
    # fault tolerance: run-level checkpoint/resume, cadence in SWEEPS.
    # -run_dir routes the periodic trigger through the manager (atomic
    # generations + retention), replacing the bespoke prefix dump; the
    # legacy -checkpoint_interval value still sets the cadence.
    mgr = wire_app(app, [app.word_topic, app.summary],
                   every_default=cfg.checkpoint_interval or 1)
    # flight recorder: env-gated stall watchdog + device capture (the
    # per-sweep beat is in train)
    with telemetry.maybe_watchdog("lda"), telemetry.profile_window("lda"):
        app.train()
    if mgr is not None:
        mgr.close()     # drain pending background checkpoint writes
    telemetry.record_device_memory()
    out = configure.get_flag("output_file")
    # skip the end-of-train dump when the last periodic store already
    # wrote this exact state (a second full collective dump is pure
    # waste at scale)
    if out and getattr(app, "_last_store", ()) != (out, app._calls_done):
        app.store(out)
    dump = configure.get_flag("dump_file")
    if dump:
        app.dump_model(dump)
    core.barrier()


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
