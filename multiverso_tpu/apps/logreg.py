"""Distributed logistic regression — TPU-native rebuild of the reference's
`Applications/LogisticRegression/` (upstream layout; SURVEY.md §3.6):
multi-threaded, multi-node linear classification over libsvm-style data,
weights in a dense ArrayTable, SGD-family objectives.

Reference shape (SURVEY.md §3.6 row 1): `LogReg` main + `Configure`
(key=value config) + `DataBlock`/`Sample` reader + trainer loop; weights in
ArrayTable (dense) across servers, deltas `Add`ed per minibatch.

TPU design:

- The weight matrix lives in an :class:`ArrayTable` (flat, sharded over the
  mesh ``"model"`` axis — the analog of the contiguous per-server blocks).
- The per-minibatch Get→local-grad→Add round trip of the reference becomes
  ONE jitted train step: batch sharded over the mesh ``"data"`` axis, loss
  grad computed per shard, and because the grad's output sharding equals
  the (data-replicated) param sharding, XLA inserts the cross-data-axis
  reduction (psum over ICI) automatically — the Aggregator + server
  round-trip collapsed into a collective.
- The server-side Updater runs fused in the same step on the sharded
  weights with donated buffers (SURVEY.md §3.9 mapping).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import client, core, telemetry
from multiverso_tpu.tables import ArrayTable, make_superstep
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log


@dataclasses.dataclass
class LogRegConfig:
    """Flag set of the reference app's key=value `Configure` file."""
    input_dim: int
    num_classes: int
    minibatch_size: int = 256
    steps_per_call: int = 8         # minibatches per fused dispatch
    epochs: int = 1
    learning_rate: float = 0.1
    updater: str = "sgd"
    regular_lambda: float = 0.0     # L2 coefficient ("regular=L2" analog)
    ftrl_l1: float = 0.0            # updater="ftrl": L1 / L2 / beta — the
    ftrl_l2: float = 0.0            # AddOption lam/rho/momentum fields
    ftrl_beta: float = 1.0          # (see updaters docstring mapping)
    objective: str = "softmax"      # "softmax" | "sigmoid"
    shard_update: bool = False      # cross-replica weight-update
    # sharding: updater state (adagrad/ftrl/...) + update compute / dp
    # over the data axis (arXiv:2004.13336); no-op for stateless sgd
    seed: int = 0

    def __post_init__(self) -> None:
        if self.objective == "sigmoid" and self.num_classes != 2:
            raise ValueError(
                "objective='sigmoid' is the binary objective; it requires "
                f"num_classes == 2, got {self.num_classes}")


def read_libsvm(path: str, input_dim: int, dtype=np.float32,
                one_based: Optional[bool] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse libsvm/sparse text: `label idx:val idx:val ...` per line.

    The reference's `Sample` reader (Applications/LogisticRegression).
    Canonical libsvm is 1-based; ``one_based=None`` autodetects: a file
    containing index 0 is 0-based, one containing index == input_dim is
    1-based; ambiguous files default to 1-based (the libsvm convention —
    and pass the SAME explicit ``one_based`` for train and test files so
    an ambiguous one cannot silently shift feature columns between them).
    Returns dense (X, y) — dense is the TPU-friendly layout; the sparse
    path of the reference maps to the KVTable app variant
    (:mod:`multiverso_tpu.apps.sparse_logreg`).
    """
    labels, rows = _parse_libsvm(path)
    if one_based is None:
        one_based = _resolve_base(*_base_markers(rows, input_dim),
                                  what=repr(path), input_dim=input_dim)
    return _densify(labels, rows, input_dim, one_based, dtype)


def _parse_libsvm(path: str):
    """One parse pass: (labels list, rows list of [(idx, val), ...])."""
    labels, rows = [], []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            rows.append([(int(t[0]), float(t[1])) for t in
                         (tok.split(":") for tok in parts[1:])])
    return labels, rows


def _base_markers(rows, input_dim: int) -> Tuple[bool, bool]:
    has_zero = has_dim = False
    for r in rows:
        for i, _ in r:
            has_zero |= i == 0
            has_dim |= i == input_dim
    return has_zero, has_dim


def _resolve_base(has_zero: bool, has_dim: bool, *, what: str,
                  input_dim: int) -> bool:
    """THE autodetect rule (single definition — read_libsvm and
    detect_libsvm_base must never disagree on the same file): index 0 ⇒
    0-based, index == input_dim ⇒ 1-based, both ⇒ error, neither ⇒
    1-based (the libsvm convention)."""
    if has_zero and has_dim:
        raise ValueError(
            f"{what}: contains both index 0 and index {input_dim} — "
            "cannot autodetect base; pass one_based explicitly")
    return not has_zero


def _densify(labels, rows, input_dim: int, one_based: bool, dtype
             ) -> Tuple[np.ndarray, np.ndarray]:
    off = 1 if one_based else 0
    xs = []
    for r in rows:
        row = np.zeros(input_dim, dtype=dtype)
        for i, val in r:
            j = i - off
            if j < 0 or j >= input_dim:
                raise ValueError(
                    f"feature index {i} out of range for input_dim "
                    f"{input_dim} (one_based={one_based})")
            row[j] = val
        xs.append(row)
    X = np.stack(xs) if xs else np.zeros((0, input_dim), dtype)
    y = np.asarray(labels)
    # labels may be {-1,+1} (binary libsvm) or {0..C-1}
    if set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y > 0).astype(np.int32)
    return X, y.astype(np.int32)


def detect_libsvm_base(paths, input_dim: int) -> bool:
    """Detect the index base JOINTLY over several libsvm files (train +
    test must agree or feature columns silently shift between them).
    Same rule as ``read_libsvm``'s autodetect (shared ``_resolve_base``)."""
    has_zero = has_dim = False
    for path in paths:
        hz, hd = _base_markers(_parse_libsvm(path)[1], input_dim)
        has_zero |= hz
        has_dim |= hd
    return _resolve_base(has_zero, has_dim, what=repr(list(paths)),
                         input_dim=input_dim)


def synthetic_blobs(n: int, input_dim: int, num_classes: int,
                    seed: int = 0, spread: float = 3.0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Gaussian class blobs — the test/benchmark stand-in dataset."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0.0, spread, (num_classes, input_dim))
    y = rng.integers(0, num_classes, n).astype(np.int32)
    X = centers[y] + rng.normal(0.0, 1.0, (n, input_dim))
    return X.astype(np.float32), y


class LogisticRegression:
    """The app: ArrayTable-backed linear model + fused DP train step."""

    def __init__(self, config: LogRegConfig, *, mesh=None,
                 name: str = "logreg") -> None:
        self.config = config
        self.mesh = mesh if mesh is not None else core.mesh()
        c = config
        self.n_weights = (c.input_dim + 1) * c.num_classes  # + bias row
        rng = np.random.default_rng(c.seed)
        init = np.zeros(self.n_weights, np.float32)
        init[: c.input_dim * c.num_classes] = rng.normal(
            0.0, 0.01, c.input_dim * c.num_classes)
        opt = AddOption.for_ftrl(c.learning_rate, c.ftrl_l1, c.ftrl_l2,
                                 c.ftrl_beta) if c.updater == "ftrl" \
            else AddOption(learning_rate=c.learning_rate)
        self.table = ArrayTable(
            self.n_weights, "float32", init_value=init, updater=c.updater,
            mesh=self.mesh, name=name, default_option=opt,
            shard_update=c.shard_update)
        # MVTPU_STALENESS: weights() (a logging/inspection read — the
        # train step never feeds it back) serves from a bounded-staleness
        # cached view instead of a blocking whole-table fetch per call
        self._view = client.maybe_cached_view(self.table)
        self._data_sharding = NamedSharding(self.mesh, P(core.DATA_AXIS))
        # fault tolerance (ft.checkpoint.wire_app): run-level manager +
        # resume cursor — epochs are the checkpoint/restart unit here.
        # _epoch_done counts completed epochs (what a checkpoint
        # records); _resume_epochs is the restored offset, consumed by
        # the FIRST train() after a resume — repeated in-session
        # train() calls keep their run-all-epochs meaning
        self.run_ckpt = None
        self._epoch_done = 0
        self._resume_epochs = 0
        self._build_step()

    # -- model math --------------------------------------------------------

    def _unflatten(self, w_flat: jax.Array) -> Tuple[jax.Array, jax.Array]:
        c = self.config
        w = w_flat[: c.input_dim * c.num_classes].reshape(
            c.input_dim, c.num_classes)
        b = w_flat[c.input_dim * c.num_classes: self.n_weights].reshape(
            c.num_classes)
        return w, b

    def _loss(self, w_flat, x, y):
        c = self.config
        w, b = self._unflatten(w_flat)
        logits = x @ w + b
        if c.objective == "sigmoid":
            # binary: y in {0,1}, logits[:, 1] - logits[:, 0] as score
            score = logits[:, 1] - logits[:, 0]
            nll = jnp.mean(jnp.logaddexp(0.0, score) - y * score)
        else:
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.mean(
                jnp.take_along_axis(logp, y[:, None], axis=1))
        reg = 0.5 * c.regular_lambda * jnp.sum(w * w)
        return nll + reg

    def _build_step(self) -> None:
        table = self.table

        def body(params, states, locals_, options, x, y):
            (param,), (state,), (opt,) = params, states, options
            loss, grad = jax.value_and_grad(self._loss)(param, x, y)
            param, state = table.updater.apply(param, state, grad, opt)
            return (param,), (state,), locals_, loss

        # supported fused path: grad + updater in one compiled program,
        # donation/sharding/step-counting handled by the table layer
        self._fused = make_superstep((table,), body, name="logreg_step")

        def body_scan(params, states, locals_, options, xs, ys):
            # the scan-superstep treatment the other apps get: S
            # minibatches per dispatch (one host round-trip, not S)
            (param,), (state,), (opt,) = params, states, options

            def sb(carry, inp):
                param, state = carry
                x, y = inp
                loss, grad = jax.value_and_grad(self._loss)(param, x, y)
                param, state = table.updater.apply(param, state, grad,
                                                   opt)
                return (param, state), loss

            (param, state), losses = lax.scan(sb, (param, state),
                                              (xs, ys))
            return (param,), (state,), locals_, losses

        self._fused_scan = make_superstep((table,), body_scan,
                                          name="logreg_superstep")

        @jax.jit
        def predict(param, x):
            w, b = self._unflatten(param)
            return jnp.argmax(x @ w + b, axis=1)

        self._predict = predict

    # -- data plumbing -----------------------------------------------------

    def _shard_batch(self, x: np.ndarray, y: np.ndarray):
        """Pad the batch to a multiple of the data-axis size and place it
        sharded over "data" (per-chip sample shards)."""
        d = self.mesh.shape[core.DATA_AXIS]
        n = len(x)
        m = -(-n // d) * d
        if m != n:
            # pad by repeating the first samples — keeps loss a true mean
            # only when n % d == 0; callers batch accordingly; remainder
            # batches get a slightly reweighted mean, which matches the
            # reference's per-block SGD semantics closely enough.
            reps = np.arange(m - n) % max(n, 1)
            x = np.concatenate([x, x[reps]])
            y = np.concatenate([y, y[reps]])
        xs = jax.device_put(x.astype(np.float32),
                            NamedSharding(self.mesh, P(core.DATA_AXIS, None)))
        ys = jax.device_put(y.astype(np.int32), self._data_sharding)
        return xs, ys

    def _shard_scan(self, xs: np.ndarray, ys: np.ndarray):
        """Place a stacked [S, B, ...] group, batch dim sharded over
        "data" (full minibatches only — B is already a size multiple)."""
        d = self.mesh.shape[core.DATA_AXIS]
        if xs.shape[1] % d:
            reps = np.arange(-xs.shape[1] % d) % xs.shape[1]
            xs = np.concatenate([xs, xs[:, reps]], axis=1)
            ys = np.concatenate([ys, ys[:, reps]], axis=1)
        xd = jax.device_put(xs.astype(np.float32), NamedSharding(
            self.mesh, P(None, core.DATA_AXIS, None)))
        yd = jax.device_put(ys.astype(np.int32), NamedSharding(
            self.mesh, P(None, core.DATA_AXIS)))
        return xd, yd

    # -- training ----------------------------------------------------------

    def train_epoch(self, X: np.ndarray, y: np.ndarray,
                    shuffle_seed: Optional[int] = None) -> float:
        c = self.config
        n = len(X)
        order = np.arange(n)
        if shuffle_seed is not None:
            np.random.default_rng(shuffle_seed).shuffle(order)
        losses = []
        t0 = time.perf_counter()
        # full minibatches group into scanned supersteps (S per dispatch);
        # the trailing partial group falls back to single-step dispatches
        starts = list(range(0, n, c.minibatch_size))
        full = [s for s in starts if s + c.minibatch_size <= n]
        tail = [s for s in starts if s + c.minibatch_size > n]
        S = max(c.steps_per_call, 1)
        step_no = 0
        for g in range(0, len(full) - len(full) % S, S):
            grp = full[g:g + S]
            xs = np.stack([X[order[s:s + c.minibatch_size]] for s in grp])
            ys = np.stack([y[order[s:s + c.minibatch_size]] for s in grp])
            xd, yd = self._shard_scan(xs, ys)
            t_step = time.perf_counter()
            with telemetry.span("logreg.superstep"):
                _, lg = self._fused_scan((), xd, yd)
            telemetry.step_timeline(
                "logreg", step_no, samples=S * c.minibatch_size,
                dispatch_s=time.perf_counter() - t_step)
            telemetry.histogram(
                "app.step.seconds", telemetry.LATENCY_BUCKETS,
                app="logreg").observe(time.perf_counter() - t_step)
            telemetry.beat()
            step_no += 1
            losses.extend(lg)
        for s in full[len(full) - len(full) % S:] + tail:
            idx = order[s:s + c.minibatch_size]
            xs, ys = self._shard_batch(X[idx], y[idx])
            t_step = time.perf_counter()
            with telemetry.span("logreg.step"):
                _, loss = self._fused((), xs, ys)
            telemetry.step_timeline(
                "logreg", step_no, samples=len(idx),
                dispatch_s=time.perf_counter() - t_step)
            telemetry.histogram(
                "app.step.seconds", telemetry.LATENCY_BUCKETS,
                app="logreg").observe(time.perf_counter() - t_step)
            telemetry.beat()
            step_no += 1
            losses.append(loss)
        # one transfer for all loss scalars (a tunneled TPU charges
        # ~100ms per individual scalar fetch)
        mean_loss = float(np.asarray(jnp.stack(losses)).mean())
        dt = time.perf_counter() - t0
        telemetry.counter("logreg.samples").inc(n)
        telemetry.emit("logreg.samples_per_sec", n / dt, "samples/s")
        if self._view is not None:
            # logging-only read off the cached view: within the
            # staleness bound, zero extra device dispatches
            telemetry.gauge("logreg.weight_norm").set(
                float(np.linalg.norm(self._view.get())))
        log.info("logreg epoch done: loss=%.4f %.0f samples/s",
                 mean_loss, n / dt)
        return mean_loss

    def train(self, X: np.ndarray, y: np.ndarray) -> float:
        loss = float("nan")
        # resume picks up at the restored epoch cursor (applied ONCE):
        # the table state is exact (CRC-verified restore) and each
        # epoch's shuffle seed derives from its index, so the remaining
        # epochs replay identically to the uninterrupted run
        e = min(self._resume_epochs, self.config.epochs)
        self._resume_epochs = 0
        while e < self.config.epochs:
            # divergence rollback (MVTPU_HEALTH_ACTION=rollback): the
            # restore ran restore_run_state, so re-read the cursor and
            # replay from the last clean generation
            if telemetry.health.maybe_rollback(self) is not None:
                e = min(self._resume_epochs, self.config.epochs)
                self._resume_epochs = 0
                continue
            loss = self.train_epoch(X, y, shuffle_seed=self.config.seed + e)
            self._epoch_done = e + 1
            if self.run_ckpt is not None:
                self.run_ckpt.maybe_save(self._epoch_done, self.run_state)
            e += 1
        return loss

    # -- fault tolerance (ft.checkpoint contract) --------------------------

    def run_state(self) -> dict:
        """App train-state for the run checkpoint manager: the epoch
        cursor (RNG state is derived from it — shuffle seeds fold the
        epoch index)."""
        return {"epoch_done": self._epoch_done}

    def restore_run_state(self, restored) -> None:
        self._epoch_done = int(restored.get("epoch_done", 0))
        self._resume_epochs = self._epoch_done

    # -- inference / eval --------------------------------------------------

    def predict(self, X: np.ndarray) -> np.ndarray:
        xs = core.place(np.asarray(X, np.float32), mesh=self.mesh)
        return np.asarray(self._predict(self.table.raw(), xs))

    def accuracy(self, X: np.ndarray, y: np.ndarray) -> float:
        return float(np.mean(self.predict(X) == y))

    def weights(self) -> Tuple[np.ndarray, np.ndarray]:
        w_flat = self._view.get() if self._view is not None \
            else self.table.get()
        c = self.config
        w = w_flat[: c.input_dim * c.num_classes].reshape(
            c.input_dim, c.num_classes)
        b = w_flat[c.input_dim * c.num_classes:].reshape(c.num_classes)
        return w, b

    # -- checkpoint --------------------------------------------------------

    def store(self, uri: str) -> None:
        self.table.store(uri)

    def load(self, uri: str) -> None:
        self.table.load(uri)


def main(argv=None) -> None:
    """CLI entry mirroring the reference binary's config-file interface."""
    from multiverso_tpu.utils import configure
    configure.define_string("train_file", "", "libsvm training data", overwrite=True)
    configure.define_string("test_file", "", "libsvm test data", overwrite=True)
    configure.define_int("input_dimension", 784, "feature dimension", overwrite=True)
    configure.define_int("output_dimension", 10, "number of classes", overwrite=True)
    configure.define_int("minibatch_size", 256, "minibatch size", overwrite=True)
    configure.define_int("train_epoch", 1, "epochs", overwrite=True)
    configure.define_float("learning_rate", 0.1, "learning rate", overwrite=True)
    configure.define_float("regular_lambda", 0.0, "L2 coefficient", overwrite=True)
    configure.define_bool("shard_update", False,
                          "cross-replica weight-update sharding "
                          "(updater state + update FLOPs / dp)",
                          overwrite=True)
    configure.define_string("output_model_file", "", "checkpoint URI", overwrite=True)
    from multiverso_tpu.ft.checkpoint import define_run_flags, wire_app
    define_run_flags()
    core.init(argv)
    # the global updater_type default is "default" (plain add) — for a
    # gradient-descent app that means ascent; this app's default is sgd
    updater = configure.get_flag("updater_type")
    if updater == "default":
        updater = "sgd"
    cfg = LogRegConfig(
        input_dim=configure.get_flag("input_dimension"),
        num_classes=configure.get_flag("output_dimension"),
        minibatch_size=configure.get_flag("minibatch_size"),
        epochs=configure.get_flag("train_epoch"),
        learning_rate=configure.get_flag("learning_rate"),
        regular_lambda=configure.get_flag("regular_lambda"),
        updater=updater,
        shard_update=configure.get_flag("shard_update"),
    )
    app = LogisticRegression(cfg)
    train_file = configure.get_flag("train_file")
    test_file = configure.get_flag("test_file")
    # parse each file ONCE, then detect the index base jointly over all of
    # them: per-file detection could assign different bases to train and
    # test, silently shifting feature columns between them
    parsed = {f: _parse_libsvm(f) for f in (train_file, test_file) if f}
    base = True
    if parsed:
        has_zero = has_dim = False
        for _, rows in parsed.values():
            hz, hd = _base_markers(rows, cfg.input_dim)
            has_zero |= hz
            has_dim |= hd
        base = _resolve_base(has_zero, has_dim,
                             what=repr(list(parsed)),
                             input_dim=cfg.input_dim)
    if train_file:
        X, y = _densify(*parsed[train_file], cfg.input_dim, base,
                        np.float32)
    else:
        X, y = synthetic_blobs(20000, cfg.input_dim, cfg.num_classes)
    # fault tolerance: -run_dir/-resume (or MVTPU_RUN_DIR/MVTPU_RESUME)
    # enable run-level checkpoint/resume, cadence in EPOCHS (default:
    # every epoch once a run dir is configured)
    mgr = wire_app(app, [app.table], every_default=1)
    # flight recorder: MVTPU_WATCHDOG=<s> arms a stall watchdog (the
    # per-step beat is in train_epoch); MVTPU_PROFILE_DIR captures a
    # device profile of the whole training run
    with telemetry.maybe_watchdog("logreg"), \
            telemetry.profile_window("logreg"):
        app.train(X, y)
    if mgr is not None:
        mgr.close()     # drain pending background checkpoint writes
    telemetry.record_device_memory()
    log.info("train accuracy: %.4f", app.accuracy(X, y))
    if test_file:
        Xt, yt = _densify(*parsed[test_file], cfg.input_dim, base,
                          np.float32)
        log.info("test accuracy: %.4f", app.accuracy(Xt, yt))
    out = configure.get_flag("output_model_file")
    if out:
        app.store(out)
    core.barrier()


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
