"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh
axis.

Beyond-parity module (SURVEY.md §3.8 lists PP as absent in the
reference): together with data parallelism (mesh data axis), model/tensor
sharding (model axis), and sequence parallelism (ring/Ulysses attention,
:mod:`multiverso_tpu.parallel.ring_attention`), this completes the
dp/tp/pp/sp set for the multi-chip story.

TPU-first design: the schedule is a single compiled program — a
`shard_map` over the pipeline axis in which every device runs the same
`lax.scan` over the S+M-1 schedule ticks, passing activations to its
right neighbor with one `ppermute` per tick (ICI neighbor traffic, the
mesh's cheapest collective). There is no host orchestration, no
per-stage dispatch, and reverse-mode AD works through the whole schedule
(scan + ppermute transpose), so `jax.grad` of a pipelined loss needs
nothing special — activation rematerialization composes via
`jax.checkpoint` on `stage_fn` if memory demands it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from multiverso_tpu import core


def pipeline_apply(stage_params: Any, x: jax.Array,
                   stage_fn: Callable[[Any, jax.Array], jax.Array], *,
                   mesh: Optional[Mesh] = None,
                   axis: str = core.MODEL_AXIS,
                   microbatches: Optional[int] = None) -> jax.Array:
    """Apply S pipeline stages (one per device of ``axis``) to ``x``.

    Args:
      stage_params: pytree whose every leaf has leading axis S (the mesh
        ``axis`` size); stage s's slice lives on device s. The classic
        homogeneous-pipeline condition applies: ``stage_fn`` maps
        activations to activations of the SAME shape/dtype (embedding
        and head layers live outside the pipelined trunk).
      x: [B, ...] global batch; B must divide by ``microbatches``.
      stage_fn: ``(params_s, h) -> h``; traced once per device.
        CONSTRAINT: must be finite — in value and in gradient — on the
        INPUT distribution (``x_mb`` microbatches): bubble ticks run it
        on the current input microbatch as a safe dummy (double-where;
        the result is discarded, but a non-finite vjp would survive the
        output mask and poison ``jax.grad``). It need NOT be finite on
        zeros or stale activations — those never reach it.
      microbatches: schedule depth M (default: the axis size — the
        minimum that fills the pipeline; larger M lowers the bubble
        fraction (S-1)/(S-1+M) at constant memory per tick).

    Returns ``stage_{S-1}(... stage_0(x))`` for the full batch,
    replicated over ``axis``.

    The input is broadcast to every stage (simple and collective-free;
    for activation-dominated trunks the input microbatch is small
    relative to stage state). Schedule: at tick t, stage s computes
    microbatch ``t - s`` if it is in [0, M), then shifts its output one
    hop right; the last stage deposits finished microbatches into an
    output buffer that a final masked ``psum`` replicates.
    """
    mesh = mesh if mesh is not None else core.mesh()
    n = mesh.shape[axis]
    leaves = jax.tree.leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != n:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != mesh "
                f"axis {axis!r} size {n}")
    m = microbatches if microbatches is not None else n
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"{m} microbatches")
    x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])

    def local(params, x_mb):
        params = jax.tree.map(lambda a: a[0], params)   # my stage slice
        me = lax.axis_index(axis)
        perm = [(j, (j + 1) % n) for j in range(n)]
        zero_act = jnp.zeros_like(x_mb[0])

        def tick(carry, t):
            act, out = carry
            mb_id = t - me
            valid = (mb_id >= 0) & (mb_id < m)
            # stage 0 pulls its microbatch from the input; later stages
            # consume the activation the previous tick shifted in
            inp = jnp.where(me == 0,
                            x_mb[jnp.clip(t, 0, m - 1)], act)
            # Double-where guard (the where-grad trap): during bubble
            # ticks ``inp`` is a zero/stale activation; if stage_fn is
            # non-finite there (log, rsqrt, division), its NaN/Inf
            # cotangent survives the output mask (0 * inf = nan inside
            # the vjp) and poisons jax.grad of the whole schedule. So
            # stage_fn only ever sees known-good data: bubble ticks feed
            # the current input microbatch (real data — stage_fn must be
            # finite, in value AND grad, on the input distribution; see
            # the docstring constraint), and the discarded result is
            # masked out below as before.
            safe_inp = jnp.where(valid, inp, x_mb[jnp.clip(t, 0, m - 1)])
            h = stage_fn(params, safe_inp)
            h = jnp.where(valid, h, inp)
            # the last stage deposits the finished microbatch
            out = lax.cond(
                valid & (me == n - 1),
                lambda o: lax.dynamic_update_index_in_dim(
                    o, h.astype(o.dtype), jnp.clip(mb_id, 0, m - 1), 0),
                lambda o: o, out)
            act = lax.ppermute(h, axis, perm)
            return (act, out), None

        out0 = jnp.zeros_like(x_mb)
        (act, out), _ = lax.scan(tick, (zero_act, out0),
                                 jnp.arange(n + m - 1))
        # only the last stage holds real outputs: masked psum replicates
        out = jnp.where(me == n - 1, out, jnp.zeros_like(out))
        out = lax.psum(out, axis)
        # flatten [M, B/M, ...] back to the caller's [B, ...]; derived
        # from the ARGUMENT, not the enclosing x — the compiled closure
        # is cached across calls and must not pin the first call's shape
        return out.reshape((out.shape[0] * out.shape[1],)
                           + out.shape[2:])

    param_specs = jax.tree.map(
        lambda leaf: P(*((axis,) + (None,) * (leaf.ndim - 1))),
        stage_params)
    x_spec = P(*((None,) * x_mb.ndim))
    from multiverso_tpu.utils.jax_compat import shard_map

    def build():
        return shard_map(local, mesh=mesh,
                         in_specs=(param_specs, x_spec),
                         out_specs=P(*((None,) * x.ndim)),
                         check_vma=False)

    # cached profiled wrapper, not a bare eager shard_map call: `local`
    # is rebuilt per call, so without the key-cache every step would be
    # a fresh function to jax (retrace + recompile) and the flight
    # recorder could never attribute compile time to the schedule. The
    # key is exactly what the closure + specs capture; jit's own cache
    # handles shape changes under the same key.
    from multiverso_tpu.telemetry.profiling import cached_profiled_jit
    fn = cached_profiled_jit(
        ("pipeline_apply", stage_fn, mesh, axis, n, m,
         jax.tree.structure(stage_params),
         tuple(leaf.ndim for leaf in leaves), x.ndim),
        "parallel.pipeline_apply", build)
    return fn(stage_params, x_mb)


def sequential_oracle(stage_params: Any, x: jax.Array,
                      stage_fn: Callable[[Any, jax.Array], jax.Array]
                      ) -> jax.Array:
    """Single-device reference: apply the stages in order (tests)."""
    n = jax.tree.leaves(stage_params)[0].shape[0]
    h = x
    for s in range(n):
        params_s = jax.tree.map(lambda a, s=s: a[s], stage_params)
        h = stage_fn(params_s, h)
    return h
