"""Ring attention + Ulysses-style all-to-all sequence parallelism.

Long-context attention where the sequence axis is sharded over mesh
devices (SURVEY.md §6.7's "idiomatic TPU path: shard_map + ppermute
ring over the sequence axis"):

- :func:`ring_attention` — blockwise ring attention: every device holds
  its Q/K/V sequence block; K/V blocks rotate around the ring
  (``lax.ppermute`` over ICI) while each device streams them through an
  online-softmax accumulator (flash-attention style max/sum carries, so
  the full [S, S] score matrix never exists anywhere). Communication
  per step is one K/V block; compute overlaps the next permute under
  XLA's latency-hiding scheduler.
- :func:`ulysses_attention` — the all-to-all alternative: reshard
  [S/p, H] -> [S, H/p] with ``lax.all_to_all``, run plain full-sequence
  attention per head group, reshard back. Cheaper at moderate S with
  enough heads; ring wins when S is the long axis.

Both take GLOBAL arrays ``[batch, seq, heads, dim]`` with the sequence
axis sharded over the given mesh axis, run under ``shard_map``, and
return the same global layout — drop-in for a dense attention call.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from multiverso_tpu import core

NEG_INF = -1e30


def _block_attn(q, k, v, *, scale, causal, q_off, k_off):
    """Scores of one (q-block, k-block) pair + streaming-softmax stats.

    q/k/v [B, s, H, D] -> (o [B, s, H, D] unnormalized, m [B, s, H] row
    max, l [B, s, H] row expsum). q_off/k_off are the blocks' global
    sequence offsets (traced scalars) for causal masking.
    """
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale     # [B, sq, H, sk]
    if causal:
        qi = q_off + jnp.arange(q.shape[1])[:, None, None]
        ki = k_off + jnp.arange(k.shape[1])[None, None, :]
        s = jnp.where(qi >= ki, s, NEG_INF)
    m = s.max(axis=-1)                                  # [B, sq, H]
    p = jnp.exp(s - m[..., None])
    # fully masked rows: exp(NEG_INF - NEG_INF) = 1 -> zero them
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = p.sum(axis=-1)
    o = jnp.einsum("bqhk,bkhd->bqhd", p, v)
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two streaming-softmax partials (associative)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(jnp.maximum(m1 - m, NEG_INF))
    a2 = jnp.exp(jnp.maximum(m2 - m, NEG_INF))
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Optional[Mesh] = None,
                   axis: str = core.DATA_AXIS,
                   causal: bool = False) -> jax.Array:
    """Sequence-parallel attention over a device ring.

    Args:
      q, k, v: [batch, seq, heads, dim]; ``seq`` must divide evenly over
        the mesh ``axis``.
      mesh: defaults to the runtime mesh.
      axis: mesh axis carrying the sequence shards (the ring).
      causal: standard causal masking in GLOBAL sequence positions.

    Returns [batch, seq, heads, dim], sharded like q.
    """
    mesh = mesh if mesh is not None else core.mesh()
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(f"seq {q.shape[1]} not divisible by mesh axis "
                         f"{axis} size {n}")
    scale = 1.0 / np.sqrt(q.shape[-1])
    s_blk = q.shape[1] // n

    def local(q, k, v):
        # q/k/v [B, s_blk, H, D] — this device's sequence block
        me = lax.axis_index(axis)
        q_off = me * s_blk

        # carry: rotating k/v block and the streaming accumulator
        # (o, m, l) per q row
        def attend(i, kb, vb, acc):
            owner = (me + i) % n         # whose block we hold at step i
            o, m, l = _block_attn(q, kb, vb, scale=scale, causal=causal,
                                  q_off=q_off, k_off=owner * s_blk)
            return _merge(*acc, o, m, l)

        def body(i, carry):
            kb, vb, *acc = carry
            acc = attend(i, kb, vb, acc)
            # pass our current block to the left neighbor (ring shift)
            perm = [(j, (j - 1) % n) for j in range(n)]
            kb = lax.ppermute(kb, axis, perm)
            vb = lax.ppermute(vb, axis, perm)
            return (kb, vb, *acc)

        B, s, H, D = q.shape
        init = (k, v,
                jnp.zeros((B, s, H, D), jnp.float32),
                jnp.full((B, s, H), NEG_INF, jnp.float32),
                jnp.zeros((B, s, H), jnp.float32))
        # n-1 rotated steps; the last block attends WITHOUT the final
        # rotation (its result would be discarded — dead ICI traffic)
        kb, vb, *acc = lax.fori_loop(0, n - 1, body, init)
        o, m, l = attend(n - 1, kb, vb, acc)
        return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    spec = P(None, axis, None, None)
    from multiverso_tpu.utils.jax_compat import shard_map
    from multiverso_tpu.telemetry.profiling import cached_profiled_jit
    # keyed on everything `local` closes over (+ mesh for shard_map):
    # same ring program → same profiled wrapper → one compile, one
    # profile.* series (see cached_profiled_jit)
    fn = cached_profiled_jit(
        ("ring_attention", mesh, axis, causal, n, s_blk, scale),
        "parallel.ring_attention",
        lambda: shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_vma=False))
    return fn(q, k, v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mesh: Optional[Mesh] = None,
                      axis: str = core.DATA_AXIS,
                      causal: bool = False) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses shape): trade
    the sequence shard for a head shard, attend over the FULL sequence
    per local head group, trade back. ``heads`` must divide over the
    mesh axis."""
    mesh = mesh if mesh is not None else core.mesh()
    n = mesh.shape[axis]
    if q.shape[1] % n or q.shape[2] % n:
        raise ValueError(f"seq {q.shape[1]} and heads {q.shape[2]} must "
                         f"divide mesh axis {axis} size {n}")
    scale = 1.0 / np.sqrt(q.shape[-1])

    def local(q, k, v):
        # [B, s_blk, H, D] -> all_to_all -> [B, S, H/n, D]
        def fwd(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def bwd(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qf, kf, vf = fwd(q), fwd(k), fwd(v)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
        if causal:
            qi = jnp.arange(s.shape[2])[:, None]
            ki = jnp.arange(s.shape[3])[None, :]
            s = jnp.where(qi >= ki, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)
        return bwd(o)

    spec = P(None, axis, None, None)
    from multiverso_tpu.utils.jax_compat import shard_map
    from multiverso_tpu.telemetry.profiling import cached_profiled_jit
    fn = cached_profiled_jit(
        ("ulysses_attention", mesh, axis, causal, n, scale),
        "parallel.ulysses_attention",
        lambda: shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_vma=False))
    return fn(q, k, v)
