"""Multi-host helpers shared by the per-process data-shard modes
(lightlda ``local_corpus``, word2vec ``local_data``).

``jax.experimental.multihost_utils.process_allgather`` canonicalizes
int64 down to int32 when ``jax_enable_x64`` is off (the default), so
counts past 2^31 would silently wrap — :func:`allgather_i64` ships the
two 32-bit halves instead.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def allgather_i64(vals) -> np.ndarray:
    """process_allgather of an int64 vector without x64 truncation.
    Returns [P, n] int64 (single-process: [1, n])."""
    import jax
    from multiverso_tpu.ft.chaos import chaos_point
    chaos_point("multihost.allgather")
    v = np.atleast_1d(np.asarray(vals, np.int64))
    if jax.process_count() == 1:
        return v[None]
    from jax.experimental import multihost_utils
    hi = (v >> np.int64(32)).astype(np.int32)
    lo = (v & np.int64(0xFFFFFFFF)).astype(np.int32)
    g = np.asarray(multihost_utils.process_allgather(
        np.stack([hi, lo])))                        # [P, 2, n] int32
    return (g[:, 0].astype(np.int64) << np.int64(32)) \
        | (g[:, 1].astype(np.int64) & np.int64(0xFFFFFFFF))


def allgather_bytes(payload: bytes) -> List[bytes]:
    """process_allgather of an arbitrary byte string: every process
    passes its own payload, every process receives all P payloads in
    rank order. Lengths travel first (x64-safe via allgather_i64), then
    the payloads padded to the max length as uint8. Single-process:
    ``[payload]`` with no collective dispatched.

    COLLECTIVE — all processes must call in lockstep. Used by
    :func:`multiverso_tpu.telemetry.aggregate.gather_metrics` to ship
    per-host registry snapshots."""
    import jax
    payload = bytes(payload)
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils
    lens = allgather_i64(np.array([len(payload)], np.int64))[:, 0]
    mx = int(lens.max())
    buf = np.zeros(max(mx, 1), np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    g = np.asarray(multihost_utils.process_allgather(buf))  # [P, mx]
    return [g[i, :int(n)].tobytes() for i, n in enumerate(lens)]


def validate_single_owner(mask: np.ndarray, what: str) -> None:
    """Every lane owned by exactly one process, or raise. ``mask`` is
    this process's 0/1 ownership vector over the lane space."""
    import jax
    if jax.process_count() == 1:
        if not np.all(mask == 1):
            raise ValueError(
                f"{what}: single process must own every lane")
        return
    from jax.experimental import multihost_utils
    owners = np.asarray(multihost_utils.process_allgather(
        mask.astype(np.int32))).sum(axis=0)
    if not np.all(owners == 1):
        raise ValueError(
            f"{what} requires every data lane to be owned by exactly "
            f"one process (got per-lane owner counts "
            f"{sorted(set(owners.tolist()))}); shard the mesh's data "
            "axis across processes")


def owned_axis_slices(sharding, shape: Tuple[int, ...],
                      axis: int) -> List[Tuple[object, int, int]]:
    """[(device, lo, hi)] — every addressable device's chunk of ``axis``
    under ``sharding`` (None-start/stop normalized)."""
    imap = sharding.devices_indices_map(shape)
    out = []
    for d in sharding.addressable_devices:
        sl = imap[d][axis]
        lo = 0 if sl.start is None else sl.start
        hi = shape[axis] if sl.stop is None else sl.stop
        out.append((d, lo, hi))
    return out
