"""Sequence/context parallelism primitives (beyond-parity extension).

The reference predates transformers — SURVEY.md §6.7 records
sequence/context parallelism as ABSENT there and out of scope for
parity. This package is the framework's forward-looking long-context
layer, built the idiomatic TPU way that §6.7 names: ``shard_map`` over
the mesh + ``ppermute`` ring / ``all_to_all`` resharding, so attention
over sequences longer than one chip's memory rides ICI.
"""

from multiverso_tpu.parallel.ring_attention import (ring_attention,
                                                    ulysses_attention)

__all__ = ["ring_attention", "ulysses_attention"]
