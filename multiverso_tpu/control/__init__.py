"""multiverso_tpu.control — the knob registry and the closed-loop
autotuner built on top of the observability plane.

``knobs`` is the typed knob table (every runtime tunable, env-seeded,
weakref-bound to the live objects whose hot paths read it);
``controller`` is the control loop that moves those knobs from live
telemetry — per-process off the registry snapshot, fleet-wide off the
merged ``/metrics?json=1`` scrape — with hysteresis, rate-limited
steps, a kill switch, and a ``control.decision`` audit span per move.

Importing this package pulls both modules: any process that
constructs a server (and therefore binds knobs) also has the
``/control`` actuation surface loaded, which ``telemetry/statusz``
resolves strictly through ``sys.modules`` to stay jax-free.
"""

from multiverso_tpu.control import knobs
from multiverso_tpu.control import controller
from multiverso_tpu.control.controller import (
    Controller, FleetController, apply_set, apply_step,
    control_status, disabled, kill, maybe_controller,
    parse_objectives, recent_decisions,
)

__all__ = [
    "Controller", "FleetController", "apply_set", "apply_step",
    "control_status", "controller", "disabled", "kill", "knobs",
    "maybe_controller", "parse_objectives", "recent_decisions",
]
