"""The typed knob table: every runtime-tunable constant behind one
registry.

Before this module the tunable surface was scattered one-shot
``os.environ`` reads — ``table_server.py`` read ``MVTPU_SERVER_FUSE``
once at construction, ``admission.py`` read ``MVTPU_SERVER_QUEUE``,
``storage/manager.py`` read ``MVTPU_TIER_DEVICE_BUCKETS``, and so on.
Each value was frozen for the life of the process, which is exactly
wrong for the workloads the fleet is built for: preemptions, phase
changes, and floods all move the optimum mid-run.

Here every knob gets one :class:`Knob` spec — name, seeding env var,
bounds, a rate-limit step, the owner subsystem — and owners register
live *bindings* (``weakref`` to the owning object plus the attribute
the hot path reads). Actuation is then a clamped ``setattr`` on every
live binding: the dispatch loop re-reads ``self._fuse`` per cycle, the
admission buckets re-read ``klass.rate`` per offer, so a binding write
takes effect on the very next operation with no locks added to any hot
path.

Env vars remain the *initial* values — :func:`initial` is the one
sanctioned way to read them, so construction-time behaviour is
unchanged when no controller ever runs. The controller
(``control/controller.py``) moves knobs only through :func:`step`,
which enforces the per-decision rate limit.

jax-free by construction: stdlib only.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple


class Knob:
    """One tunable: identity, seeding env var, bounds, step policy.

    ``step`` is the per-decision rate limit: additive for
    ``mode="add"`` knobs, a multiplicative factor for ``mode="mul"``
    knobs (token rates span orders of magnitude; counts do not).
    ``step == 0`` marks an *initial-only* knob — documented and
    env-seeded through this table but not actuatable at runtime.
    """

    __slots__ = ("name", "env", "kind", "default", "lo", "hi", "step",
                 "mode", "owner", "doc")

    def __init__(self, name: str, *, env: Optional[str], kind: str,
                 default: float, lo: float, hi: float, step: float,
                 mode: str = "add", owner: str, doc: str) -> None:
        assert kind in ("int", "float") and mode in ("add", "mul")
        self.name = name
        self.env = env
        self.kind = kind
        self.default = default
        self.lo = lo
        self.hi = hi
        self.step = step
        self.mode = mode
        self.owner = owner
        self.doc = doc

    def clamp(self, value: float) -> Any:
        v = min(max(float(value), self.lo), self.hi)
        return int(v) if self.kind == "int" else float(v)

    def stepped(self, value: float, direction: int) -> Any:
        """One rate-limited move from ``value`` in ``direction``."""
        v = float(value)
        if self.mode == "mul":
            # a multiplicative knob stuck at 0 can never move; step
            # off the floor additively first
            if v <= 0:
                v = self.step if direction > 0 else 0.0
            else:
                v = v * self.step if direction > 0 else v / self.step
        else:
            v = v + self.step if direction > 0 else v - self.step
        return self.clamp(v)


def _spec(*args, **kw) -> Knob:
    return Knob(*args, **kw)


#: The knob surface. Actuatable knobs bind live objects; step=0 rows
#: exist so *every* env-seeded tunable flows through one table (and so
#: the README lint check has a single source of truth to point at).
SPECS: Dict[str, Knob] = {k.name: k for k in (
    _spec("server.fuse", env="MVTPU_SERVER_FUSE", kind="int",
          default=1, lo=1, hi=64, step=2, owner="server",
          doc="dispatch-loop request fusion depth"),
    _spec("server.queue_bound", env="MVTPU_SERVER_QUEUE", kind="int",
          default=0, lo=0, hi=1 << 16, step=64, owner="server",
          doc="admission dispatch-queue bound (0 = unbounded)"),
    _spec("server.qos.rate", env=None, kind="float",
          default=0.0, lo=0.0, hi=1e9, step=2.0, mode="mul",
          owner="server",
          doc="per-QoS-class token rate, ops/s (0 = unlimited)"),
    _spec("server.qos.weight", env=None, kind="float",
          default=1.0, lo=1.0, hi=64.0, step=1.0, owner="server",
          doc="per-QoS-class WFQ weight"),
    _spec("server.replica.slack", env="MVTPU_REPLICA_SLACK",
          kind="int", default=0, lo=0, hi=1024, step=1,
          owner="server",
          doc="extra generations a replica may serve past the "
              "client-requested staleness bound"),
    _spec("server.repl.slack", env="MVTPU_REPL_SLACK",
          kind="int", default=0, lo=0, hi=1 << 20, step=1,
          owner="server",
          doc="extra generations a cross-process FOLLOWER read may "
              "lag past the client bound before it bounces to the "
              "primary"),
    _spec("server.migrate.rate", env="MVTPU_MIGRATE_RATE",
          kind="float", default=0.0, lo=0.0, hi=1e6, step=2.0,
          mode="mul", owner="server",
          doc="reshard donor stream rate, chunks/s (0 = unthrottled) "
              "— the autotuner's reshard-speed vs serving-p999 "
              "lever"),
    _spec("client.staleness", env="MVTPU_STALENESS", kind="int",
          default=0, lo=0, hi=1024, step=1, owner="client",
          doc="cached-view max staleness, generations"),
    _spec("client.coalesce_k", env="MVTPU_COALESCE", kind="int",
          default=1, lo=1, hi=256, step=2, owner="client",
          doc="client delta-coalescing depth K"),
    _spec("storage.device_buckets", env="MVTPU_TIER_DEVICE_BUCKETS",
          kind="int", default=0, lo=1, hi=1 << 20, step=4,
          owner="storage",
          doc="tiered-KV device-resident bucket budget"),
    # initial-only rows (step=0): env-seeded here, never actuated —
    # resizing them live would mean reallocating wire dedup rings or
    # exemplar reservoirs under traffic
    _spec("server.dedup", env="MVTPU_WIRE_DEDUP", kind="int",
          default=128, lo=1, hi=1 << 16, step=0, owner="server",
          doc="wire dedup replay-cache depth (initial-only)"),
    _spec("server.dedup_clients", env="MVTPU_WIRE_DEDUP_CLIENTS",
          kind="int", default=1024, lo=1, hi=1 << 20, step=0,
          owner="server",
          doc="wire dedup per-client cache cap (initial-only)"),
    _spec("server.exemplars", env="MVTPU_SERVER_EXEMPLARS",
          kind="int", default=8, lo=1, hi=1 << 12, step=0,
          owner="server",
          doc="slow-request exemplar ring depth (initial-only)"),
    _spec("storage.host_buckets", env="MVTPU_TIER_HOST_BUCKETS",
          kind="int", default=0, lo=0, hi=1 << 20, step=0,
          owner="storage",
          doc="tiered-KV host-tier bucket count (initial-only)"),
    _spec("telemetry.ts_every", env="MVTPU_TS_EVERY", kind="float",
          default=1.0, lo=0.0, hi=3600.0, step=0, owner="telemetry",
          doc="time-series sampler cadence, seconds (0 = off; unset "
              "= on once statusz arms; initial-only)"),
    _spec("attribution.topk_k", env="MVTPU_TOPK_K", kind="int",
          default=32, lo=0, hi=4096, step=0, owner="telemetry",
          doc="heavy-hitter sketch capacity K (0 kills the "
              "attribution plane; initial-only)"),
    _spec("attribution.heat_buckets", env="MVTPU_TOPK_HEAT",
          kind="int", default=16, lo=1, hi=4096, step=0,
          owner="telemetry",
          doc="per-table range-heat buckets (initial-only)"),
)}


_LOCK = threading.Lock()
#: knob name -> [(label, weakref-to-owner, attr)]
_BINDINGS: Dict[str, List[Tuple[str, "weakref.ref", str]]] = {}


def spec(name: str) -> Knob:
    try:
        return SPECS[name]
    except KeyError:
        raise KeyError(f"unknown knob {name!r} "
                       f"(known: {sorted(SPECS)})") from None


def specs() -> List[Knob]:
    return list(SPECS.values())


def initial(name: str, default: Optional[float] = None) -> Any:
    """The knob's starting value: its env var if set (parsed and
    clamped), else ``default`` when given, else the spec default. The
    one sanctioned env read for every tunable."""
    k = spec(name)
    fallback = k.default if default is None else default
    raw = os.environ.get(k.env) if k.env else None
    if raw is None or not raw.strip():
        return k.clamp(fallback)
    try:
        v = float(raw) if k.kind == "float" else int(raw)
    except ValueError:
        raise ValueError(
            f"{k.env}={raw!r} is not a valid {k.kind} "
            f"for knob {name!r}") from None
    return k.clamp(v)


def env_raw(name: str) -> Optional[str]:
    """The knob's env var, unparsed (None when it has no env var or
    the var is unset) — for callers whose unset/zero semantics differ
    from the knob's clamped range (e.g. ``MVTPU_COALESCE=0`` means
    *off*, not *K=1*)."""
    k = spec(name)
    return os.environ.get(k.env) if k.env else None


def bind(name: str, owner: Any, attr: str, *, label: str) -> None:
    """Register a live binding: future :func:`set`/:func:`step` calls
    on ``name`` write ``owner.<attr>``. Weakly referenced — a dead
    owner silently drops out, so short-lived tables and test servers
    need no unbind ceremony."""
    k = spec(name)
    if k.step == 0:
        raise ValueError(f"knob {name!r} is initial-only")
    if not hasattr(owner, attr):
        raise AttributeError(f"knob {name!r}: owner has no {attr!r}")
    ref = weakref.ref(owner)
    with _LOCK:
        rows = _BINDINGS.setdefault(name, [])
        rows[:] = [(l, r, a) for (l, r, a) in rows
                   if r() is not None and not (l == label and a == attr)]
        rows.append((label, ref, attr))


def _live(name: str) -> List[Tuple[str, Any, str]]:
    with _LOCK:
        rows = _BINDINGS.get(name, [])
        rows[:] = [row for row in rows if row[1]() is not None]
        return [(l, r(), a) for (l, r, a) in rows if r() is not None]


def set(name: str, value: float, *,
        label: Optional[str] = None) -> List[Tuple[str, Any, Any]]:
    """Clamp ``value`` and write every live binding (or just
    ``label``'s). Returns ``[(label, from, to)]`` for bindings that
    actually moved — the controller's audit trail is built from it."""
    k = spec(name)
    v = k.clamp(value)
    changed = []
    for l, owner, attr in _live(name):
        if label is not None and l != label:
            continue
        frm = getattr(owner, attr)
        if frm == v:
            continue
        setattr(owner, attr, v)
        changed.append((l, frm, v))
    return changed


def step(name: str, direction: int, *,
         label: Optional[str] = None) -> List[Tuple[str, Any, Any]]:
    """One rate-limited move per live binding: each binding steps from
    its OWN current value, clamped to the knob's bounds. Returns
    ``[(label, from, to)]`` for bindings that moved."""
    k = spec(name)
    changed = []
    for l, owner, attr in _live(name):
        if label is not None and l != label:
            continue
        frm = getattr(owner, attr)
        to = k.stepped(frm, 1 if direction > 0 else -1)
        if frm == to:
            continue
        setattr(owner, attr, to)
        changed.append((l, frm, to))
    return changed


def current() -> Dict[str, Dict[str, Any]]:
    """Live knob values, ``{knob: {label: value}}`` — the
    ``/statusz`` control section's knob table."""
    out: Dict[str, Dict[str, Any]] = {}
    for name in SPECS:
        vals = {l: getattr(o, a) for l, o, a in _live(name)}
        if vals:
            out[name] = vals
    return out
