"""Closed-loop autotuning: the observability plane becomes the
control plane.

The fleet already *measures* every tradeoff it exposes — merged
metrics snapshots, clock-aligned distributed traces, slow-request
exemplars — while the knobs those metrics grade stayed static env
config. This module closes the loop: a per-process
:class:`Controller` thread (armed by ``MVTPU_AUTOTUNE``) evaluates
*objectives* against the live registry snapshot and moves knobs
through ``control/knobs.py``; a :class:`FleetController` runs the same
state machine over the merged ``/metrics?json=1`` scrape of a whole
fleet and actuates members through their ``/control`` POST endpoint.

Objective grammar — the ``MVTPU_SLO`` rule grammar with an action
suffix, semicolon-separated::

    MVTPU_AUTOTUNE="server.wire.latency.p99 < 5ms -> server.fuse+,
                    server.qos.rate+; storage.miss_ratio < 0.05 ->
                    storage.device_buckets+"

The rule half is parsed by ``telemetry.slo.parse_rule`` when it names
a histogram statistic; names that grammar rejects fall through to
:class:`DerivedRule` — counter-derived ratios (``storage.miss_ratio``,
``server.shed_ratio``) or any gauge/counter by exact name. The action
half is ``<knob>+`` / ``<knob>-``: while the rule is violated, move
that knob one rate-limited step in that direction.

Stability over speed, by construction:

- **hysteresis** — a violation must persist ``confirm`` consecutive
  evaluations before anything moves (one noisy sample crossing the
  boundary does nothing), and
- **cooldown** — after a move the objective holds for ``hold``
  evaluations so the change can show up in the metrics it is judged
  by. Step sizes are clamped by the knob table. The controller never
  oscillates on a noisy boundary; it ratchets.

Kill switch, twice over: ``MVTPU_AUTOTUNE=0`` refuses arming AND
vetoes every ``apply_*`` (so a fleet controller cannot push knobs into
an opted-out process), and a ``/control`` POST ``{"op": "kill"}``
flips the process-wide :func:`kill` latch.

Every decision is an audit span —
``control.decision{knob, from, to, rule, evidence}`` — parent-linked
into the trace plane (fleet-driven decisions adopt the remote ctx
shipped in the POST, so a tuning episode reads as ONE tree across
processes in ``report --fleet``), mirrored into a decision ring served
by ``/statusz`` and carried by watchdog dumps.

jax-free: stdlib + telemetry only, like the rest of the
observability plane.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from multiverso_tpu.control import knobs
from multiverso_tpu.telemetry import metrics as _metrics
from multiverso_tpu.telemetry import slo as _slo
from multiverso_tpu.telemetry import timeseries as _timeseries
from multiverso_tpu.telemetry import trace as _trace
from multiverso_tpu.utils import log

#: objective spec (arming) OR "0"/"off" (hard kill)
AUTOTUNE_ENV = "MVTPU_AUTOTUNE"
#: evaluation cadence, seconds
EVERY_ENV = "MVTPU_AUTOTUNE_EVERY"

_KILL_VALUES = ("0", "off", "false", "no")
_RING_DEPTH = 64

_LOCK = threading.Lock()
_DECISIONS: deque = deque(maxlen=_RING_DEPTH)
_CONTROLLERS: List["Controller"] = []
_KILLED = False
_KILL_REASON: Optional[str] = None


# -- rules -----------------------------------------------------------------

class DerivedRule:
    """A rule over a value the histogram grammar can't name: a
    counter-derived ratio or a gauge/counter read by exact name.
    Same ``metric < bound`` surface as ``slo.SloRule``."""

    RATIOS = ("storage.miss_ratio", "server.shed_ratio")

    def __init__(self, raw: str, metric: str, bound: float) -> None:
        self.raw = raw
        self.metric = metric
        self.bound_s = float(bound)     # SloRule field name, kept

    def score(self, snap: dict) -> Optional[float]:
        counters = snap.get("counters", {})
        if self.metric == "storage.miss_ratio":
            hits = _sum_named(counters, "storage.hits")
            misses = _sum_named(counters, "storage.misses")
            total = hits + misses
            return misses / total if total > 0 else None
        if self.metric == "server.shed_ratio":
            shed = _sum_named(counters, "server.shed")
            admitted = _sum_named(counters, "server.admission.admitted")
            total = shed + admitted
            return shed / total if total > 0 else None
        for table in (snap.get("gauges", {}), counters):
            vals = [v for k, v in table.items()
                    if k.partition("{")[0] == self.metric]
            if vals:
                return max(float(v) for v in vals)
        return None


def _sum_named(table: Dict[str, float], name: str) -> float:
    return sum(float(v) for k, v in table.items()
               if k.partition("{")[0] == name)


def _parse_bound(raw: str) -> float:
    raw = raw.strip()
    try:
        return float(raw)
    except ValueError:
        return _slo._parse_value(raw)       # "5ms" -> 0.005


class WindowedRule:
    """A rule over the trailing window instead of lifetime totals:
    ``rate(server.ops)@30s < 500`` (windowed counter rate, summed
    across label series) or ``server.latency.p99@30s < 5ms``
    (windowed histogram quantile via interval-delta of bucket counts,
    worst matching series). The rule carries its OWN bounded
    :class:`telemetry.timeseries.SeriesStore` fed by every snapshot
    its controller evaluates — so the same rule object reacts to the
    local registry under a :class:`Controller` and to the MERGED
    fleet snapshot under a :class:`FleetController`, with no global-
    store cross-talk between the two."""

    STATS = ("p50", "p90", "p99", "p999", "mean")

    def __init__(self, raw: str, form: str, metric: str,
                 stat: Optional[str], window_s: float,
                 bound: float) -> None:
        self.raw = raw
        self.form = form            # "rate" | "hist"
        self.metric = metric
        self.stat = stat
        self.window_s = float(window_s)
        self.bound_s = float(bound)     # SloRule field name, kept
        self._store = _timeseries.SeriesStore()

    def observe(self, snap: dict) -> None:
        self._store.sample(snap)

    def score_windowed(self) -> Tuple[Optional[float], Optional[dict]]:
        """(worst windowed value, evidence) from the accumulated
        history; (None, None) until two samples straddle a window."""
        st = self._store
        if self.form == "rate":
            total, found = 0.0, False
            for full in st.keys():
                kind, _, key = full.partition(":")
                if kind != "counter" \
                        or key.partition("{")[0] != self.metric:
                    continue
                r = st.rate(key, self.window_s)
                if r is not None:
                    total += r
                    found = True
            if not found:
                return None, None
            return total, {"metric": self.metric, "stat": "rate",
                           "window_s": self.window_s, "value": total,
                           "bound": self.bound_s}
        worst: Optional[float] = None
        worst_key = None
        for full in st.keys():
            kind, _, key = full.partition(":")
            if kind != "hist" or not _slo._match(self.metric, key):
                continue
            if self.stat == "mean":
                h = st.hist_window(key, self.window_s)
                value = (h["sum"] / h["count"]
                         if h and h["count"] else None)
            else:
                q = int(self.stat[1:]) / 10.0 ** len(self.stat[1:])
                value = st.quantile(key, q, self.window_s)
            if value is None:
                continue
            if worst is None or value > worst:
                worst, worst_key = value, key
        if worst is None:
            return None, None
        return worst, {"metric": worst_key, "stat": self.stat,
                       "window_s": self.window_s, "value": worst,
                       "bound": self.bound_s}


def _parse_windowed(rule_part: str) -> Optional[WindowedRule]:
    """Parse one windowed rule clause, or None when the clause has no
    ``@window`` term (the cumulative grammars take it). A PRESENT
    ``@`` with a malformed window/stat raises — same loud-typo policy
    as the rest of the grammar."""
    metric_part, lt, bound_part = rule_part.partition("<")
    if not lt:
        return None
    term = metric_part.strip()
    name, at, win = term.rpartition("@")
    if not at:
        return None
    name = name.strip()
    try:
        window_s = _slo._parse_value(win.strip())
    except ValueError:
        raise ValueError(f"windowed rule {rule_part!r}: bad window "
                         f"{win.strip()!r} (want e.g. 30s)") from None
    if window_s <= 0:
        raise ValueError(f"windowed rule {rule_part!r}: window must "
                         "be positive")
    bound = _parse_bound(bound_part)
    if name.startswith("rate(") and name.endswith(")"):
        metric = name[5:-1].strip()
        if not metric:
            raise ValueError(
                f"windowed rule {rule_part!r}: empty rate() metric")
        return WindowedRule(rule_part, "rate", metric, None,
                            window_s, bound)
    metric, dot, stat = name.rpartition(".")
    if not dot or stat not in WindowedRule.STATS:
        raise ValueError(
            f"windowed rule {rule_part!r}: expected "
            "'rate(<counter>)@<win>' or "
            f"'<hist>.<{'|'.join(WindowedRule.STATS)}>@<win>'")
    return WindowedRule(rule_part, "hist", metric, stat, window_s,
                        bound)


class Objective:
    """One parsed ``rule -> actions`` clause."""

    def __init__(self, raw: str, rule: Any,
                 actions: List[Tuple[str, int]]) -> None:
        self.raw = raw
        self.rule = rule
        self.actions = actions      # [(knob name, +1|-1)]

    def evaluate(self, snap: dict) -> Tuple[bool, Optional[dict]]:
        """(violated, evidence) against one registry snapshot. For
        histogram rules the evidence names the worst-scoring series,
        mirroring ``SloMonitor.check_once``."""
        if isinstance(self.rule, WindowedRule):
            self.rule.observe(snap)
            value, evidence = self.rule.score_windowed()
            if value is None or value <= self.rule.bound_s:
                return False, None
            return True, evidence
        if isinstance(self.rule, DerivedRule):
            value = self.rule.score(snap)
            if value is None or value <= self.rule.bound_s:
                return False, None
            return True, {"metric": self.rule.metric, "value": value,
                          "bound": self.rule.bound_s}
        worst = None
        for key, hist in snap.get("histograms", {}).items():
            if not _slo._match(self.rule.metric, key):
                continue
            value = self.rule.score(hist)
            if value is None or value <= self.rule.bound_s:
                continue
            if worst is None or value > worst["value"]:
                worst = {"metric": key, "stat": self.rule.stat,
                         "value": value, "bound": self.rule.bound_s}
        return worst is not None, worst


def parse_objectives(spec: str) -> List[Objective]:
    """``MVTPU_AUTOTUNE`` grammar: semicolon-separated
    ``<rule> -> <knob>+[, <knob>-]`` clauses. Raises ``ValueError``
    on malformed specs — a controller armed with a typo is worse than
    no controller."""
    out: List[Objective] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        rule_part, sep, action_part = clause.partition("->")
        if not sep or not action_part.strip():
            raise ValueError(
                f"objective {clause!r}: expected '<rule> -> <knob>+'")
        rule_part = rule_part.strip()
        # windowed terms first: an '@window' suffix means "react to
        # the trailing window, not lifetime totals"
        rule: Any = _parse_windowed(rule_part)
        if rule is None:
            try:
                rule = _slo.parse_rule(rule_part)
            except ValueError:
                # not a histogram statistic — a derived ratio or a
                # plain gauge/counter name
                metric, lt, bound = rule_part.partition("<")
                if not lt:
                    raise ValueError(
                        f"objective rule {rule_part!r}: expected "
                        "'<metric> < <bound>'") from None
                rule = DerivedRule(rule_part, metric.strip(),
                                   _parse_bound(bound))
        actions: List[Tuple[str, int]] = []
        for item in action_part.split(","):
            item = item.strip()
            if not item:
                continue
            if item[-1] not in "+-":
                raise ValueError(
                    f"objective action {item!r}: expected "
                    "'<knob>+' or '<knob>-'")
            name = item[:-1].strip()
            try:
                knobs.spec(name)
            except KeyError as e:
                raise ValueError(str(e)) from None
            if knobs.spec(name).step == 0:
                raise ValueError(
                    f"objective action {item!r}: knob is initial-only")
            actions.append((name, 1 if item[-1] == "+" else -1))
        if not actions:
            raise ValueError(f"objective {clause!r}: no actions")
        out.append(Objective(clause, rule, actions))
    return out


# -- kill switch -----------------------------------------------------------

def disabled() -> bool:
    """True when autotuning is vetoed — by ``MVTPU_AUTOTUNE=0`` in the
    environment or by a :func:`kill` latch. Checked on every apply, so
    the env veto also blocks fleet-pushed actuation."""
    if _KILLED:
        return True
    raw = os.environ.get(AUTOTUNE_ENV, "").strip().lower()
    return raw in _KILL_VALUES


def kill(reason: str = "kill") -> None:
    """Hard kill: latch the process-wide veto, stop every controller
    thread, and ring the event so the audit trail records WHY tuning
    stopped."""
    global _KILLED, _KILL_REASON
    _KILLED = True
    _KILL_REASON = reason
    with _LOCK:
        ctls = list(_CONTROLLERS)
    for c in ctls:
        c.stop()
    _ring({"ts": time.time(), "op": "kill", "reason": reason})
    log.info(f"control: autotune killed ({reason})")


def _ring(entry: dict) -> None:
    with _LOCK:
        _DECISIONS.append(entry)


# -- actuation choke point -------------------------------------------------

def _record(changes: List[Tuple[str, Any, Any]], *, knob: str,
            rule: str, evidence: Optional[dict], origin: str,
            ctx: Optional[dict] = None) -> List[dict]:
    """Every knob move funnels through here: ring entry + counter +
    ``control.decision`` audit span per changed binding. ``ctx`` is a
    remote trace context (fleet POST) — adopting it parent-links the
    local decision span under the fleet controller's retune span."""
    out: List[dict] = []
    ts = time.time()
    for label, frm, to in changes:
        decision = {"ts": ts, "op": "set", "knob": knob,
                    "label": label, "from": frm, "to": to,
                    "rule": rule, "evidence": evidence,
                    "origin": origin}
        _ring(decision)
        out.append(decision)
        _metrics.counter("control.decisions", knob=knob).inc()
        with _trace.adopt_remote(ctx):
            _trace.emit_span(
                "control.decision", ts, 0.0,
                **{"knob": knob, "label": label, "from": frm,
                   "to": to, "rule": rule,
                   "evidence": json.dumps(evidence)
                   if evidence else "", "origin": origin})
        log.info(f"control: {knob}[{label}] {frm} -> {to} "
            f"({origin}; rule {rule!r})")
    return out


def apply_step(knob: str, direction: int, *,
               label: Optional[str] = None, rule: str = "",
               evidence: Optional[dict] = None, origin: str = "local",
               ctx: Optional[dict] = None) -> List[dict]:
    """One rate-limited move on every live binding of ``knob`` (or
    just ``label``'s). Refused outright when killed."""
    if disabled():
        return []
    return _record(knobs.step(knob, direction, label=label),
                   knob=knob, rule=rule, evidence=evidence,
                   origin=origin, ctx=ctx)


def apply_set(knob: str, value: float, *,
              label: Optional[str] = None, rule: str = "",
              evidence: Optional[dict] = None, origin: str = "local",
              ctx: Optional[dict] = None) -> List[dict]:
    """Absolute (still clamped) actuation — the ``/control`` POST
    surface for operators. Refused outright when killed."""
    if disabled():
        return []
    return _record(knobs.set(knob, value, label=label),
                   knob=knob, rule=rule, evidence=evidence,
                   origin=origin, ctx=ctx)


def recent_decisions(limit: int = _RING_DEPTH) -> List[dict]:
    with _LOCK:
        return list(_DECISIONS)[-limit:]


def control_status(limit: int = 16) -> dict:
    """The ``/statusz`` control section: armed objectives, live knob
    values, last N decisions with evidence."""
    with _LOCK:
        ctls = list(_CONTROLLERS)
    return {
        "enabled": bool(ctls) and not disabled(),
        "killed": _KILLED,
        "kill_reason": _KILL_REASON,
        "objectives": [o.raw for c in ctls for o in c.objectives],
        "knobs": knobs.current(),
        "decisions": recent_decisions(limit),
    }


# -- the state machine -----------------------------------------------------

class _ObjectiveState:
    __slots__ = ("obj", "streak", "hold_left")

    def __init__(self, obj: Objective) -> None:
        self.obj = obj
        self.streak = 0
        self.hold_left = 0


def _tick(states: List[_ObjectiveState], snap: dict, *, confirm: int,
          hold: int, actuate: Callable[..., List[dict]]) -> List[dict]:
    """One evaluation pass shared by the local and fleet controllers:
    confirm-streak hysteresis in, cooldown hold out, ``actuate`` is
    the only side effect."""
    decisions: List[dict] = []
    for st in states:
        if st.hold_left > 0:
            # cooldown: the last move hasn't had time to show up in
            # the metrics judging it — don't stack another on top
            st.hold_left -= 1
            continue
        violated, evidence = st.obj.evaluate(snap)
        if not violated:
            st.streak = 0
            continue
        st.streak += 1
        if st.streak < confirm:
            continue
        st.streak = 0
        st.hold_left = hold
        for name, direction in st.obj.actions:
            decisions.extend(actuate(name, direction,
                                     rule=st.obj.raw,
                                     evidence=evidence))
    return decisions


class Controller:
    """The per-process control loop: evaluate objectives against the
    local registry snapshot on cadence, actuate through the knob
    table. ``source`` (tests) replaces the registry snapshot."""

    def __init__(self, objectives: List[Objective], *,
                 every_s: float = 1.0, confirm: int = 2,
                 hold: int = 2,
                 source: Optional[Callable[[], dict]] = None) -> None:
        self.objectives = list(objectives)
        self.every_s = float(every_s)
        self.confirm = max(int(confirm), 1)
        self.hold = max(int(hold), 0)
        self._source = source
        self._states = [_ObjectiveState(o) for o in self.objectives]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self) -> List[dict]:
        if disabled():
            return []
        snap = (self._source() if self._source
                else _metrics.registry().snapshot())
        return _tick(self._states, snap, confirm=self.confirm,
                     hold=self.hold, actuate=apply_step)

    def start(self) -> "Controller":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="mvtpu-control", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.every_s):
            try:
                self.check_once()
            except Exception as e:     # never kill the loop on noise
                log.info(f"control: check failed: {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def maybe_controller() -> Optional[Controller]:
    """Arm the per-process controller from ``MVTPU_AUTOTUNE`` (no-op
    when unset, killed, or already armed) — ``core.init``'s
    observability hook, beside ``maybe_statusz`` and
    ``maybe_slo_monitor``."""
    spec = os.environ.get(AUTOTUNE_ENV, "").strip()
    if not spec or disabled():
        return None
    with _LOCK:
        if _CONTROLLERS:
            return _CONTROLLERS[0]
    try:
        objectives = parse_objectives(spec)
    except ValueError as e:
        log.info(f"control: bad {AUTOTUNE_ENV}: {e}")
        return None
    if not objectives:
        return None
    every = float(os.environ.get(EVERY_ENV, "") or 1.0)
    ctl = Controller(objectives, every_s=every).start()
    with _LOCK:
        _CONTROLLERS.append(ctl)
    log.info(f"control: autotune armed ({len(objectives)} objective(s), "
        f"every {every:g}s)")
    return ctl


def shutdown_controllers() -> None:
    """Stop controller threads without latching the kill veto (test
    teardown; ``kill`` is the operator path)."""
    with _LOCK:
        ctls = list(_CONTROLLERS)
        _CONTROLLERS.clear()
    for c in ctls:
        c.stop()


# -- fleet control loop ----------------------------------------------------

class FleetController:
    """The fleet-level loop: scrape every member's
    ``/metrics?json=1`` (the PR 9 fleet-file contract), evaluate
    objectives against the MERGED snapshot, and actuate by POSTing
    ``/control`` steps to every member — each POST carries this
    process's trace context, so members' ``control.decision`` spans
    parent-link under one ``control.retune`` root and the episode
    merges into a single tree in ``report --fleet``."""

    def __init__(self, fleet_file: str, objectives: List[Objective],
                 *, every_s: float = 2.0, confirm: int = 2,
                 hold: int = 2, timeout: float = 5.0) -> None:
        self.fleet_file = fleet_file
        self.objectives = list(objectives)
        self.every_s = float(every_s)
        self.confirm = max(int(confirm), 1)
        self.hold = max(int(hold), 0)
        self.timeout = float(timeout)
        self._states = [_ObjectiveState(o) for o in self.objectives]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _ports(self) -> List[int]:
        from multiverso_tpu.server import partition
        doc = partition.read_fleet_file(self.fleet_file)
        if doc is None:
            raise ValueError(f"not a fleet file: {self.fleet_file}")
        return [m["statusz_port"] for m in doc.get("members", [])
                if m.get("statusz_port")]

    def _scrape(self, ports: List[int]) -> Optional[dict]:
        import urllib.request
        from multiverso_tpu.telemetry import aggregate
        snaps = []
        for port in ports:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics?json=1",
                        timeout=self.timeout) as resp:
                    snap = json.loads(resp.read())
            except (OSError, ValueError) as e:
                log.info(f"control: fleet scrape port={port} failed: {e!r}")
                continue
            if snap.get("kind") == _metrics.SNAPSHOT_KIND:
                snaps.append(snap)
        return aggregate.merge_snapshots(snaps) if snaps else None

    def _post(self, port: int, doc: dict) -> List[dict]:
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/control",
            data=json.dumps(doc).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            reply = json.loads(resp.read())
        return reply.get("changes", [])

    def check_once(self) -> List[dict]:
        if disabled():
            return []
        ports = self._ports()
        snap = self._scrape(ports)
        if snap is None:
            return []

        def actuate(name: str, direction: int, *, rule: str,
                    evidence: Optional[dict]) -> List[dict]:
            decisions: List[dict] = []
            # one retune span per triggered action — every member's
            # control.decision span adopts its ctx, so the episode is
            # one tree across processes
            with _trace.request("control.retune", knob=name,
                                rule=rule):
                ctx = _trace.wire_context()
                doc = {"op": "step", "knob": name, "dir": direction,
                       "rule": rule, "evidence": evidence,
                       "origin": "fleet", "ctx": ctx}
                for port in ports:
                    try:
                        changes = self._post(port, doc)
                    except (OSError, ValueError) as e:
                        log.info(f"control: fleet actuate port={port} "
                            f"failed: {e!r}")
                        continue
                    for ch in changes:
                        ch = dict(ch)
                        ch["port"] = port
                        decisions.append(ch)
                        _ring({**ch, "origin": "fleet"})
            return decisions

        return _tick(self._states, snap, confirm=self.confirm,
                     hold=self.hold, actuate=actuate)

    def start(self) -> "FleetController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="mvtpu-fleet-control",
                daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.every_s):
            try:
                self.check_once()
            except Exception as e:
                log.info(f"control: fleet check failed: {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
