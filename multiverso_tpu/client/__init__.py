"""Worker-side client pipeline (PAPER.md §3.7/§4.2-4.3).

The reference parameter server's worker perf model, rebuilt over the
table contract: deltas coalesce locally and flush as ONE fused dispatch
(:class:`CoalescingBuffer`), reads come from a bounded-staleness local
cache refreshed in the background (:class:`CachedView` — the SSP-style
bound), and KV Add batches double-buffer their host prep + H2D against
the device apply (:class:`KVStagingWriter`). Everything is layered ON
the tables — no table semantics change unless a buffer/view is attached.

Opt-in env knobs, honored by the apps:

- ``MVTPU_COALESCE=<K>`` — coalesce K adds per flush (0/unset: off),
- ``MVTPU_STALENESS=<S>`` — serve logging-only reads from a CachedView
  within S generations (unset: off; ``0`` is a valid bound — it dedupes
  reads of an unchanged table).

Telemetry: ``client.coalesce.{flushes,deltas,bytes}``,
``client.cache.{hits,misses,staleness}``, ``client.stage.{batches,
inflight}`` — and the per-dispatch proof lives in
``profile.calls{fn=table.apply.*/kv.apply.*}`` (every table kernel is a
``profiled_jit``).
"""

from __future__ import annotations

import os
from typing import Any, Optional

from multiverso_tpu.client.cache import CachedView
from multiverso_tpu.client.coalesce import CoalescingBuffer, PendingHandle
from multiverso_tpu.client.staging import KVStagingWriter, stage_kv_adds

COALESCE_ENV = "MVTPU_COALESCE"
STALENESS_ENV = "MVTPU_STALENESS"


def coalesce_from_env() -> int:
    """``MVTPU_COALESCE`` as an int (0 = coalescing off)."""
    try:
        return max(int(os.environ.get(COALESCE_ENV, "0") or "0"), 0)
    except ValueError:
        return 0


def staleness_from_env() -> Optional[int]:
    """``MVTPU_STALENESS`` as an int bound, or None when unset/invalid
    (0 is a VALID bound — dedupe-only caching)."""
    raw = os.environ.get(STALENESS_ENV)
    if raw is None or raw == "":
        return None
    try:
        return max(int(raw), 0)
    except ValueError:
        return None


def maybe_coalescing(table: Any, **kwargs) -> Optional[CoalescingBuffer]:
    """A CoalescingBuffer over ``table`` when ``MVTPU_COALESCE`` asks
    for one, else None (the app wiring shape: buffer or passthrough)."""
    k = coalesce_from_env()
    if k <= 1:
        return None
    return CoalescingBuffer(table, max_deltas=k, **kwargs)


def maybe_cached_view(table: Any, **kwargs) -> Optional[CachedView]:
    """A CachedView over ``table`` when ``MVTPU_STALENESS`` asks for
    one, else None."""
    s = staleness_from_env()
    if s is None:
        return None
    return CachedView(table, max_staleness=s, **kwargs)


__all__ = [
    "CachedView", "CoalescingBuffer", "KVStagingWriter", "PendingHandle",
    "COALESCE_ENV", "STALENESS_ENV", "coalesce_from_env",
    "maybe_cached_view", "maybe_coalescing", "staleness_from_env",
    "stage_kv_adds",
]
