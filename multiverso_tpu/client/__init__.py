"""Worker-side client pipeline (PAPER.md §3.7/§4.2-4.3).

The reference parameter server's worker perf model, rebuilt over the
table contract: deltas coalesce locally and flush as ONE fused dispatch
(:class:`CoalescingBuffer`), reads come from a bounded-staleness local
cache refreshed in the background (:class:`CachedView` — the SSP-style
bound), and KV Add batches double-buffer their host prep + H2D against
the device apply (:class:`KVStagingWriter`). Everything is layered ON
the tables — no table semantics change unless a buffer/view is attached.

Opt-in env knobs, honored by the apps:

- ``MVTPU_COALESCE=<K>`` — coalesce K adds per flush (0/unset: off),
- ``MVTPU_STALENESS=<S>`` — serve logging-only reads from a CachedView
  within S generations (unset: off; ``0`` is a valid bound — it dedupes
  reads of an unchanged table).

Telemetry: ``client.coalesce.{flushes,deltas,bytes}``,
``client.cache.{hits,misses,staleness}``, ``client.stage.{batches,
inflight}`` — and the per-dispatch proof lives in
``profile.calls{fn=table.apply.*/kv.apply.*}`` (every table kernel is a
``profiled_jit``).

The multi-PROCESS worker path lives in :mod:`.transport`
(``WireClient``, ``RemoteArrayTable``, ``RemoteKVTable``): the same
table surface over a socket to a
:class:`~multiverso_tpu.server.table_server.TableServer` process, with
the CoalescingBuffer working over remote tables unchanged. It is
re-exported lazily (PEP 562): transport is file-path loadable by
jax-free workers, and importing it here eagerly would be harmless —
but keeping it lazy preserves the invariant that only code that talks
to a wire loads the wire.
"""

from __future__ import annotations

from typing import Any, Optional

from multiverso_tpu.client.cache import CachedView
from multiverso_tpu.client.coalesce import CoalescingBuffer, PendingHandle
from multiverso_tpu.client.staging import KVStagingWriter, stage_kv_adds
from multiverso_tpu.control import knobs as _knobs

_TRANSPORT_NAMES = ("WireClient", "RemoteArrayTable", "RemoteKVTable",
                    "RemoteHandle", "DeltaBatcher", "RemoteError",
                    "connect", "wire_retry_policy")

#: scatter-gather fleet names, lazily re-exported from .router (same
#: rationale as the transport names: only wire code loads the wire)
_ROUTER_NAMES = ("FleetClient", "FleetArrayTable", "FleetKVTable",
                 "FleetHandle", "connect_fleet", "connect_fleet_file",
                 "fleet_addresses")


def __getattr__(name: str):
    if name in _TRANSPORT_NAMES or name == "transport":
        # import_module, NOT `from ... import transport`: the from-
        # import resolves the submodule via getattr on this package,
        # which lands back here before sys.modules is populated
        import importlib
        transport = importlib.import_module(
            "multiverso_tpu.client.transport")
        return transport if name == "transport" \
            else getattr(transport, name)
    if name in _ROUTER_NAMES or name == "router":
        import importlib
        router = importlib.import_module(
            "multiverso_tpu.client.router")
        return router if name == "router" else getattr(router, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

# env names come from the control-plane knob table — one source of
# truth for name, bounds, and docs (control/knobs.py)
COALESCE_ENV = _knobs.spec("client.coalesce_k").env
STALENESS_ENV = _knobs.spec("client.staleness").env


def coalesce_from_env() -> int:
    """``MVTPU_COALESCE`` as an int (0 = coalescing off — OFF is
    outside the knob's clamped range, hence the raw read)."""
    raw = _knobs.env_raw("client.coalesce_k")
    try:
        return max(int(raw or "0"), 0)
    except ValueError:
        return 0


def staleness_from_env() -> Optional[int]:
    """``MVTPU_STALENESS`` as an int bound, or None when unset/invalid
    (0 is a VALID bound — dedupe-only caching)."""
    raw = _knobs.env_raw("client.staleness")
    if raw is None or raw == "":
        return None
    try:
        return _knobs.spec("client.staleness").clamp(int(raw))
    except ValueError:
        return None


def maybe_coalescing(table: Any, **kwargs) -> Optional[CoalescingBuffer]:
    """A CoalescingBuffer over ``table`` when ``MVTPU_COALESCE`` asks
    for one, else None (the app wiring shape: buffer or passthrough)."""
    k = coalesce_from_env()
    if k <= 1:
        return None
    return CoalescingBuffer(table, max_deltas=k, **kwargs)


def maybe_cached_view(table: Any, **kwargs) -> Optional[CachedView]:
    """A CachedView over ``table`` when ``MVTPU_STALENESS`` asks for
    one, else None."""
    s = staleness_from_env()
    if s is None:
        return None
    return CachedView(table, max_staleness=s, **kwargs)


__all__ = [
    "CachedView", "CoalescingBuffer", "KVStagingWriter", "PendingHandle",
    "COALESCE_ENV", "STALENESS_ENV", "coalesce_from_env",
    "maybe_cached_view", "maybe_coalescing", "staleness_from_env",
    "stage_kv_adds", *_TRANSPORT_NAMES, *_ROUTER_NAMES,
]
