"""Async H2D staging: overlap host batch prep with device apply.

A ``KVTable.add`` is two halves: a host half (key validation, splitmix
hash, uint64→2×uint32 split, delta conversion, the H2D ``device_put``s)
and a device half (the fused probe+updater dispatch). Issued serially,
the host half of batch k+1 waits for nothing but still sits on the
critical path between dispatches. :class:`KVStagingWriter` double-
buffers them: a persistent worker thread runs ``KVTable.prepare_add``
(the host half, safe off-thread — it touches no table state) up to
``depth`` batches ahead, while the caller's thread dispatches
``KVTable.add_prepared`` (the device half, which swaps live buffers and
must stay on the owning thread). Host conversion of batch k+1 overlaps
device apply of batch k — the reference's ParameterLoader/ASyncBuffer
pipelining role (SURVEY.md §4.5), applied to the Add path.

Update order is submission order: one worker + FIFO queues means
prepared batches come back in the order they went in, and dispatches
happen on the caller's thread in that order.

The writer is duck-typed over ``prepare_add``/``add_prepared``, so a
:class:`~multiverso_tpu.storage.tiered_kv.TieredKVTable` slots in
unchanged — there the prepare half is host-only (validate/hash/sort;
packing and the H2D wait for the dispatch-thread fault-in that decides
slot placement), and the dispatch half may chunk a batch wider than
the device tier.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Optional, Tuple

from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.updaters import AddOption


class KVStagingWriter:
    """Double-buffered Add writer for one :class:`KVTable`.

    ``add(keys, deltas)`` submits the batch for background prep and
    dispatches any batches whose prep (H2D) already landed; when
    ``depth`` batches are in flight it blocks until one drains — the
    pipeline is bounded, not unbounded. ``flush()`` drains everything
    and returns the last table Handle. The caller must not mutate
    ``keys``/``deltas`` until the writer flushes (zero-copy hand-off).

    AddOptions resolve at PREPARE time (see ``KVTable.prepare_add``) —
    an lr schedule advanced mid-pipeline applies from the next batch.
    """

    def __init__(self, table: Any, depth: int = 2, *,
                 option: Optional[AddOption] = None) -> None:
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._table = table
        self._depth = int(depth)
        self._option = option
        self._req: "queue.Queue[Optional[Tuple]]" = queue.Queue()
        self._ready: "queue.Queue[Tuple]" = queue.Queue()
        self._inflight = 0
        self._last_handle = None
        self._closed = False
        lbl = f"{table.table_id}:{table.name}"
        self._lbl = lbl
        self._m_batches = telemetry.counter("client.stage.batches",
                                            table=lbl)
        self._m_inflight = telemetry.gauge("client.stage.inflight",
                                           table=lbl)
        self._qg = telemetry.QueueGauges(f"stage:{lbl}")
        self._thread = threading.Thread(target=self._work, daemon=True)
        self._thread.start()

    def _work(self) -> None:
        while True:
            item = self._req.get()
            if item is None:
                return
            keys, deltas, option, token = item
            self._qg.on_take()
            try:
                # the off-thread prep chains to the add that submitted it
                with tracing.adopt(token):
                    with tracing.span("client.stage_prepare",
                                      table=self._lbl):
                        prepared = self._table.prepare_add(keys, deltas,
                                                           option)
                self._ready.put((prepared, None, token))
            except BaseException as exc:    # surfaces on the caller side
                self._ready.put((None, exc, token))

    def _land(self, item: Tuple) -> None:
        """Dispatch one prepared batch on the caller's thread."""
        prepared, exc, token = item
        self._inflight -= 1
        self._m_inflight.set(self._inflight)
        if exc is not None:
            raise exc
        # the dispatch chains to the batch's ORIGINAL request, not to
        # whichever later add happened to drain it
        with tracing.adopt(token):
            with tracing.span("client.stage_dispatch",
                              table=self._lbl):
                self._last_handle = self._table.add_prepared(prepared)

    def add(self, keys: Any, deltas: Any,
            option: Optional[AddOption] = None) -> None:
        """Submit one Add batch into the pipeline (prep off-thread,
        dispatch on the next add/flush once its H2D lands)."""
        if self._closed:
            raise RuntimeError("KVStagingWriter already closed")
        with tracing.request("client.stage_add", table=self._lbl):
            self._req.put((keys, deltas,
                           option if option is not None
                           else self._option, tracing.link()))
            self._qg.on_put()
            self._inflight += 1
            self._m_batches.inc()
            self._m_inflight.set(self._inflight)
            # dispatch whatever prep already finished (non-blocking) ...
            while True:
                try:
                    self._land(self._ready.get_nowait())
                except queue.Empty:
                    break
            # ... then apply the depth bound (blocking)
            while self._inflight > self._depth:
                self._land(self._ready.get())

    def flush(self):
        """Drain the pipeline; returns the last dispatched batch's table
        Handle (None when nothing was ever added)."""
        while self._inflight:
            self._land(self._ready.get())
        return self._last_handle

    def close(self):
        """Flush, then stop the worker thread. Returns the last Handle."""
        handle = self.flush() if not self._closed else self._last_handle
        if not self._closed:
            self._closed = True
            self._req.put(None)
            self._thread.join(timeout=5.0)
        return handle

    def __enter__(self) -> "KVStagingWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:   # don't mask the in-flight error with a flush error
            self._closed = True
            self._req.put(None)


def stage_kv_adds(table: Any, batches: Iterable[Tuple[Any, Any]], *,
                  depth: int = 2, option: Optional[AddOption] = None):
    """Drive an iterable of ``(keys, deltas)`` batches through a
    :class:`KVStagingWriter`; returns the last batch's table Handle."""
    with KVStagingWriter(table, depth, option=option) as writer:
        for keys, deltas in batches:
            writer.add(keys, deltas)
        return writer.flush()
