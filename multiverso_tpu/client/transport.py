"""Client transport: worker-process side of the parameter-server wire.

The reference's ``WorkerTable`` proxies (`src/worker.cpp`: Get/Add
become ZeroMQ messages to the server processes) for this port:
:class:`WireClient` dials a :class:`~multiverso_tpu.server
.table_server.TableServer`, and :class:`RemoteArrayTable` /
:class:`RemoteKVTable` present the local ``Table`` surface
(``get``/``add``/handles, CoalescingBuffer-compatible) over it.

Perf shape of the hot path:

- **Pipelined adds**: ``add(...)`` returns a :class:`RemoteHandle`
  immediately; up to :data:`MAX_PIPELINE` adds ride the wire unacked.
  ``Handle.wait()`` / any sync op drains the ack backlog first (server
  replies are in request order per connection).
- **Client-side coalescing**: :class:`DeltaBatcher` sums K local
  deltas into one wire frame (the jax-free twin of
  ``client/coalesce.py``'s CoalescingBuffer — which also works over
  these remote tables unchanged, via the same duck-typed surface).
- **Quantized delta frames** (``MVTPU_WIRE_QUANT=1bit|int8``): deltas
  are quantized ONCE at submit time — the pending entry keeps the
  quantized arrays, so a post-reconnect resend ships the identical
  bytes (re-quantizing would double-count the error-feedback
  residual). Residuals live in a per-client
  :class:`~multiverso_tpu.server.wire.ResidualStore`, keyed per
  (table, kind, geometry).

Delivery semantics: **at-least-once resend, exactly-once effect**. On
any connection failure (server restart, chaos ``drop``/``torn`` storm)
the client redials under a jittered
:class:`~multiverso_tpu.ft.retry.RetryPolicy` and resends every
unacked mutation; the server dedups by (client id, request id).
:class:`~multiverso_tpu.ft.chaos.ChaosCrash` is a BaseException and is
NEVER retried — a simulated process kill stays a kill.

Overload is distinct from failure. A server shedding load replies
``{ok:false, shed:true, retry_after_ms}`` (see
``server/admission.py``); the client honors the contract instead of
escalating: sleep the hint, resend the IDENTICAL bytes (same rid, same
already-quantized arrays — the dedup cache keeps exactly-once effect),
and treat the shed as *progress* in the reconnect retry loop (a
shedding server is an alive server: no reconnect, no attempt-budget
burn). Cumulative retry-after waits without a single ack are bounded
by the retry policy's deadline. Requests can carry a client-stamped
``deadline`` (``MVTPU_WIRE_DEADLINE_S`` or ``deadline_s=``, epoch
seconds on the wire) that the server checks at dispatch dequeue —
expired requests come back ``{ok:false, expired:true}`` as a
:class:`RemoteError`, never silently dropped.

The client talks to a transport-agnostic **Channel**
(:func:`multiverso_tpu.server.wire.dial_channel`): ``unix:``/``tcp:``
addresses get socket frames, ``shm://`` addresses negotiate the
same-host shared-memory ring pair (``io/shmring.py``) with graceful
fallback to the socket when the server doesn't take the offer.
Everything here — pipelining, resend, coalescing, quantization — is
identical on either transport.

Reads tolerate staleness explicitly: ``get(staleness=K)`` on a remote
table asks the server to answer from a read replica at most K
generations behind, off the dispatch queue entirely (reads stop paying
for writes). ``staleness=None`` (default) keeps strict
read-your-queue semantics through the dispatch thread.

Like :mod:`multiverso_tpu.server.wire`, this module is file-path
loadable with no package import: worker processes stay jax-free.
Use :func:`load_transport` from a bare script::

    transport = load_transport("/path/to/multiverso_tpu")
    client = transport.connect("unix:/tmp/mvtpu.sock", client="w0")
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _dep(modname: str, *relpath: str):
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    if "multiverso_tpu" in sys.modules:
        import importlib
        return importlib.import_module(modname)
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, *relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(modname, None)
        raise
    return mod


wire = _dep("multiverso_tpu.server.wire", "server", "wire.py")
wiresock = _dep("multiverso_tpu.io.wiresock", "io", "wiresock.py")
_chaos = _dep("multiverso_tpu.ft.chaos", "ft", "chaos.py")
_retry = _dep("multiverso_tpu.ft.retry", "ft", "retry.py")
_trace = _dep("multiverso_tpu.telemetry.trace", "telemetry", "trace.py")


def load_transport(package_dir: str):
    """File-path load this module (canonical name, no package import)
    from a bare worker script. ``package_dir`` is the
    ``multiverso_tpu`` directory."""
    modname = "multiverso_tpu.client.transport"
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    import importlib.util
    path = os.path.join(package_dir, "client", "transport.py")
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


#: max adds on the wire unacked; MUST stay below the server's dedup
#: cache depth (256) or a resend could outrun the replay window
MAX_PIPELINE = 64

#: per-connection clock-offset re-sample period (seconds). The ping
#: RTT-midpoint estimate drifts with the hosts' clocks; re-sampling
#: keeps merged fleet timelines honest without a ping per request.
CLOCK_RESAMPLE_S = 30.0

_OPTION_FIELDS = ("learning_rate", "momentum", "rho", "lam")


class RemoteError(RuntimeError):
    """The server replied ``{ok: false}`` — a real application error
    (bad table, shape mismatch), not a transport fault; never retried."""


def _option_dict(option: Any) -> Optional[Dict[str, float]]:
    """AddOption instance or plain dict → wire dict (jax-free: the
    transport never imports the updater layer)."""
    if option is None:
        return None
    if isinstance(option, dict):
        return {k: float(option[k]) for k in _OPTION_FIELDS
                if k in option}
    out = {}
    for k in _OPTION_FIELDS:
        v = getattr(option, k, None)
        if v is not None:
            out[k] = float(v)
    return out


def wire_retry_policy(name: str = "wire"):
    """Reconnect policy: more attempts / tighter backoff than disk IO
    (a dropped conn under a chaos storm is cheap to redial; defaults
    overridable by the same ``MVTPU_RETRY_*`` envs)."""
    env = os.environ.get
    return _retry.RetryPolicy(
        max_attempts=max(int(env("MVTPU_RETRY_ATTEMPTS", "") or 10), 1),
        base_delay_s=float(env("MVTPU_RETRY_BASE_S", "") or 0.01),
        max_delay_s=float(env("MVTPU_RETRY_MAX_S", "") or 0.25),
        deadline_s=float(env("MVTPU_RETRY_DEADLINE_S", "") or 60.0),
        name=name)


class _Pending:
    """One unacked mutation: header + the EXACT wire arrays (already
    quantized), kept for post-reconnect resend."""

    __slots__ = ("rid", "header", "arrays", "sent")

    def __init__(self, rid: int, header: Dict[str, Any],
                 arrays: List[np.ndarray]) -> None:
        self.rid = rid
        self.header = header
        self.arrays = arrays
        self.sent = False


class WireClient:
    """One connection to a table server; thread-safe via one lock
    (workers are processes — a client is normally single-threaded).

    Local ``tx_bytes`` / ``rx_bytes`` counters measure bytes-on-wire
    without needing the telemetry registry (jax-free workers report
    them straight from here)."""

    def __init__(self, address: str, *, client: Optional[str] = None,
                 quant: Optional[str] = "env",
                 seed: Optional[int] = None,
                 retry_policy=None,
                 deadline_s="env",
                 partition: Optional[Dict[str, Any]] = None) -> None:
        self.address = address
        self.client_id = client or f"pid{os.getpid()}"
        # partition-map claim (PartitionMap.to_wire()); sent in every
        # hello so a fleet member refuses a stale map BEFORE data flows
        self.partition = dict(partition) if partition else None
        self.quant = wire.quant_mode_from_env() if quant == "env" \
            else quant
        self.block = wire.wire_block()
        self.residuals = wire.ResidualStore()
        if deadline_s == "env":
            raw = os.environ.get(wire.DEADLINE_ENV, "").strip()
            self.deadline_s = float(raw) if raw else None
        else:
            self.deadline_s = float(deadline_s) if deadline_s else None
        self._rng = np.random.default_rng(seed)
        self._policy = retry_policy if retry_policy is not None \
            else wire_retry_policy()
        self._lock = threading.RLock()
        self._chan = None
        self._rid = 0
        self._pending: "collections.deque[_Pending]" = collections.deque()
        self._acked_rid = 0
        self._max_ack = 0
        self.tx_bytes = 0
        self.rx_bytes = 0
        self.reconnects = 0
        self.sheds = 0              # shed replies honored (bench reads)
        self._shed_wait_s = 0.0     # retry-after slept since last ack
        # ping-based clock alignment vs this server (RTT midpoint):
        # offset_us = server wall clock minus ours; the fleet report
        # shifts the server's spans by it when merging timelines
        self.clock_offset_us: Optional[float] = None
        self.clock_rtt_us: Optional[float] = None
        self.server_ident: Optional[Dict[str, Any]] = None
        self._clock_sampled = 0.0
        self._clock_sampling = False
        self._closed = False
        self._retry_loop(self._ensure_connected)

    def _retry_loop(self, fn):
        """Progress-aware reconnect retry. Like ``RetryPolicy.call``
        but the attempt budget RESETS whenever the acked rid advances:
        under a wire storm each reconnect drains part of the pending
        window before dying, and steady progress must not exhaust a
        fixed attempt count — while a genuinely dead server (no
        progress) still fails loudly after ``max_attempts``.

        A shed reply counts as progress too: a server shedding load is
        an ALIVE server telling this client to back off — escalating
        that to the reconnect budget would tear down the very pipeline
        the shed was protecting."""
        import time as _time
        policy = self._policy
        t0 = _time.monotonic()
        attempt = 0
        last_acked = self._acked_rid
        last_sheds = self.sheds
        while True:
            try:
                return fn()
            except policy.non_retryable:
                raise
            except (ConnectionError, OSError) as exc:
                self._mark_dead()
                self._count("retry.attempts", policy=policy.name)
                if self._acked_rid > last_acked \
                        or self.sheds > last_sheds:
                    last_acked = self._acked_rid
                    last_sheds = self.sheds
                    attempt = 0
                attempt += 1
                elapsed = _time.monotonic() - t0
                if attempt >= policy.max_attempts:
                    raise _retry.RetryError(
                        f"wire retry: {attempt} attempts without "
                        f"progress ({elapsed:.2f}s): {exc!r}") from exc
                delay = policy.backoff_s(attempt)
                if policy.deadline_s > 0 \
                        and elapsed + delay > policy.deadline_s:
                    raise _retry.RetryError(
                        f"wire retry: deadline {policy.deadline_s}s "
                        f"exceeded after {attempt} attempts: "
                        f"{exc!r}") from exc
                if delay > 0:
                    _time.sleep(delay)

    # -- connection management ---------------------------------------------

    def _mark_dead(self) -> None:
        if self._chan is not None:
            try:
                self._chan.close()
            except OSError:
                pass
            self._chan = None
            for p in self._pending:
                p.sent = False

    @property
    def transport(self) -> Optional[str]:
        """The live channel's transport kind ("socket" | "shm"), or
        None while disconnected."""
        chan = self._chan
        return chan.transport if chan is not None else None

    def _ensure_connected(self) -> None:
        """Dial + hello + resend every unacked mutation. Runs under the
        retry policy: any OSError here is retried with backoff."""
        if self._chan is not None:
            return
        if self._closed:
            raise RemoteError("wire client is closed")
        chan = wire.dial_channel(self.address)
        try:
            self._rid += 1
            hello_rid = self._rid
            hello: Dict[str, Any] = {"op": "hello", "rid": hello_rid,
                                     "client": self.client_id}
            if self.partition is not None:
                hello["partition"] = self.partition
            self._tx(chan, hello, [])
            header, _, nbytes = chan.recv()
            self.rx_bytes += nbytes
            if not header.get("ok") or header.get("rid") != hello_rid:
                # includes a fleet member refusing a partition-map
                # mismatch: WireProtocolError is not in the retryable
                # set, so the refusal propagates loudly, unretried.
                # The reply header rides on the exception — a refusal
                # carries the server's CURRENT map, which is how a
                # stale router refreshes itself (client/router.py)
                err = wire.WireProtocolError(
                    f"bad hello reply: {header}")
                err.header = header
                raise err
        except BaseException:
            try:
                chan.close()
            except OSError:
                pass
            raise
        self._chan = chan
        if self.reconnects or self._pending:
            self.reconnects += 1
            self._count("wire.reconnects")
        # at-least-once replay of the unacked window (server dedups).
        # SYNCHRONOUS on purpose — one frame, one ack: a storm that
        # drops the connection mid-replay costs at most one frame of
        # progress, where a pipelined replay of W frames would restart
        # all W on every drop and never converge (acks shrink
        # ``_pending``, and :meth:`_retry_loop` resets its attempt
        # budget whenever the acked rid advances)
        while self._pending:
            p = self._pending[0]
            if not p.sent:      # a shed mid-replay already resent it
                self._tx(chan, p.header, p.arrays)
                p.sent = True
            header, _, nbytes = chan.recv()
            self.rx_bytes += nbytes
            self._consume_ack(header)

    def _tx(self, chan, header, arrays) -> None:
        self.tx_bytes += chan.send(header, arrays)

    @staticmethod
    def _count(name: str, n: float = 1, **labels) -> None:
        m = sys.modules.get("multiverso_tpu.telemetry.metrics")
        if m is not None:
            try:
                m.counter(name, **labels).inc(n)
            except Exception:
                pass

    @staticmethod
    def _gauge(name: str, value: float, **labels) -> None:
        m = sys.modules.get("multiverso_tpu.telemetry.metrics")
        if m is not None:
            try:
                m.gauge(name, **labels).set(value)
            except Exception:
                pass

    # -- clock alignment ----------------------------------------------------

    def _maybe_sample_clock(self) -> None:
        """Re-estimate this connection's clock offset every
        :data:`CLOCK_RESAMPLE_S`: ping, take ``t_server`` from the
        reply, and put the server's clock at the RTT midpoint —
        ``offset_us = t_server - (t0 + t1)/2``. Published as the
        ``wire.clock.offset_us`` gauge and a ``clock`` trace record so
        merged fleet timelines can shift the server's spans honestly.
        Best-effort: estimation failures never touch the data path."""
        if self._clock_sampling or self._closed:
            return
        now = time.monotonic()
        if self._clock_sampled \
                and now - self._clock_sampled < CLOCK_RESAMPLE_S:
            return
        self._clock_sampling = True
        self._clock_sampled = now
        try:
            t0 = time.time()
            header, _ = self.call("ping")
            t1 = time.time()
            t_server = header.get("t_server")
            if t_server is None:
                return
            offset_us = (float(t_server) - (t0 + t1) / 2.0) * 1e6
            rtt_us = max(t1 - t0, 0.0) * 1e6
            self.clock_offset_us = offset_us
            self.clock_rtt_us = rtt_us
            peer = {k: header[k] for k in ("host", "pid")
                    if header.get(k) is not None}
            self.server_ident = peer or None
            self._gauge("wire.clock.offset_us", offset_us,
                        addr=self.address)
            try:
                _trace.clock_record(peer, offset_us, rtt_us)
            except Exception:
                pass
        except (ConnectionError, OSError, _retry.RetryError):
            pass
        finally:
            self._clock_sampling = False

    # -- request plumbing --------------------------------------------------

    def _next_rid(self) -> int:
        self._rid += 1
        return self._rid

    def _recv_reply(self) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        header, arrays, nbytes = self._chan.recv()
        self.rx_bytes += nbytes
        return header, arrays

    def _consume_ack(self, header: Dict[str, Any]) -> None:
        """Match a reply against the pending window. Without shedding
        acks arrive in rid order, but admission breaks that: when r1 is
        shed and r2 admitted (a token accrued or a queue slot freed in
        between), r2's dispatch ack reaches us while the window head is
        still the resent r1. So BOTH shed replies and acks scan the
        whole window; ``_acked_rid`` only advances past rids with no
        pending mutation left at or below them."""
        rid = header.get("rid")
        if header.get("shed"):
            self._honor_shed(rid, header)
            return
        for i, p in enumerate(self._pending):
            if p.rid != rid:
                continue
            del self._pending[i]
            self._max_ack = max(self._max_ack, rid)
            if self._pending:
                self._acked_rid = max(
                    self._acked_rid,
                    min(self._max_ack, self._pending[0].rid - 1))
            else:
                self._acked_rid = max(self._acked_rid, self._max_ack)
            self._shed_wait_s = 0.0     # an ack = shed-wait progress
            if not header.get("ok"):
                err = RemoteError(
                    f"remote add rid={rid} failed: "
                    f"{header.get('error')}")
                err.header = header
                raise err
            return

    def _honor_shed(self, rid, header: Dict[str, Any]) -> None:
        """A shed reply is neither a failure nor a dead server: the
        request was never applied (and never entered the dedup cache).
        Honor the retry-after hint, then resend the IDENTICAL bytes —
        same rid, same already-quantized arrays — so the server's
        dedup keeps the exactly-once effect if both copies land."""
        target = None
        for p in self._pending:
            if p.rid == rid:
                target = p
                break
        if target is None:
            return      # a sync call's shed: _recv_until resends it
        target.sent = False
        self._shed_backoff(header)
        if self._chan is not None:
            self._tx(self._chan, target.header, target.arrays)
            target.sent = True

    def _shed_backoff(self, header: Dict[str, Any]) -> None:
        """Sleep the server's retry-after hint. Cumulative shed waits
        without a single ack are bounded by the retry policy deadline —
        a server that sheds forever still fails loudly, it just never
        triggers a reconnect (it is alive)."""
        self.sheds += 1
        self._count("wire.client.sheds")
        delay = max(float(header.get("retry_after_ms") or 0.0),
                    0.0) / 1000.0
        self._shed_wait_s += max(delay, 1e-4)
        policy = self._policy
        if policy.deadline_s > 0 \
                and self._shed_wait_s > policy.deadline_s:
            raise _retry.RetryError(
                f"server shed {self.sheds} requests; cumulative "
                f"retry-after wait {self._shed_wait_s:.2f}s exceeds "
                f"the retry deadline {policy.deadline_s}s without an "
                "ack")
        if delay > 0:
            # the shed reply echoes who shed what (server name, QoS
            # class, trace id) — the retry-wait span names them, so a
            # slow traced request shows WHERE its wait went
            attrs = {k: header[k]
                     for k in ("server", "class", "req")
                     if header.get(k) is not None}
            with _trace.span("wire.client.shed_wait", **attrs):
                time.sleep(delay)

    def _recv_until(self, rid: int, resend=None
                    ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        while True:
            header, arrays = self._recv_reply()
            got = header.get("rid")
            if got == rid:
                if header.get("shed"):
                    if any(p.rid == rid for p in self._pending):
                        self._consume_ack(header)   # pipelined target
                    else:
                        # sync request shed: back off, resend the same
                        # bytes, keep waiting for the same rid
                        self._shed_backoff(header)
                        if resend is not None:
                            resend()
                    continue
                # the target itself may also be a pending mutation
                self._consume_ack(header)
                if not header.get("ok"):
                    err = RemoteError(f"remote op rid={rid} failed: "
                                      f"{header.get('error')}")
                    err.header = header     # structured refusals
                    raise err               # (stale follower, ...)
                return header, arrays
            self._consume_ack(header)

    def call(self, op: str, header: Optional[Dict[str, Any]] = None,
             arrays: Sequence[np.ndarray] = ()
             ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        """Synchronous request/reply (drains pending acks on the way).
        Reconnects + retries on transport faults; application errors
        (:class:`RemoteError`) and protocol desync are never retried."""
        with self._lock, \
                _trace.request(f"wire.client.{op}", op=op,
                               addr=self.address):
            req = dict(header or {})
            req["op"] = op
            req["rid"] = self._next_rid()
            if self.partition is not None:
                # the map version this frame was built against: a
                # committed reshard uses it to relay old-geometry
                # writes instead of misapplying them. Stamped once —
                # resends must claim the ORIGINAL version to hit the
                # relay path (and its dedup) identically.
                req.setdefault(
                    "pv", int(self.partition.get("version", 0) or 0))
            if self.deadline_s:
                # stamped ONCE: shed/reconnect resends keep the
                # original expiry (a deadline is end-to-end)
                wire.stamp_deadline(req, self.deadline_s)
            if wire.trace_enabled():
                # also stamped once: resends ship the identical trace
                # context, so the server-side tree stays one tree
                wire.stamp_trace(req, _trace.wire_context())
            arrays = [np.ascontiguousarray(a) for a in arrays]

            def attempt():
                try:
                    self._ensure_connected()
                    self._tx(self._chan, req, arrays)
                    return self._recv_until(
                        req["rid"],
                        resend=lambda: self._tx(self._chan, req,
                                                arrays))
                except (ConnectionError, OSError):
                    self._mark_dead()
                    raise
            result = self._retry_loop(attempt)
            if op != "shutdown":    # never ping a server we just told
                self._maybe_sample_clock()  # to drain and exit
            return result

    def submit(self, header: Dict[str, Any],
               arrays: Sequence[np.ndarray]) -> int:
        """Pipelined mutation: send now, ack later. Returns the rid
        (wait for it with :meth:`drain_to`)."""
        with self._lock, \
                _trace.request(
                    f"wire.client.{header.get('op', 'submit')}",
                    op=str(header.get("op", "submit")),
                    addr=self.address):
            rid = self._next_rid()
            req = dict(header)
            req["rid"] = rid
            if self.partition is not None:
                req.setdefault(
                    "pv", int(self.partition.get("version", 0) or 0))
            if self.deadline_s:
                wire.stamp_deadline(req, self.deadline_s)
            if wire.trace_enabled():
                wire.stamp_trace(req, _trace.wire_context())
            p = _Pending(rid, req,
                         [np.ascontiguousarray(a) for a in arrays])
            self._pending.append(p)

            def attempt():
                try:
                    self._ensure_connected()
                    for q in self._pending:
                        if not q.sent:
                            self._tx(self._chan, q.header, q.arrays)
                            q.sent = True
                    while len(self._pending) > MAX_PIPELINE:
                        self._consume_ack(self._recv_reply()[0])
                    return rid
                except (ConnectionError, OSError):
                    self._mark_dead()
                    raise
            return self._retry_loop(attempt)

    def drain_to(self, rid: int) -> None:
        """Block until the ack for ``rid`` (and everything before it)
        has arrived."""
        with self._lock:
            if self._acked_rid >= rid:
                return

            def attempt():
                try:
                    self._ensure_connected()
                    while self._pending \
                            and self._pending[0].rid <= rid:
                        self._consume_ack(self._recv_reply()[0])
                except (ConnectionError, OSError):
                    self._mark_dead()
                    raise
            self._retry_loop(attempt)

    def drain(self) -> None:
        """Block until every pipelined mutation is acked."""
        with self._lock:
            if self._pending:
                self.drain_to(self._pending[-1].rid)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            try:
                self.drain()
            finally:
                self._closed = True
                if self._chan is not None:
                    try:
                        self._chan.close()
                    except OSError:
                        pass
                    self._chan = None

    def abort(self) -> None:
        """Close WITHOUT draining: for a peer known to be dead (a
        SIGKILLed primary, a dropped replication follower) where
        :meth:`close`'s drain would burn the whole retry budget
        against a corpse. Pending mutations stay pending — a
        :meth:`rebind` to a successor replays them."""
        with self._lock:
            self._closed = True
            if self._chan is not None:
                try:
                    self._chan.close()
                except OSError:
                    pass
                self._chan = None

    def rebind(self, address: str,
               partition: Optional[Dict[str, Any]] = None) -> None:
        """Repoint this client at a successor server (failover: the
        promoted follower inherits the dead primary's range). The
        pending window survives: the next request redials ``address``,
        hellos with the NEW partition claim, and replays every unacked
        mutation — the successor's dedup (fed by the replication
        stream's origin records) keeps the exactly-once effect."""
        with self._lock:
            self.address = address
            if partition is not None:
                self.partition = dict(partition)
            self._closed = False
            if self._chan is not None:
                try:
                    self._chan.close()
                except OSError:
                    pass
                self._chan = None
            for p in self._pending:
                p.sent = False

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- table surface -----------------------------------------------------

    def create_array(self, name: str, size: int, *,
                     dtype: str = "float32",
                     updater: Optional[str] = None,
                     init_value: float = 0) -> "RemoteArrayTable":
        spec: Dict[str, Any] = {"size": int(size), "dtype": dtype,
                                "init_value": init_value}
        if updater:
            spec["updater"] = updater
        header, _ = self.call("create", {"name": name, "kind": "array",
                                         "spec": spec})
        return RemoteArrayTable(self, header)

    def create_kv(self, name: str, capacity: int, *, value_dim: int = 0,
                  dtype: str = "float32", updater: Optional[str] = None,
                  tiered: bool = False) -> "RemoteKVTable":
        spec: Dict[str, Any] = {"capacity": int(capacity),
                                "value_dim": int(value_dim),
                                "dtype": dtype}
        if updater:
            spec["updater"] = updater
        kind = "tiered_kv" if tiered else "kv"
        header, _ = self.call("create", {"name": name, "kind": kind,
                                         "spec": spec})
        return RemoteKVTable(self, header)

    def ping(self) -> bool:
        return bool(self.call("ping")[0].get("ok"))

    def server_status(self) -> Dict[str, Any]:
        return self.call("stats")[0].get("status", {})

    def shutdown_server(self) -> None:
        """Ask the server process to drain and exit (best-effort: the
        reply may be cut off by the exit itself)."""
        with self._lock:
            try:
                self.call("shutdown")
            except (ConnectionError, OSError, _retry.RetryError):
                pass


class RemoteHandle:
    """Handle-compatible ack future for a pipelined remote add."""

    def __init__(self, client: WireClient, rid: int) -> None:
        self._client = client
        self._rid = rid

    def done(self) -> bool:
        return self._client._acked_rid >= self._rid

    def wait(self) -> None:
        self._client.drain_to(self._rid)

    def result(self) -> None:
        return self.wait()


class _RemoteTable:
    """Shared surface: the duck type ``client/coalesce.py``'s
    CoalescingBuffer needs (``table_id``/``name``/``dtype``/
    ``num_cols``/``_attach_coalescer``/``add``)."""

    def __init__(self, client: WireClient,
                 meta: Dict[str, Any]) -> None:
        self.client = client
        self.table_id = int(meta["table"])
        self.name = str(meta["name"])
        self.kind = str(meta["kind"])
        self.dtype = np.dtype(str(meta["dtype"]))
        self._coalescers: List[Any] = []

    def _attach_coalescer(self, buf: Any) -> None:
        self._coalescers.append(buf)

    def flush_coalesced(self) -> None:
        for buf in self._coalescers:
            buf.flush()

    def wait(self) -> None:
        self.client.drain()

    def _quant_kind(self) -> str:
        raise NotImplementedError

    def _encode(self, delta: np.ndarray) -> tuple:
        c = self.client
        return wire.encode_delta(
            np.asarray(delta, self.dtype), c.quant,
            table=self.table_id, kind=self._quant_kind(),
            residuals=c.residuals, rng=c._rng, block=c.block)


class RemoteArrayTable(_RemoteTable):
    """Dense 1-D table over the wire (local twin:
    ``tables/array_table.py``)."""

    def __init__(self, client: WireClient,
                 meta: Dict[str, Any]) -> None:
        super().__init__(client, meta)
        self.size = int(meta.get("size", 0))
        self.num_cols = 1

    def get(self, staleness: Optional[int] = None) -> np.ndarray:
        """Whole-table fetch. ``staleness=K`` allows the server to
        answer from its read replica when it is at most K generations
        behind — served on the reader thread, never queued behind
        writes."""
        header: Dict[str, Any] = {"table": self.table_id}
        if staleness is not None:
            header["staleness"] = int(staleness)
        _, arrays = self.client.call("get", header)
        return np.array(arrays[0])    # copy out of the frame buffer

    def add(self, delta, option=None, sync: bool = False
            ) -> RemoteHandle:
        quant, payload = self._encode(delta)
        header = {"op": "add", "table": self.table_id, "quant": quant,
                  "option": _option_dict(option)}
        rid = self.client.submit(header, payload)
        handle = RemoteHandle(self.client, rid)
        if sync:
            handle.wait()
        return handle

    add_async = add

    def _quant_kind(self) -> str:
        return "dense"


class RemoteKVTable(_RemoteTable):
    """Hashed KV table over the wire (local twin:
    ``tables/kv_table.py``; ``tiered`` creates a
    ``storage/tiered_kv.py`` table server-side)."""

    def __init__(self, client: WireClient,
                 meta: Dict[str, Any]) -> None:
        super().__init__(client, meta)
        self.value_dim = int(meta.get("value_dim", 0))
        self.num_cols = max(self.value_dim, 1)

    def get(self, keys, staleness: Optional[int] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """Batch lookup. ``staleness=K`` as on
        :meth:`RemoteArrayTable.get` — replica-served when fresh
        enough, at most K generations behind."""
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        header: Dict[str, Any] = {"table": self.table_id}
        if staleness is not None:
            header["staleness"] = int(staleness)
        _, arrays = self.client.call("kv_get", header, [keys])
        return np.array(arrays[0]), np.array(arrays[1])

    def add(self, keys, deltas, option=None, sync: bool = False
            ) -> RemoteHandle:
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        quant, payload = self._encode(deltas)
        header = {"op": "kv_add", "table": self.table_id,
                  "quant": quant, "option": _option_dict(option)}
        rid = self.client.submit(header, [keys] + payload)
        handle = RemoteHandle(self.client, rid)
        if sync:
            handle.wait()
        return handle

    add_async = add

    def _quant_kind(self) -> str:
        # 1-bit EF needs stable geometry per residual; a KV batch's key
        # set varies, so KV always quantizes with the unbiased
        # stateless int8 path (encode_delta enforces it too)
        return "kv"


class DeltaBatcher:
    """Jax-free client-side coalescer: sum K dense deltas locally,
    ship ONE wire frame. The minimal twin of ``client/coalesce.py``
    (which needs the package; this one runs in bare workers) — same
    contract: buffered deltas are invisible until the flush."""

    def __init__(self, table: RemoteArrayTable,
                 max_deltas: int = 8) -> None:
        if max_deltas < 1:
            raise ValueError("max_deltas must be >= 1")
        self.table = table
        self.max_deltas = int(max_deltas)
        self._acc: Optional[np.ndarray] = None
        self._count = 0
        self.flushes = 0

    def add(self, delta) -> None:
        delta = np.asarray(delta, self.table.dtype)
        if self._acc is None:
            self._acc = delta.copy()
        else:
            self._acc += delta
        self._count += 1
        if self._count >= self.max_deltas:
            self.flush()

    def flush(self) -> Optional[RemoteHandle]:
        if self._acc is None:
            return None
        handle = self.table.add(self._acc)
        self._acc = None
        self._count = 0
        self.flushes += 1
        return handle


def connect(address: str, *, client: Optional[str] = None,
            quant: Optional[str] = "env",
            seed: Optional[int] = None,
            deadline_s="env",
            partition: Optional[Dict[str, Any]] = None) -> WireClient:
    """Dial a table server; ``quant="env"`` reads ``MVTPU_WIRE_QUANT``,
    ``deadline_s="env"`` reads ``MVTPU_WIRE_DEADLINE_S`` (pass a float
    to stamp every request with that deadline, ``None`` for none).
    ``partition`` is a PartitionMap wire dict claimed at hello when
    dialing one member of a sharded fleet (see ``client/router.py``)."""
    return WireClient(address, client=client, quant=quant, seed=seed,
                      deadline_s=deadline_s, partition=partition)
