"""Client-side scatter-gather router over a sharded server fleet.

One :class:`~multiverso_tpu.client.transport.WireClient` talks to ONE
table server. A fleet (``python -m multiverso_tpu.server --fleet N``)
is N such servers, each owning a contiguous partition of every table
(:mod:`multiverso_tpu.server.partition`). :class:`FleetClient` makes
the fleet look like one server: it wraps N ``WireClient``\\ s and the
fleet tables split every get/add HOST-side by ownership, pipeline the
per-server sub-requests concurrently, and reassemble replies by the
inverse index — the client half of the reference's multi-server
``ProcessGet``/``ProcessAdd`` partitioning (`src/server.cpp` routes by
row hash; we route by the PartitionMap's contiguous blocks).

Why throughput scales with N: each sub-request rides its OWN
connection, so the existing ≤``MAX_PIPELINE``-unacked windows run in
parallel across servers, and each server runs its own dispatch thread,
fusion cycle, replica publisher, and admission controller over a table
1/N the size.

Layering is deliberate: :class:`FleetArrayTable` / :class:`FleetKVTable`
are thin routers over per-server ``RemoteArrayTable`` /
``RemoteKVTable`` subtables, so everything the transport already does
— pipelined windows, at-least-once resend + server dedup
(exactly-once), shed honoring, quantize-once-at-submit — applies
per shard unchanged. Each per-server ``WireClient`` owns its own
``ResidualStore``, so 1-bit error feedback stays correct *per
connection* (a shared residual across servers would leak one shard's
quantization error into another's stream). KV duplicates are pre-summed
per shard before submit (``np.unique`` + ``np.add.at``, the same
associativity CoalescingBuffer leans on), so a key appearing twice in
one batch costs one wire row and applies once.

The fleet tables present the same duck-typed surface as the remote
tables (``table_id``/``name``/``dtype``/``num_cols``/
``_attach_coalescer``/``add``/``get``/``wait``), so
``client/coalesce.py``'s CoalescingBuffer and the transport's
``DeltaBatcher`` stack on top unchanged.

Partial failure is partial: a SIGKILLed member costs ONLY its
partition. Ops routed to surviving shards keep completing (their
connections never notice); ops touching the dead shard block in that
one client's standard reconnect/replay loop and resume exactly-once
when the member returns. ``get_shard(rank)`` exposes the per-rank
subtable for exactly that kind of surviving-partition work.

jax-free and file-path loadable (:func:`load_router`) like the
transport — this is worker-process code.
"""

from __future__ import annotations

import os
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _dep(modname: str, *relpath: str):
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    if "multiverso_tpu" in sys.modules:
        import importlib
        return importlib.import_module(modname)
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, *relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(modname, None)
        raise
    return mod


transport = _dep("multiverso_tpu.client.transport",
                 "client", "transport.py")
partition = _dep("multiverso_tpu.server.partition",
                 "server", "partition.py")
_trace = _dep("multiverso_tpu.telemetry.trace", "telemetry",
              "trace.py")


def load_router(package_dir: str):
    """File-path load this module (canonical name, no package import)
    from a bare worker script. ``package_dir`` is the
    ``multiverso_tpu`` directory."""
    modname = "multiverso_tpu.client.router"
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    import importlib.util
    path = os.path.join(package_dir, "client", "router.py")
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


class FleetHandle:
    """Handle-compatible future over the per-shard handles of one
    logical mutation. ``done()``/``wait()`` quantify over every shard
    the op actually touched."""

    def __init__(self, handles: Sequence[Any]) -> None:
        self._handles = list(handles)

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self) -> None:
        for h in self._handles:
            h.wait()

    def result(self) -> None:
        return self.wait()


class _FleetTable:
    """Shared router surface (the CoalescingBuffer duck type, same as
    ``transport._RemoteTable``)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any]) -> None:
        self.fleet = fleet
        self.subs = list(subs)          # rank-ordered per-server tables
        head = self.subs[0]
        self.table_id = head.table_id   # names the table in coalescers
        self.name = head.name
        self.kind = head.kind
        self.dtype = head.dtype
        self._coalescers: List[Any] = []

    @property
    def pmap(self) -> "partition.PartitionMap":
        return self.fleet.pmap

    def get_shard(self, rank: int):
        """The per-rank remote subtable — the surface that keeps
        serving a surviving partition while another member is down."""
        return self.subs[rank]

    def _attach_coalescer(self, buf: Any) -> None:
        self._coalescers.append(buf)

    def flush_coalesced(self) -> None:
        for buf in self._coalescers:
            buf.flush()

    def wait(self) -> None:
        for sub in self.subs:
            sub.wait()


class FleetArrayTable(_FleetTable):
    """Dense 1-D table scattered across the fleet by contiguous
    element ranges (rank r serves global elements [bounds[r],
    bounds[r+1]) as ITS local rows 0..len)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any],
                 size: int) -> None:
        super().__init__(fleet, subs)
        self.size = int(size)
        self.num_cols = 1
        self._bounds = fleet.pmap.dense_bounds(self.size)

    def get(self, staleness: Optional[int] = None) -> np.ndarray:
        """Whole-table scatter-gather: each server returns its shard
        concurrently; concat in rank order is the inverse map (the
        zero-index-math payoff of contiguous ownership)."""
        with _trace.request("fleet.get", table=self.name):
            parts = self.fleet._fanout(
                [lambda s=s: s.get(staleness=staleness)
                 for s in self.subs])
            return np.concatenate(parts)

    def get_range(self, lo: int, hi: int,
                  staleness: Optional[int] = None) -> np.ndarray:
        """Elements [lo, hi) — fetched ONLY from the shards whose
        ranges overlap it. This is the partitioning payoff a single
        server cannot offer: its wire ``get`` is a whole-table
        snapshot, so a range read there ships every element; here a
        shard-aligned range ships 1/N of the bytes end to end."""
        lo, hi = int(lo), int(hi)
        if not 0 <= lo < hi <= self.size:
            raise ValueError(
                f"range [{lo}, {hi}) out of bounds for size {self.size}")
        b = self._bounds
        ranks = [r for r in range(self.pmap.n)
                 if b[r] < hi and b[r + 1] > lo]
        with _trace.request("fleet.get_range", table=self.name,
                            lo=lo, hi=hi):
            parts = self.fleet._fanout(
                [lambda s=self.subs[r]: s.get(staleness=staleness)
                 for r in ranks])
        if len(parts) == 1:
            r = ranks[0]
            return parts[0][lo - b[r]:hi - b[r]]
        first = ranks[0]
        return np.concatenate(parts)[lo - b[first]:hi - b[first]]

    def add(self, delta, option=None, sync: bool = False) -> FleetHandle:
        """Split the global delta by ownership; each slice is submitted
        on its own pipelined connection (quantized there, against that
        connection's residual store)."""
        delta = np.asarray(delta, self.dtype)
        if delta.shape != (self.size,):
            raise ValueError(
                f"fleet add to {self.name!r} expects shape "
                f"({self.size},), got {delta.shape}")
        b = self._bounds
        with _trace.request("fleet.add", table=self.name):
            handles = [sub.add(delta[b[r]:b[r + 1]], option)
                       for r, sub in enumerate(self.subs)]
        handle = FleetHandle(handles)
        if sync:
            handle.wait()
        return handle

    add_async = add


class FleetKVTable(_FleetTable):
    """Hashed KV table scattered by contiguous logical-bucket blocks:
    a key's splitmix64 bucket picks its owning rank, forever (until a
    map-version bump)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any]) -> None:
        super().__init__(fleet, subs)
        head = self.subs[0]
        self.value_dim = head.value_dim
        self.num_cols = head.num_cols

    def _route(self, keys: np.ndarray
               ) -> List[Tuple[int, np.ndarray]]:
        """(rank, positions-into-keys) per rank that owns >= 1 key."""
        owner = self.pmap.kv_owner(keys)
        out = []
        for r in range(self.pmap.n):
            idx = np.nonzero(owner == r)[0]
            if idx.size:
                out.append((r, idx))
        return out

    def get(self, keys, staleness: Optional[int] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """Batch lookup fanned out by ownership, reassembled into the
        caller's key order via the inverse index."""
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        n = keys.shape[0]
        shape = (n, self.value_dim) if self.value_dim else (n,)
        values = np.zeros(shape, self.dtype)
        found = np.zeros(n, bool)
        routed = self._route(keys)
        with _trace.request("fleet.kv_get", table=self.name):
            replies = self.fleet._fanout(
                [lambda r=r, idx=idx: self.subs[r].get(
                    keys[idx], staleness=staleness)
                 for r, idx in routed])
        for (r, idx), (vals, fnd) in zip(routed, replies):
            values[idx] = vals
            found[idx] = fnd
        return values, found

    def add(self, keys, deltas, option=None,
            sync: bool = False) -> FleetHandle:
        """Scatter an add by ownership, pre-summing duplicate keys per
        shard first — one wire row per distinct key, one apply per
        distinct key, same associative-sum contract the server's own
        fused batches use."""
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        deltas = np.asarray(deltas, self.dtype)
        handles = []
        with _trace.request("fleet.kv_add", table=self.name):
            for r, idx in self._route(keys):
                sub_keys = keys[idx]
                sub_deltas = deltas[idx]
                uniq, inv = np.unique(sub_keys, return_inverse=True)
                if uniq.shape[0] != sub_keys.shape[0]:
                    acc = np.zeros(
                        (uniq.shape[0],) + sub_deltas.shape[1:],
                        sub_deltas.dtype)
                    np.add.at(acc, inv, sub_deltas)
                    sub_keys, sub_deltas = uniq, acc
                handles.append(self.subs[r].add(sub_keys, sub_deltas,
                                                option))
        handle = FleetHandle(handles)
        if sync:
            handle.wait()
        return handle

    add_async = add


class FleetClient:
    """N ``WireClient``\\ s + one :class:`PartitionMap` = one logical
    parameter server (see module docstring)."""

    def __init__(self, addresses: Sequence[str], *,
                 pmap: Optional["partition.PartitionMap"] = None,
                 version: int = 1,
                 kv_buckets: Optional[int] = None,
                 client: Optional[str] = None,
                 quant: Optional[str] = "env",
                 seed: Optional[int] = None,
                 deadline_s="env") -> None:
        addresses = list(addresses)
        if not addresses:
            raise ValueError("fleet needs at least one server address")
        if pmap is None:
            pmap = partition.PartitionMap(
                len(addresses), version=version, kv_buckets=kv_buckets)
        if pmap.n != len(addresses):
            raise ValueError(
                f"partition map is for {pmap.n} servers, got "
                f"{len(addresses)} addresses")
        self.pmap = pmap
        self.client_id = client or f"pid{os.getpid()}"
        claim = pmap.to_wire()
        # one client per member: its OWN pipeline window, dedup stream,
        # residual store, and reconnect/replay loop — shard isolation
        # on the client side mirrors process isolation on the server's
        self.clients = [
            transport.WireClient(
                addr, client=self.client_id, quant=quant,
                seed=None if seed is None else int(seed) + rank,
                deadline_s=deadline_s, partition=claim)
            for rank, addr in enumerate(addresses)]
        self._pool = ThreadPoolExecutor(
            max_workers=pmap.n, thread_name_prefix="mvtpu-fleet")

    def _fanout(self, thunks: Sequence[Any]) -> List[Any]:
        """Run per-server sub-requests concurrently; surface the first
        failure (a dead member fails ITS sub-request after its client's
        retry budget — other shards' results are already home).

        Trace linkage: the caller's request scope is captured on THIS
        thread and adopted inside every pooled thunk, so each shard's
        ``wire.client.*`` span — and through the wire context, each
        member server's spans — parent under the ONE fleet request
        (one fleet get = one tree spanning N+1 processes)."""
        if len(thunks) <= 1:
            return [t() for t in thunks]
        token = _trace.link()

        def run(t, shard):
            with _trace.adopt(token), \
                    _trace.span("fleet.fanout", shard=shard):
                return t()
        futures = [self._pool.submit(run, t, shard)
                   for shard, t in enumerate(thunks)]
        return [f.result() for f in futures]

    # -- table surface -----------------------------------------------------

    def create_array(self, name: str, size: int, *,
                     dtype: str = "float32",
                     updater: Optional[str] = None,
                     init_value: float = 0) -> FleetArrayTable:
        """Create the GLOBAL table on every member; each instantiates
        only its local slice (rank r holds bounds[r+1]-bounds[r]
        elements) from the same spec."""
        self.pmap.dense_bounds(size)    # validate split up front
        subs = self._fanout(
            [lambda c=c: c.create_array(name, size, dtype=dtype,
                                        updater=updater,
                                        init_value=init_value)
             for c in self.clients])
        return FleetArrayTable(self, subs, size)

    def create_kv(self, name: str, capacity: int, *, value_dim: int = 0,
                  dtype: str = "float32",
                  updater: Optional[str] = None,
                  tiered: bool = False) -> FleetKVTable:
        subs = self._fanout(
            [lambda c=c: c.create_kv(name, capacity,
                                     value_dim=value_dim, dtype=dtype,
                                     updater=updater, tiered=tiered)
             for c in self.clients])
        return FleetKVTable(self, subs)

    # -- fleet plumbing ----------------------------------------------------

    @property
    def n(self) -> int:
        return self.pmap.n

    def client_for(self, rank: int) -> Any:
        return self.clients[rank]

    def ping(self) -> bool:
        return all(self._fanout([c.ping for c in self.clients]))

    def server_status(self) -> List[Dict[str, Any]]:
        return self._fanout([c.server_status for c in self.clients])

    def drain(self) -> None:
        for c in self.clients:
            c.drain()

    @property
    def tx_bytes(self) -> int:
        return sum(c.tx_bytes for c in self.clients)

    @property
    def rx_bytes(self) -> int:
        return sum(c.rx_bytes for c in self.clients)

    @property
    def sheds(self) -> int:
        return sum(c.sheds for c in self.clients)

    @property
    def reconnects(self) -> int:
        return sum(c.reconnects for c in self.clients)

    def close(self) -> None:
        errors = []
        for c in self.clients:
            try:
                c.close()
            except Exception as exc:    # noqa: BLE001 — close them all
                errors.append(exc)
        self._pool.shutdown(wait=False)
        if errors:
            raise errors[0]

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect_fleet(addresses: Sequence[str], *,
                  version: int = 1,
                  kv_buckets: Optional[int] = None,
                  client: Optional[str] = None,
                  quant: Optional[str] = "env",
                  seed: Optional[int] = None,
                  deadline_s="env") -> FleetClient:
    """Dial every member of a fleet. ``addresses`` is rank-ordered;
    the map claimed at each hello is ``PartitionMap(len(addresses),
    version, kv_buckets)`` — member ranks refuse a mismatch."""
    return FleetClient(addresses, version=version,
                       kv_buckets=kv_buckets, client=client,
                       quant=quant, seed=seed, deadline_s=deadline_s)


def fleet_addresses(fleet_file: str,
                    scheme: Optional[str] = None) -> List[str]:
    """Rank-ordered member addresses out of a launcher fleet file;
    ``scheme`` picks a transport ("unix"/"tcp"/"shm") when members
    listen on several, else each member's first address wins."""
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        raise FileNotFoundError(
            f"fleet file {fleet_file!r} missing or malformed")
    members = sorted(doc.get("members", []),
                     key=lambda m: int(m.get("rank", 0)))
    out = []
    for m in members:
        addrs = list(m.get("addresses") or [])
        if not addrs:
            raise ValueError(f"fleet member {m.get('rank')} has no "
                             "addresses")
        picked = addrs[0]
        if scheme:
            for a in addrs:
                if a.split(":", 1)[0].rstrip("/") == scheme \
                        or a.startswith(scheme + "://"):
                    picked = a
                    break
        out.append(picked)
    return out


def connect_fleet_file(fleet_file: str, *,
                       scheme: Optional[str] = None,
                       client: Optional[str] = None,
                       quant: Optional[str] = "env",
                       seed: Optional[int] = None,
                       deadline_s="env") -> FleetClient:
    """Dial a fleet straight from its launcher fleet file (addresses
    AND the authoritative map come from the file)."""
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        raise FileNotFoundError(
            f"fleet file {fleet_file!r} missing or malformed")
    pmap = partition.PartitionMap.from_wire(doc["map"])
    return FleetClient(fleet_addresses(fleet_file, scheme),
                       pmap=pmap, client=client, quant=quant,
                       seed=seed, deadline_s=deadline_s)
