"""Client-side scatter-gather router over a sharded server fleet.

One :class:`~multiverso_tpu.client.transport.WireClient` talks to ONE
table server. A fleet (``python -m multiverso_tpu.server --fleet N``)
is N such servers, each owning a contiguous partition of every table
(:mod:`multiverso_tpu.server.partition`). :class:`FleetClient` makes
the fleet look like one server: it wraps N ``WireClient``\\ s and the
fleet tables split every get/add HOST-side by ownership, pipeline the
per-server sub-requests concurrently, and reassemble replies by the
inverse index — the client half of the reference's multi-server
``ProcessGet``/``ProcessAdd`` partitioning (`src/server.cpp` routes by
row hash; we route by the PartitionMap's contiguous blocks).

Why throughput scales with N: each sub-request rides its OWN
connection, so the existing ≤``MAX_PIPELINE``-unacked windows run in
parallel across servers, and each server runs its own dispatch thread,
fusion cycle, replica publisher, and admission controller over a table
1/N the size.

Layering is deliberate: :class:`FleetArrayTable` / :class:`FleetKVTable`
are thin routers over per-server ``RemoteArrayTable`` /
``RemoteKVTable`` subtables, so everything the transport already does
— pipelined windows, at-least-once resend + server dedup
(exactly-once), shed honoring, quantize-once-at-submit — applies
per shard unchanged. Each per-server ``WireClient`` owns its own
``ResidualStore``, so 1-bit error feedback stays correct *per
connection* (a shared residual across servers would leak one shard's
quantization error into another's stream). KV duplicates are pre-summed
per shard before submit (``np.unique`` + ``np.add.at``, the same
associativity CoalescingBuffer leans on), so a key appearing twice in
one batch costs one wire row and applies once.

The fleet tables present the same duck-typed surface as the remote
tables (``table_id``/``name``/``dtype``/``num_cols``/
``_attach_coalescer``/``add``/``get``/``wait``), so
``client/coalesce.py``'s CoalescingBuffer and the transport's
``DeltaBatcher`` stack on top unchanged.

Partial failure is partial: a SIGKILLed member costs ONLY its
partition. Ops routed to surviving shards keep completing (their
connections never notice); ops touching the dead shard block in that
one client's standard reconnect/replay loop and resume exactly-once
when the member returns. ``get_shard(rank)`` exposes the per-rank
subtable for exactly that kind of surviving-partition work.

Replicated ranks (``--replicas R``, ``server/replication.py``) add two
client-side behaviours on top, both read-path-only by construction:

* **Follower read routing.** When the PartitionMap carries ``replicas
  > 1`` and the fleet file lists follower addresses, bounded-staleness
  reads (``staleness=K``) are served by a STICKY replica pick —
  ``crc32(client_id) % R`` so a worker fleet spreads itself across the
  replica set while each worker keeps one warm connection — with
  fallback to the primary when the follower refuses (lag past the
  bound, structured ``stale`` refusal) or is unreachable. Unbounded
  reads (``staleness=None``) and every mutation always go to the
  primary; follower table ids are valid verbatim because followers
  build tables from the primary's forced-tid replicated creates.

* **Failover.** A shard call that exhausts its retry budget (dead
  primary) or is hello-refused with a NEWER map (someone else already
  failed over) triggers :meth:`FleetClient._recover`: re-read the
  fleet file, ``promote`` the rank's first live follower (idempotent —
  a second promote just reports the bumped map), adopt the v+1 map,
  ``rebind`` the rank's WireClient at the successor (the unacked
  pipeline window survives and replays — the follower's
  origin-(client, rid) dedup records keep the replay exactly-once),
  and broadcast ``adopt`` to the survivors so their next hellos are
  not refused. In-flight mutations that already sat in the pending
  window are NOT resubmitted — the rebind replay is their redelivery.

**Elastic fleet (live resharding).** A reshard (``--grow``/``--shrink``)
bumps the map v→v+1 with a DIFFERENT n. Committed members answer
old-map reads with a structured ``remap`` refusal (carrying the new
map) and RELAY old-map writes — applied locally where retained,
forwarded to the new owner, exactly-once via the origin dedup — so
nothing is lost while this router catches up. On the first ``remap``
(or a hello refusal claiming a different n) the router re-reads the
fleet file with jittered backoff (an N-worker fleet must not
thundering-herd the file at the flip), rebinds surviving rank clients
under the new claim, dials joining ranks, drops evicted ones, re-splits
every fleet table's bounds, and retries the interrupted operation under
the new ownership.

jax-free and file-path loadable (:func:`load_router`) like the
transport — this is worker-process code.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _dep(modname: str, *relpath: str):
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    if "multiverso_tpu" in sys.modules:
        import importlib
        return importlib.import_module(modname)
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, *relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(modname, None)
        raise
    return mod


transport = _dep("multiverso_tpu.client.transport",
                 "client", "transport.py")
partition = _dep("multiverso_tpu.server.partition",
                 "server", "partition.py")
_trace = _dep("multiverso_tpu.telemetry.trace", "telemetry",
              "trace.py")


#: faults that mean "the peer may be gone", not "the request is bad":
#: connection-level errors and an exhausted retry budget trigger the
#: failover path; RemoteError (an application refusal) never does
_DEAD = (ConnectionError, OSError, transport._retry.RetryError)
#: a hello refusal — carries the server's CURRENT map on ``.header``
_REFUSED = transport.wire.WireProtocolError
#: how long a follower stays benched after a hard (transport) miss
#: before reads probe it again
_REPLICA_RETRY_S = 5.0


class _Remapped(Exception):
    """Internal: the fleet changed SHAPE (n) under this operation; the
    tables were re-split — re-run the whole op under the new map."""


def _count(name: str, n: float = 1, **labels) -> None:
    m = sys.modules.get("multiverso_tpu.telemetry.metrics")
    if m is not None:
        try:
            m.counter(name, **labels).inc(n)
        except Exception:
            pass


def _pick_addr(addrs: Sequence[str],
               scheme: Optional[str] = None) -> Optional[str]:
    """First address, or the first matching ``scheme`` when given."""
    addrs = list(addrs or [])
    if not addrs:
        return None
    if scheme:
        for a in addrs:
            if a.split(":", 1)[0].rstrip("/") == scheme \
                    or a.startswith(scheme + "://"):
                return a
    return addrs[0]


def _clone_sub(sub: Any, client: "transport.WireClient") -> Any:
    """A follower-facing twin of a primary subtable: same table id
    (forced-tid replicated creates keep follower id spaces aligned),
    same dtype/geometry, different connection."""
    meta: Dict[str, Any] = {"table": sub.table_id, "name": sub.name,
                            "kind": sub.kind,
                            "dtype": np.dtype(sub.dtype).str}
    if hasattr(sub, "value_dim"):
        meta["value_dim"] = sub.value_dim
        return transport.RemoteKVTable(client, meta)
    meta["size"] = sub.size
    return transport.RemoteArrayTable(client, meta)


def load_router(package_dir: str):
    """File-path load this module (canonical name, no package import)
    from a bare worker script. ``package_dir`` is the
    ``multiverso_tpu`` directory."""
    modname = "multiverso_tpu.client.router"
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    import importlib.util
    path = os.path.join(package_dir, "client", "router.py")
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


class FleetHandle:
    """Handle-compatible future over the per-shard handles of one
    logical mutation. ``done()``/``wait()`` quantify over every shard
    the op actually touched. When built by a fleet table the wait path
    runs through the fleet's failover guard, so waiting out a window
    that straddles a primary death completes against the promoted
    follower instead of raising."""

    def __init__(self, handles: Sequence[Any],
                 fleet: Optional["FleetClient"] = None,
                 ranks: Optional[Sequence[int]] = None) -> None:
        self._handles = list(handles)
        self._fleet = fleet
        self._ranks = list(ranks) if ranks is not None \
            else list(range(len(self._handles)))

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self) -> None:
        if self._fleet is None:
            for h in self._handles:
                h.wait()
            return
        for rank, h in zip(self._ranks, self._handles):
            self._fleet._guard_wait(rank, h)

    def result(self) -> None:
        return self.wait()


class _FleetTable:
    """Shared router surface (the CoalescingBuffer duck type, same as
    ``transport._RemoteTable``)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any]) -> None:
        self.fleet = fleet
        self.subs = list(subs)          # rank-ordered per-server tables
        head = self.subs[0]
        self.table_id = head.table_id   # names the table in coalescers
        self.name = head.name
        self.kind = head.kind
        self.dtype = head.dtype
        self._coalescers: List[Any] = []

    @property
    def pmap(self) -> "partition.PartitionMap":
        return self.fleet.pmap

    def get_shard(self, rank: int):
        """The per-rank remote subtable — the surface that keeps
        serving a surviving partition while another member is down."""
        return self.subs[rank]

    def _attach_coalescer(self, buf: Any) -> None:
        self._coalescers.append(buf)

    def flush_coalesced(self) -> None:
        for buf in self._coalescers:
            buf.flush()

    def _resplit(self) -> None:
        """Rebind this table to the fleet's CURRENT client list after
        a reshard: one subtable per new rank (same table id — forced-
        tid manifests keep every member's id space aligned), bounds
        recomputed by the subclass."""
        head = self.subs[0]
        self.subs = [_clone_sub(head, c) for c in self.fleet.clients]

    def _retry_remap(self, thunk: Any) -> Any:
        """Run one whole-table op, re-running it when a reshard
        re-split the table underneath it (bounded — a second flip
        mid-retry is a second re-split, not a loop)."""
        for _ in range(3):
            try:
                return thunk()
            except _Remapped:
                _count("fleet.reshard.resplit", table=self.name)
        raise RuntimeError(
            f"fleet table {self.name!r}: partition map kept moving "
            "across 3 re-splits — aborting this op")

    def wait(self) -> None:
        for rank in range(len(self.subs)):
            self.fleet._guard_drain(rank)

    def _shard_get(self, rank: int, *args: Any,
                   staleness: Optional[int] = None) -> Any:
        """One shard's read, replica-routed: try the sticky follower
        when the read is bounded-staleness and the rank has one, fall
        back to the primary on a structured ``stale`` refusal (lag past
        the bound) or any transport fault — a lagging or dead follower
        costs one extra hop, never an error. The primary leg runs under
        the failover guard."""
        fleet = self.fleet
        rsub = fleet._replica_sub(self, rank, staleness)
        if rsub is not None:
            try:
                out = rsub.get(*args, staleness=staleness)
                fleet._replica_served(rank)
                return out
            except transport.RemoteError as exc:
                header = getattr(exc, "header", None) or {}
                if not (header.get("stale") or header.get("follower")
                        or header.get("remap")):
                    raise       # a real application error, not routing
                # remap: the follower committed a reshard this router
                # hasn't seen — the primary leg will refuse too and
                # drive the re-split through the guard
                fleet._replica_miss(rank, soft=True)
            except (_REFUSED,) + _DEAD:
                fleet._replica_miss(rank, soft=False)
        return fleet._guard(
            rank,
            lambda: self.subs[rank].get(*args, staleness=staleness))


class FleetArrayTable(_FleetTable):
    """Dense 1-D table scattered across the fleet by contiguous
    element ranges (rank r serves global elements [bounds[r],
    bounds[r+1]) as ITS local rows 0..len)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any],
                 size: int) -> None:
        super().__init__(fleet, subs)
        self.size = int(size)
        self.num_cols = 1
        self._bounds = fleet.pmap.dense_bounds(self.size)

    def _resplit(self) -> None:
        super()._resplit()
        self._bounds = self.fleet.pmap.dense_bounds(self.size)

    def get(self, staleness: Optional[int] = None) -> np.ndarray:
        """Whole-table scatter-gather: each server returns its shard
        concurrently; concat in rank order is the inverse map (the
        zero-index-math payoff of contiguous ownership)."""
        def attempt():
            parts = self.fleet._fanout(
                [lambda r=r: self._shard_get(r, staleness=staleness)
                 for r in range(len(self.subs))])
            return np.concatenate(parts)
        with _trace.request("fleet.get", table=self.name):
            return self._retry_remap(attempt)

    def get_range(self, lo: int, hi: int,
                  staleness: Optional[int] = None) -> np.ndarray:
        """Elements [lo, hi) — fetched ONLY from the shards whose
        ranges overlap it. This is the partitioning payoff a single
        server cannot offer: its wire ``get`` is a whole-table
        snapshot, so a range read there ships every element; here a
        shard-aligned range ships 1/N of the bytes end to end."""
        lo, hi = int(lo), int(hi)
        if not 0 <= lo < hi <= self.size:
            raise ValueError(
                f"range [{lo}, {hi}) out of bounds for size {self.size}")

        def attempt():
            b = self._bounds
            ranks = [r for r in range(self.pmap.n)
                     if b[r] < hi and b[r + 1] > lo]
            parts = self.fleet._fanout(
                [lambda r=r: self._shard_get(r, staleness=staleness)
                 for r in ranks])
            if len(parts) == 1:
                r = ranks[0]
                return parts[0][lo - b[r]:hi - b[r]]
            first = ranks[0]
            return np.concatenate(parts)[lo - b[first]:hi - b[first]]
        with _trace.request("fleet.get_range", table=self.name,
                            lo=lo, hi=hi):
            return self._retry_remap(attempt)

    def add(self, delta, option=None, sync: bool = False) -> FleetHandle:
        """Split the global delta by ownership; each slice is submitted
        on its own pipelined connection (quantized there, against that
        connection's residual store)."""
        delta = np.asarray(delta, self.dtype)
        if delta.shape != (self.size,):
            raise ValueError(
                f"fleet add to {self.name!r} expects shape "
                f"({self.size},), got {delta.shape}")
        subs, b = list(self.subs), self._bounds
        handles, ranks = [], []
        with _trace.request("fleet.add", table=self.name):
            for r, sub in enumerate(subs):
                try:
                    handles.append(self.fleet._guard_add(
                        r, lambda sub=sub, lo=b[r], hi=b[r + 1]:
                        sub.add(delta[lo:hi], option)))
                    ranks.append(r)
                except _Remapped:
                    # the fleet changed shape under this op and rank
                    # r's slice never landed (its member is gone):
                    # redistribute JUST that slice by the new bounds —
                    # slices already submitted to survivors are relayed
                    # server-side, resubmitting them would double-apply
                    _count("fleet.reshard.resplit", table=self.name)
                    for r2, h2 in self._readd_range(
                            delta, b[r], b[r + 1], option):
                        handles.append(h2)
                        ranks.append(r2)
        handle = FleetHandle(handles, self.fleet, ranks)
        if sync:
            handle.wait()
        return handle

    add_async = add

    def _readd_range(self, delta: np.ndarray, glo: int, ghi: int,
                     option) -> List[Tuple[int, Any]]:
        """Submit global elements [glo, ghi) of ``delta`` by CURRENT
        ownership, zero-padded to each new owner's full local range."""
        b = self._bounds
        out = []
        for r in range(self.pmap.n):
            lo, hi = max(glo, b[r]), min(ghi, b[r + 1])
            if lo >= hi:
                continue
            local = np.zeros(b[r + 1] - b[r], self.dtype)
            local[lo - b[r]: hi - b[r]] = delta[lo:hi]
            out.append((r, self.fleet._guard_add(
                r, lambda sub=self.subs[r], d=local:
                sub.add(d, option))))
        return out


class FleetKVTable(_FleetTable):
    """Hashed KV table scattered by contiguous logical-bucket blocks:
    a key's splitmix64 bucket picks its owning rank, forever (until a
    map-version bump)."""

    def __init__(self, fleet: "FleetClient", subs: Sequence[Any]) -> None:
        super().__init__(fleet, subs)
        head = self.subs[0]
        self.value_dim = head.value_dim
        self.num_cols = head.num_cols

    def _route(self, keys: np.ndarray
               ) -> List[Tuple[int, np.ndarray]]:
        """(rank, positions-into-keys) per rank that owns >= 1 key."""
        owner = self.pmap.kv_owner(keys)
        out = []
        for r in range(self.pmap.n):
            idx = np.nonzero(owner == r)[0]
            if idx.size:
                out.append((r, idx))
        return out

    def get(self, keys, staleness: Optional[int] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
        """Batch lookup fanned out by ownership, reassembled into the
        caller's key order via the inverse index."""
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        n = keys.shape[0]

        def attempt():
            shape = (n, self.value_dim) if self.value_dim else (n,)
            values = np.zeros(shape, self.dtype)
            found = np.zeros(n, bool)
            routed = self._route(keys)
            replies = self.fleet._fanout(
                [lambda r=r, idx=idx: self._shard_get(
                    r, keys[idx], staleness=staleness)
                 for r, idx in routed])
            for (r, idx), (vals, fnd) in zip(routed, replies):
                values[idx] = vals
                found[idx] = fnd
            return values, found
        with _trace.request("fleet.kv_get", table=self.name):
            return self._retry_remap(attempt)

    def add(self, keys, deltas, option=None,
            sync: bool = False) -> FleetHandle:
        """Scatter an add by ownership, pre-summing duplicate keys per
        shard first — one wire row per distinct key, one apply per
        distinct key, same associative-sum contract the server's own
        fused batches use."""
        keys = np.ascontiguousarray(np.asarray(keys, np.uint64))
        deltas = np.asarray(deltas, self.dtype)
        handles = []
        ranks = []
        with _trace.request("fleet.kv_add", table=self.name):
            subs = list(self.subs)
            for r, idx in self._route(keys):
                sub_keys = keys[idx]
                sub_deltas = deltas[idx]
                uniq, inv = np.unique(sub_keys, return_inverse=True)
                if uniq.shape[0] != sub_keys.shape[0]:
                    acc = np.zeros(
                        (uniq.shape[0],) + sub_deltas.shape[1:],
                        sub_deltas.dtype)
                    np.add.at(acc, inv, sub_deltas)
                    sub_keys, sub_deltas = uniq, acc
                try:
                    handles.append(self.fleet._guard_add(
                        r, lambda sub=subs[r], k=sub_keys,
                        d=sub_deltas: sub.add(k, d, option)))
                    ranks.append(r)
                except _Remapped:
                    # redistribute ONLY this rank's keys by the new
                    # ownership (survivor submits relay server-side)
                    _count("fleet.reshard.resplit", table=self.name)
                    owner = self.pmap.kv_owner(sub_keys)
                    for r2 in np.unique(owner):
                        sel = owner == r2
                        handles.append(self.fleet._guard_add(
                            int(r2),
                            lambda sub=self.subs[int(r2)],
                            k=sub_keys[sel], d=sub_deltas[sel]:
                            sub.add(k, d, option)))
                        ranks.append(int(r2))
        handle = FleetHandle(handles, self.fleet, ranks)
        if sync:
            handle.wait()
        return handle

    add_async = add


class FleetClient:
    """N ``WireClient``\\ s + one :class:`PartitionMap` = one logical
    parameter server (see module docstring)."""

    def __init__(self, addresses: Sequence[str], *,
                 pmap: Optional["partition.PartitionMap"] = None,
                 version: int = 1,
                 kv_buckets: Optional[int] = None,
                 replicas: int = 1,
                 client: Optional[str] = None,
                 quant: Optional[str] = "env",
                 seed: Optional[int] = None,
                 deadline_s="env",
                 fleet_file: Optional[str] = None,
                 scheme: Optional[str] = None,
                 replica_addrs: Optional[
                     Sequence[Sequence[str]]] = None,
                 read_replica="env") -> None:
        addresses = list(addresses)
        if not addresses:
            raise ValueError("fleet needs at least one server address")
        if pmap is None:
            pmap = partition.PartitionMap(
                len(addresses), version=version, kv_buckets=kv_buckets,
                replicas=replicas)
        if pmap.n != len(addresses):
            raise ValueError(
                f"partition map is for {pmap.n} servers, got "
                f"{len(addresses)} addresses")
        self.pmap = pmap
        self.client_id = client or f"pid{os.getpid()}"
        self._claim = pmap.to_wire()
        self._deadline_s = deadline_s
        self._fleet_file = fleet_file
        self._scheme = scheme
        self._quant = quant
        self._seed = seed
        self._tables: List[_FleetTable] = []
        # one client per member: its OWN pipeline window, dedup stream,
        # residual store, and reconnect/replay loop — shard isolation
        # on the client side mirrors process isolation on the server's
        self.clients = [
            transport.WireClient(
                addr, client=self.client_id, quant=quant,
                seed=None if seed is None else int(seed) + rank,
                deadline_s=deadline_s, partition=self._claim)
            for rank, addr in enumerate(addresses)]
        # ONE persistent pool per fleet client (never a thread per
        # get): sub-requests outlive none of these workers, and the
        # replica fallback is a second sequential hop on the same
        # worker, so pmap.n workers cover every fan-out shape
        self._pool = ThreadPoolExecutor(
            max_workers=pmap.n, thread_name_prefix="mvtpu-fleet")
        # -- replica read routing state --
        # rank -> [follower addresses]; static override (tests) wins,
        # else the launcher fleet file's per-member "replicas" rows
        if replica_addrs is not None:
            self._replica_addrs = [list(a) for a in replica_addrs]
        else:
            self._replica_addrs = self._load_replica_addrs()
        self._replica_clients: Dict[int, Any] = {}
        self._replica_subs: Dict[Tuple[int, int], Any] = {}
        self._replica_down: Dict[int, float] = {}
        self._rlock = threading.Lock()
        # reentrant: _recover may escalate to _restructure (reshard)
        self._folock = threading.RLock()
        reads_on = os.environ.get(
            "MVTPU_REPLICA_READS", "1").strip().lower() \
            not in ("0", "false", "off", "no")
        if read_replica == "env":
            raw = os.environ.get("MVTPU_REPLICA_PICK", "").strip()
            if raw:
                pick = int(raw)
            else:
                # sticky per client: worker fleets hash themselves
                # uniformly across the replica set (0 = primary)
                pick = zlib.crc32(self.client_id.encode()) \
                    % max(int(pmap.replicas), 1)
        else:
            pick = int(read_replica or 0)
        self._replica_pick = pick if reads_on else 0

    def _load_replica_addrs(self) -> List[List[str]]:
        doc = partition.read_fleet_file(self._fleet_file) \
            if self._fleet_file else None
        if doc is None:
            return [[] for _ in range(self.pmap.n)]
        return self._replica_addrs_from(doc)

    def _replica_addrs_from(self, doc: Dict[str, Any]
                            ) -> List[List[str]]:
        members = sorted(doc.get("members", []),
                         key=lambda m: int(m.get("rank", 0)))
        out: List[List[str]] = [[] for _ in range(self.pmap.n)]
        for m in members:
            rank = int(m.get("rank", 0))
            if not 0 <= rank < self.pmap.n:
                continue
            for rep in (m.get("replicas") or []):
                a = _pick_addr(rep.get("addresses"), self._scheme)
                if a:
                    out[rank].append(a)
        return out

    def _fanout(self, thunks: Sequence[Any]) -> List[Any]:
        """Run per-server sub-requests concurrently; surface the first
        failure (a dead member fails ITS sub-request after its client's
        retry budget — other shards' results are already home).

        Trace linkage: the caller's request scope is captured on THIS
        thread and adopted inside every pooled thunk, so each shard's
        ``wire.client.*`` span — and through the wire context, each
        member server's spans — parent under the ONE fleet request
        (one fleet get = one tree spanning N+1 processes)."""
        if len(thunks) <= 1:
            return [t() for t in thunks]
        token = _trace.link()

        def run(t, shard):
            with _trace.adopt(token), \
                    _trace.span("fleet.fanout", shard=shard):
                return t()
        futures = [self._pool.submit(run, t, shard)
                   for shard, t in enumerate(thunks)]
        return [f.result() for f in futures]

    # -- replica read routing ----------------------------------------------

    def _replica_sub(self, table: _FleetTable, rank: int,
                     staleness: Optional[int]) -> Optional[Any]:
        """The follower subtable a read on ``rank`` should try first,
        or None when the read must go to the primary: unbounded reads
        (a follower cannot serve read-your-writes honestly), a pick of
        0 (this client is sticky-primary), no followers for the rank,
        or a follower benched after a recent hard miss."""
        if staleness is None or self._replica_pick <= 0:
            return None
        addrs = self._replica_addrs[rank] \
            if rank < len(self._replica_addrs) else []
        if not addrs:
            return None
        if time.monotonic() < self._replica_down.get(rank, 0.0):
            return None
        key = (id(table), rank)
        sub = self._replica_subs.get(key)
        if sub is not None:
            return sub
        with self._rlock:
            sub = self._replica_subs.get(key)
            if sub is not None:
                return sub
            c = self._replica_clients.get(rank)
            if c is None:
                idx = min(self._replica_pick, len(addrs)) - 1
                try:
                    c = transport.WireClient(
                        addrs[idx], client=self.client_id,
                        quant=None, deadline_s=self._deadline_s,
                        partition=dict(self._claim))
                except Exception:   # noqa: BLE001 — dead follower:
                    # bench it, reads fall back to the primary
                    self._replica_down[rank] = \
                        time.monotonic() + _REPLICA_RETRY_S
                    _count("fleet.replica.down", rank=rank)
                    return None
                self._replica_clients[rank] = c
            sub = _clone_sub(table.subs[rank], c)
            self._replica_subs[key] = sub
            return sub

    def _replica_served(self, rank: int) -> None:
        _count("fleet.replica.reads", rank=rank)

    def _replica_miss(self, rank: int, *, soft: bool) -> None:
        """A follower read that fell back to the primary. Soft (stale
        refusal) keeps the connection — lag is transient; hard
        (transport fault) benches the follower and drops its client so
        the next probe redials."""
        _count("fleet.replica.fallbacks", rank=rank,
               kind="stale" if soft else "down")
        if soft:
            return
        with self._rlock:
            c = self._replica_clients.pop(rank, None)
            for key in [k for k in self._replica_subs
                        if k[1] == rank]:
                self._replica_subs.pop(key, None)
            self._replica_down[rank] = \
                time.monotonic() + _REPLICA_RETRY_S
        if c is not None:
            try:
                c.abort()
            except Exception:   # noqa: BLE001 — already dead
                pass

    # -- failover ----------------------------------------------------------

    def _guard(self, rank: int, thunk: Any) -> Any:
        """Run a shard request; on a dead-peer fault or a newer-map
        hello refusal, recover the rank (promotion, adoption, or — on
        a shape change — a full re-split, surfaced as ``_Remapped`` so
        the table re-runs the whole op) and re-run it once.
        Application errors pass through untouched — except a reshard
        ``remap`` refusal, which IS the re-split trigger."""
        try:
            return thunk()
        except transport.RemoteError as exc:
            if not self._maybe_remap(exc):
                raise
            raise _Remapped() from exc
        except (_REFUSED,) + _DEAD as exc:
            n0 = self.pmap.n
            if not self._recover(rank, exc):
                raise
            if self.pmap.n != n0 or rank >= len(self.clients):
                raise _Remapped() from exc
            return thunk()

    def _guard_add(self, rank: int, thunk: Any) -> Any:
        """Failover guard for PIPELINED mutations. The failed submit's
        frame already sits in the rank client's pending window, so
        re-running the thunk would double-submit it under a fresh rid;
        the rebind replay is the redelivery — hand back a handle over
        the surviving window instead. A shape-change recovery raises
        ``_Remapped``: the rank may not exist any more, the table
        redistributes the slice."""
        try:
            return thunk()
        except transport.RemoteError as exc:
            if not self._maybe_remap(exc):
                raise
            raise _Remapped() from exc
        except (_REFUSED,) + _DEAD as exc:
            n0 = self.pmap.n
            if not self._recover(rank, exc):
                raise
            if self.pmap.n != n0 or rank >= len(self.clients):
                raise _Remapped() from exc
            c = self.clients[rank]
            rid = c._pending[-1].rid if c._pending else c._acked_rid
            return transport.RemoteHandle(c, rid)

    def _guard_wait(self, rank: int, handle: Any) -> None:
        try:
            handle.wait()
        except transport.RemoteError as exc:
            if not self._maybe_remap(exc):
                raise
            # resharded mid-wait: survivors' windows replayed at the
            # rebind; an evicted rank's acked writes were relayed
        except (_REFUSED,) + _DEAD as exc:
            if not self._recover(rank, exc):
                raise
            if rank >= len(self.clients):
                return
            handle.wait()

    def _guard_drain(self, rank: int) -> None:
        if rank >= len(self.clients):
            return      # evicted mid-wait by a reshard
        try:
            self.clients[rank].drain()
        except transport.RemoteError as exc:
            if not self._maybe_remap(exc):
                raise
        except (_REFUSED,) + _DEAD as exc:
            if not self._recover(rank, exc):
                raise
            if rank >= len(self.clients):
                return
            self.clients[rank].drain()

    # -- elastic fleet (live resharding) ------------------------------------

    def _maybe_remap(self, exc: BaseException) -> bool:
        """True iff ``exc`` is a reshard ``remap`` refusal AND the
        router successfully re-split onto the new map."""
        header = getattr(exc, "header", None) or {}
        wmap = header.get("partition")
        if not header.get("remap") or not isinstance(wmap, dict):
            return False
        return self._restructure(int(wmap.get("version", 0)))

    def _refresh_fleet(self, min_version: int) -> Dict[str, Any]:
        """Re-read the fleet file until it reaches ``min_version``,
        with JITTERED exponential backoff — at a map flip every worker
        of an N-worker fleet lands here at once, and the jitter (seeded
        per client id, so it is deterministic per worker but spread
        across the fleet) keeps them from thundering-herding the file
        while the admin's atomic rewrite is still in flight."""
        if not self._fleet_file:
            raise RuntimeError(
                f"fleet resharded to v{min_version} but this client "
                "was not connected via a fleet file — reconnect with "
                "connect_fleet_file to follow elastic fleets")
        tries = int(os.environ.get(
            "MVTPU_FLEET_REFRESH_TRIES", "") or 12)
        rng = random.Random(zlib.crc32(self.client_id.encode()))
        delay = 0.05
        for attempt in range(tries):
            doc = partition.read_fleet_file(self._fleet_file)
            got = int((doc.get("map") or {}).get("version", 0)) \
                if doc is not None else None
            if got is not None and got >= min_version:
                return doc
            _count("fleet.refresh.retry")
            time.sleep(delay * (0.5 + rng.random()))
            delay = min(delay * 2.0, 1.0)
        raise RuntimeError(
            f"fleet file {self._fleet_file!r} is still at "
            f"v{got} after {tries} re-reads but the fleet serves "
            f"v{min_version}: the reshard's fleet-file flip never "
            "landed (admin crashed mid-commit?) — raise "
            "MVTPU_FLEET_REFRESH_TRIES or re-run the reshard")

    def _restructure(self, min_version: int) -> bool:
        """Swing this router onto a DIFFERENT-SHAPE map (reshard):
        refresh the fleet file, rebind every surviving rank's client
        under the new claim (pending windows replay — the members'
        relay + origin dedup keep that exactly-once), dial joining
        ranks, drop evicted ones, resize the fan-out pool, and
        re-split every fleet table."""
        with self._folock:
            if self.pmap.version >= min_version:
                return True     # raced: another thread re-split first
            doc = self._refresh_fleet(min_version)
            new = partition.PartitionMap.from_wire(doc["map"])
            members = sorted(doc.get("members", []),
                             key=lambda m: int(m.get("rank", 0)))
            addrs = [_pick_addr(m.get("addresses"), self._scheme)
                     for m in members]
            if len(addrs) != new.n or any(a is None for a in addrs):
                raise RuntimeError(
                    f"fleet file {self._fleet_file!r} lists "
                    f"{len(addrs)} member addresses for a map of "
                    f"{new.n}")
            claim = new.to_wire()
            old_n = len(self.clients)
            for r in range(min(old_n, new.n)):
                self.clients[r].rebind(addrs[r],
                                       partition=dict(claim))
            for c in self.clients[new.n:]:
                try:    # evicted member: acked writes were relayed
                    c.abort()
                except Exception:   # noqa: BLE001
                    pass
            self.clients = self.clients[:new.n] + [
                transport.WireClient(
                    addrs[r], client=self.client_id,
                    quant=self._quant,
                    seed=None if self._seed is None
                    else int(self._seed) + r,
                    deadline_s=self._deadline_s,
                    partition=dict(claim))
                for r in range(old_n, new.n)]
            self.pmap = new
            self._claim = claim
            # replica routing: follower sets moved with their ranks
            with self._rlock:
                dead = list(self._replica_clients.values())
                self._replica_clients.clear()
                self._replica_subs.clear()
                self._replica_down.clear()
            for c in dead:
                try:
                    c.abort()
                except Exception:   # noqa: BLE001
                    pass
            self._replica_addrs = self._replica_addrs_from(doc)
            old_pool = self._pool
            self._pool = ThreadPoolExecutor(
                max_workers=new.n, thread_name_prefix="mvtpu-fleet")
            old_pool.shutdown(wait=False)
            for t in self._tables:
                t._resplit()
            _count("fleet.reshard.refresh")
            return True

    def _recover(self, rank: int, exc: BaseException) -> bool:
        """Client half of shard failover. Serialized: concurrent shard
        threads that hit the same dead primary queue here, the first
        one promotes, the rest find the map already bumped and just
        re-run their request against the rebound client. Returns True
        when the rank is routable again."""
        with self._folock:
            start_v = self.pmap.version
            header = getattr(exc, "header", None) or {}
            wmap = header.get("partition")
            if isinstance(wmap, dict) \
                    and int(wmap.get("version", 0)) > start_v:
                if int(wmap.get("n", self.pmap.n)) != self.pmap.n:
                    # the fleet changed SHAPE (reshard), not just
                    # leadership: full re-split, not a rank rebind
                    return self._restructure(
                        int(wmap.get("version", 0)))
                # refused BECAUSE someone already failed over: the
                # refusal carries the new map — adopt, no promote
                return self._adopt_map(wmap, rank)
            doc = partition.read_fleet_file(self._fleet_file) \
                if self._fleet_file else None
            if doc is not None:
                dmap = doc.get("map") or {}
                if int(dmap.get("version", 0)) > start_v:
                    if int(dmap.get("n", self.pmap.n)) \
                            != self.pmap.n:
                        return self._restructure(
                            int(dmap.get("version", 0)))
                    # another worker promoted and rewrote the file
                    return self._adopt_map(dmap, rank, doc=doc)
            if self.pmap.version > start_v:
                return True     # a queued thread behind the promoter
            addrs = self._follower_addrs(rank, doc)
            if not addrs:
                return False
            for addr in addrs:
                try:
                    c = transport.WireClient(
                        addr, client=self.client_id + ".fo",
                        quant=None, deadline_s=None,
                        partition=dict(self._claim))
                except Exception:   # noqa: BLE001 — follower dead too
                    continue
                try:
                    try:
                        h, _ = c.call("promote")
                    finally:
                        try:
                            c.abort()
                        except Exception:   # noqa: BLE001
                            pass
                except _REFUSED as refusal:
                    rh = getattr(refusal, "header", None) or {}
                    wm = rh.get("partition")
                    if isinstance(wm, dict) \
                            and int(wm.get("version", 0)) > start_v:
                        # the follower is ALREADY the new primary
                        return self._adopt_map(wm, rank,
                                               fallback=addr)
                    continue
                except _DEAD:
                    continue
                wm = h.get("partition")
                if isinstance(wm, dict):
                    return self._adopt_map(wm, rank, fallback=addr)
            return False

    def _follower_addrs(self, rank: int,
                        doc: Optional[Dict[str, Any]]) -> List[str]:
        if doc is not None:
            fresh = self._replica_addrs_from(doc)
            if rank < len(fresh) and fresh[rank]:
                return fresh[rank]
        return list(self._replica_addrs[rank]) \
            if rank < len(self._replica_addrs) else []

    def _adopt_map(self, wmap: Dict[str, Any], rank: int,
                   doc: Optional[Dict[str, Any]] = None,
                   fallback: Optional[str] = None) -> bool:
        """Swing the fleet onto a newer map: rebind the dead rank's
        client at its successor (pending window replays there), point
        every future hello at the new claim, and best-effort broadcast
        ``adopt`` so survivors bump before their next refused hello."""
        new = partition.PartitionMap.from_wire(wmap)
        if new.version <= self.pmap.version:
            return True     # lost a race to an even newer adoption
        claim = new.to_wire()
        addr = fallback
        if self._fleet_file:
            d = doc
            if d is None or int((d.get("map") or {})
                                .get("version", -1)) < new.version:
                d = partition.read_fleet_file(self._fleet_file)
            if d is not None and int((d.get("map") or {})
                                     .get("version", -1)) \
                    >= new.version:
                members = sorted(d.get("members", []),
                                 key=lambda m: int(m.get("rank", 0)))
                if rank < len(members):
                    picked = _pick_addr(
                        members[rank].get("addresses"), self._scheme)
                    if picked:
                        addr = picked
                self._replica_addrs = self._replica_addrs_from(d)
        if addr is None:
            return False
        self.pmap = new
        self._claim = claim
        self.clients[rank].rebind(addr, partition=claim)
        for c in self.clients:
            c.partition = dict(claim)
        # this rank's follower read path is void: its follower may BE
        # the new primary; reads route primary until addrs say else
        with self._rlock:
            dead_rc = self._replica_clients.pop(rank, None)
            for key in [k for k in self._replica_subs
                        if k[1] == rank]:
                self._replica_subs.pop(key, None)
        if dead_rc is not None:
            try:
                dead_rc.abort()
            except Exception:   # noqa: BLE001
                pass
        _count("fleet.failover", rank=rank)
        for r, c in enumerate(self.clients):
            if r == rank:
                continue    # the promoted server already holds v+1
            try:
                c.call("adopt", {"map": dict(claim)})
            except Exception:   # noqa: BLE001 — their next refused
                pass            # hello self-heals via err.header
        for c in list(self._replica_clients.values()):
            try:
                c.call("adopt", {"map": dict(claim)})
            except Exception:   # noqa: BLE001
                pass
        return True

    # -- table surface -----------------------------------------------------

    def create_array(self, name: str, size: int, *,
                     dtype: str = "float32",
                     updater: Optional[str] = None,
                     init_value: float = 0) -> FleetArrayTable:
        """Create the GLOBAL table on every member; each instantiates
        only its local slice (rank r holds bounds[r+1]-bounds[r]
        elements) from the same spec."""
        self.pmap.dense_bounds(size)    # validate split up front
        # guarded: creates are idempotent by name server-side, so the
        # post-failover re-run attaches instead of re-building
        subs = self._fanout(
            [lambda c=c, r=r: self._guard(
                r, lambda: c.create_array(name, size, dtype=dtype,
                                          updater=updater,
                                          init_value=init_value))
             for r, c in enumerate(self.clients)])
        table = FleetArrayTable(self, subs, size)
        self._tables.append(table)
        return table

    def create_kv(self, name: str, capacity: int, *, value_dim: int = 0,
                  dtype: str = "float32",
                  updater: Optional[str] = None,
                  tiered: bool = False) -> FleetKVTable:
        subs = self._fanout(
            [lambda c=c, r=r: self._guard(
                r, lambda: c.create_kv(name, capacity,
                                       value_dim=value_dim,
                                       dtype=dtype, updater=updater,
                                       tiered=tiered))
             for r, c in enumerate(self.clients)])
        table = FleetKVTable(self, subs)
        self._tables.append(table)
        return table

    # -- fleet plumbing ----------------------------------------------------

    @property
    def n(self) -> int:
        return self.pmap.n

    def client_for(self, rank: int) -> Any:
        return self.clients[rank]

    def ping(self) -> bool:
        return all(self._fanout([c.ping for c in self.clients]))

    def server_status(self) -> List[Dict[str, Any]]:
        return self._fanout([c.server_status for c in self.clients])

    def drain(self) -> None:
        for rank in range(len(self.clients)):
            self._guard_drain(rank)

    @property
    def tx_bytes(self) -> int:
        return sum(c.tx_bytes for c in self.clients)

    @property
    def rx_bytes(self) -> int:
        return sum(c.rx_bytes for c in self.clients)

    @property
    def sheds(self) -> int:
        return sum(c.sheds for c in self.clients)

    @property
    def reconnects(self) -> int:
        return sum(c.reconnects for c in self.clients)

    def close(self) -> None:
        errors = []
        for c in self.clients:
            try:
                c.close()
            except Exception as exc:    # noqa: BLE001 — close them all
                errors.append(exc)
        with self._rlock:
            rclients = list(self._replica_clients.values())
            self._replica_clients.clear()
            self._replica_subs.clear()
        for c in rclients:
            try:    # read-only connections: nothing pending to drain
                c.abort()
            except Exception:   # noqa: BLE001
                pass
        self._pool.shutdown(wait=False)
        if errors:
            raise errors[0]

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect_fleet(addresses: Sequence[str], *,
                  version: int = 1,
                  kv_buckets: Optional[int] = None,
                  replicas: int = 1,
                  client: Optional[str] = None,
                  quant: Optional[str] = "env",
                  seed: Optional[int] = None,
                  deadline_s="env",
                  replica_addrs: Optional[
                      Sequence[Sequence[str]]] = None,
                  read_replica="env") -> FleetClient:
    """Dial every member of a fleet. ``addresses`` is rank-ordered;
    the map claimed at each hello is ``PartitionMap(len(addresses),
    version, kv_buckets, replicas)`` — member ranks refuse a mismatch.
    ``replica_addrs`` (rank-ordered lists of follower addresses) opts
    bounded-staleness reads into follower routing without a fleet
    file."""
    return FleetClient(addresses, version=version,
                       kv_buckets=kv_buckets, replicas=replicas,
                       client=client, quant=quant, seed=seed,
                       deadline_s=deadline_s,
                       replica_addrs=replica_addrs,
                       read_replica=read_replica)


def fleet_addresses(fleet_file: str,
                    scheme: Optional[str] = None) -> List[str]:
    """Rank-ordered member addresses out of a launcher fleet file;
    ``scheme`` picks a transport ("unix"/"tcp"/"shm") when members
    listen on several, else each member's first address wins."""
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        raise FileNotFoundError(
            f"fleet file {fleet_file!r} missing or malformed")
    members = sorted(doc.get("members", []),
                     key=lambda m: int(m.get("rank", 0)))
    out = []
    for m in members:
        picked = _pick_addr(m.get("addresses"), scheme)
        if picked is None:
            raise ValueError(f"fleet member {m.get('rank')} has no "
                             "addresses")
        out.append(picked)
    return out


def replica_addresses(fleet_file: str,
                      scheme: Optional[str] = None
                      ) -> List[List[str]]:
    """Rank-ordered follower address lists out of a launcher fleet
    file (``[]`` for a rank with no followers)."""
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        raise FileNotFoundError(
            f"fleet file {fleet_file!r} missing or malformed")
    members = sorted(doc.get("members", []),
                     key=lambda m: int(m.get("rank", 0)))
    out = []
    for m in members:
        out.append([a for a in
                    (_pick_addr(rep.get("addresses"), scheme)
                     for rep in (m.get("replicas") or []))
                    if a])
    return out


def connect_fleet_file(fleet_file: str, *,
                       scheme: Optional[str] = None,
                       client: Optional[str] = None,
                       quant: Optional[str] = "env",
                       seed: Optional[int] = None,
                       deadline_s="env",
                       read_replica="env") -> FleetClient:
    """Dial a fleet straight from its launcher fleet file (addresses,
    the authoritative map, AND the replica sets come from the file —
    keeping the file name around is what arms failover)."""
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        raise FileNotFoundError(
            f"fleet file {fleet_file!r} missing or malformed")
    pmap = partition.PartitionMap.from_wire(doc["map"])
    return FleetClient(fleet_addresses(fleet_file, scheme),
                       pmap=pmap, client=client, quant=quant,
                       seed=seed, deadline_s=deadline_s,
                       fleet_file=fleet_file, scheme=scheme,
                       read_replica=read_replica)
