"""Staleness-bounded get cache: reads that never block on the server.

The reference serves worker ``Get``s from a local cache kept within a
bounded number of versions of the server copy (PAPER.md §4.2-4.3 — the
SSP-style bound), so the hot loop never pays the round-trip. Our
``Table.get()`` is the opposite: a jitted snapshot dispatch plus a
blocking ``np.asarray`` D2H per call. :class:`CachedView` restores the
cached read:

- it serves the last host snapshot as long as that snapshot is within
  ``max_staleness`` GENERATIONS of the table (the table's monotone
  update counter — one generation per applied add/superstep/load),
- refresh is split along the thread-safety line: the snapshot PROGRAM
  is dispatched on the table's own dispatch thread (tables notify
  attached views from their generation bump; dispatch is async and
  cheap, and multi-device collective programs MUST all launch from one
  thread — two threads dispatching concurrently interleave the
  per-device rendezvous and deadlock the backend), while the blocking
  D2H readback of the result rides a persistent worker thread
  (:class:`multiverso_tpu.utils.async_buffer.ASyncBuffer`) — so the
  hot loop never waits on the transfer,
- a read that WOULD exceed the bound blocks until a fresh-enough
  snapshot lands: the bound is a guarantee, not a hint.

At most one refresh is in flight at a time (a generation bump while one
is pending is picked up by the next bump or read), and
``max_staleness=0`` still dedupes: repeated reads of an unchanged table
cost zero dispatches (the common "log the weights every step" shape).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional, Tuple

import numpy as np

from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.utils.async_buffer import ASyncBuffer


class CachedView:
    """Bounded-staleness host view of one dense table's logical value.

    Works on any :class:`multiverso_tpu.tables.base.Table` (ArrayTable /
    MatrixTable / SparseMatrixTable — anything with ``get_jax()`` and a
    ``generation`` counter). KVTables are keyed, not whole-value; their
    cached-read analog is :meth:`KVTable.get_async` + coalescing.

    Reads (``get``) may come from any thread; table UPDATES must come
    from the table's single dispatch thread — the same SPMD contract
    every table op already has.
    """

    def __init__(self, table: Any, max_staleness: int = 0, *,
                 background: bool = True) -> None:
        if max_staleness < 0:
            raise ValueError("max_staleness must be >= 0")
        self._table = table
        self.max_staleness = int(max_staleness)
        self._lock = threading.Lock()
        self._closed = False
        lbl = f"{table.table_id}:{table.name}"
        self._lbl = lbl
        self._m_hits = telemetry.counter("client.cache.hits", table=lbl)
        self._m_misses = telemetry.counter("client.cache.misses",
                                           table=lbl)
        self._m_staleness = telemetry.gauge("client.cache.staleness",
                                            table=lbl)
        self._h_get = telemetry.histogram(
            "client.get.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        # control-plane binding: get() reads max_staleness per call,
        # so a controller write widens/narrows the bound live
        _knobs.bind("client.staleness", self, "max_staleness",
                    label=lbl)
        # a view never serves nothing: first snapshot is synchronous
        self._gen, self._val = self._sync_snapshot()
        # refresh pipeline: (generation, device future, trace link)
        # handed to the worker, which only WAITS and copies (no program
        # dispatch)
        self._req: "queue.Queue[Optional[Tuple[int, Any, Any]]]" = \
            queue.Queue()
        self._inflight = False
        self._buf: Optional[ASyncBuffer] = (
            ASyncBuffer(self._fill, name=f"view:{lbl}")
            if background else None)
        table._attach_view(self)

    # -- snapshot machinery -----------------------------------------------

    def _sync_snapshot(self) -> Tuple[int, np.ndarray]:
        """(generation, host value), dispatched AND read on the calling
        thread. The generation is read BEFORE the snapshot dispatch:
        updates apply in program order, so the snapshot reflects at
        least that generation (it may be fresher)."""
        gen = self._table.generation
        return gen, np.asarray(self._table.get_jax())

    def _fill(self, _idx: int) -> Optional[Tuple[int, np.ndarray]]:
        """Worker-thread body: wait for a dispatched snapshot future and
        pull it to host. No jax program is ever DISPATCHED here — only
        the D2H wait/copy happens off-thread (see module docstring)."""
        item = self._req.get()
        if item is None:                # close() sentinel
            return None
        gen, fut, token = item
        # the D2H wait chains to whatever request triggered the refresh
        with tracing.adopt(token):
            with tracing.span("client.d2h_wait", table=self._lbl,
                              gen=gen):
                return gen, np.asarray(fut)

    def _on_table_update(self) -> None:
        """Table hook, invoked on the table's dispatch thread right
        after a generation bump: launch one async snapshot (cheap — the
        D2H wait happens on the worker) unless one is already in
        flight."""
        if self._buf is None or self._closed or self._inflight:
            return
        gen = self._table.generation
        if gen == self._gen:
            return
        fut = self._table.get_jax()     # async dispatch, this thread
        self._inflight = True
        self._req.put((gen, fut, tracing.link()))

    def _absorb(self, snap: Optional[Tuple[int, np.ndarray]]) -> None:
        self._inflight = False
        if snap is not None:
            gen, val = snap
            if gen > self._gen:
                self._gen, self._val = gen, val

    # -- reads -------------------------------------------------------------

    @property
    def generation(self) -> int:
        """Generation of the snapshot currently served."""
        return self._gen

    def staleness(self) -> int:
        """Current gap (generations) between the table and the served
        snapshot."""
        return self._table.generation - self._gen

    def get(self, max_staleness: Optional[int] = None) -> np.ndarray:
        """The cached host value, guaranteed within ``max_staleness``
        generations of the table. Non-blocking on the hit path; a read
        past the bound blocks on the in-flight refresh (or snapshots
        synchronously).

        The bound defaults to the view owner's ``max_staleness`` (set
        at construction — the per-client bound); pass ``max_staleness=``
        to override for THIS read only (``0`` forces freshness, a
        larger value lets a tolerant reader skip the wait a strict
        default would impose)."""
        bound = self.max_staleness if max_staleness is None \
            else int(max_staleness)
        if bound < 0:
            raise ValueError("max_staleness must be >= 0")
        t0 = time.monotonic()
        try:
            with tracing.request("client.get", table=self._lbl), \
                    self._lock:
                cur = self._table.generation
                if self._inflight and self._buf is not None:
                    snap = self._buf.poll()  # absorb finished refresh
                    if snap is not None:
                        self._absorb(snap)
                stale = cur - self._gen
                self._m_staleness.set(max(stale, 0))
                if stale <= bound:
                    self._m_hits.inc()
                    return self._val
                self._m_misses.inc()
                if self._inflight and self._buf is not None:
                    with tracing.span("client.d2h_wait",
                                      table=self._lbl):
                        self._absorb(self._buf.get())  # blocking wait
                if cur - self._gen > bound:
                    # in-flight refresh was older than needed (or none
                    # was running): snapshot here, on the reading
                    # thread — for single-dispatcher apps this IS the
                    # dispatch thread
                    self._absorb(self._sync_snapshot())
                return self._val
        finally:
            self._h_get.observe(time.monotonic() - t0)

    def refresh(self) -> np.ndarray:
        """Force an up-to-date snapshot (staleness 0 as of the call)."""
        with self._lock:
            self._absorb(self._sync_snapshot())
            return self._val

    def close(self) -> None:
        """Stop the background reader (idempotent)."""
        self._closed = True
        if self._buf is not None:
            self._req.put(None)         # release a fill blocked on _req
            self._buf.stop()
            self._buf = None

    def __enter__(self) -> "CachedView":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
