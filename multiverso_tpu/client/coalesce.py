"""Delta coalescing: the reference's worker-side Aggregator as a buffer.

The reference parameter server's headline perf trick (PAPER.md
§3.7/§4.2-4.3) is that workers do NOT ship every local delta: deltas
accumulate in a client-side Aggregator and reach the server as one
summed update. On the TPU port every ``add`` is its own jitted dispatch
(program launch + option placement + buffer swap), so K small adds pay
K dispatches — the per-op-vs-fused gap arXiv:2004.13336 / 2204.06514
measure. :class:`CoalescingBuffer` restores the aggregation: it absorbs
up to ``max_deltas`` adds (or a byte / age budget) per table host-side
and flushes them through ONE fused ``updater.apply`` dispatch.

Semantics (the SSP-style contract coalescing opts into):

- Buffered deltas are INVISIBLE to reads until their flush; fused
  supersteps and ``store``/``load`` force a flush first (the table
  attaches the buffer via ``_attach_coalescer``), so ops that must
  observe every prior add still do.
- Summation before a single updater step is EXACT for the linear
  updaters (``default``, ``sgd``) and the standard mini-batch
  approximation for stateful ones (adagrad/adam/...: one state update
  for K deltas instead of K — the same semantics the reference's
  Aggregator always had).
- Deltas are cast to the table dtype at buffer time, matching what a
  direct ``add`` would have done per delta.
- KV / row / COO adds coalesce BY KEY: duplicate keys across the
  buffered batches are pre-summed host-side before upload, which also
  satisfies the table layer's unique-keys-per-add requirement.

Every buffered add returns a :class:`PendingHandle` — Handle-compatible
(``wait``/``done``/``result``); ``wait()`` forces the flush carrying the
delta and then blocks on the table, so ``flush()`` + ``Handle.wait()``
observe all buffered deltas exactly like plain add-handles.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

import numpy as np

from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.updaters import AddOption


class PendingHandle:
    """Async handle for a BUFFERED delta (Handle-compatible surface).

    Carries the flush ticket its delta will ride: ``wait()`` forces that
    flush (if it has not happened) and then blocks on the table — the
    same generation contract as :class:`multiverso_tpu.tables.base
    .Handle`: by program order, the table's buffers being ready implies
    this delta's flush has been applied.
    """

    def __init__(self, buffer: "CoalescingBuffer", ticket: int,
                 request_id: Optional[str] = None) -> None:
        self._buffer = buffer
        self._ticket = ticket
        #: request id minted by the buffered add this handle tracks —
        #: ``wait()`` re-enters that request's trace tree
        self.request_id = request_id

    def flushed(self) -> bool:
        """True once the flush carrying this delta has been dispatched."""
        return self._buffer.flush_generation > self._ticket

    def done(self) -> bool:
        """Non-blocking: False while buffered; after the flush, the
        underlying table handle's (non-monotonic) readiness."""
        if not self.flushed():
            return False
        h = self._buffer._last_handle
        return h is not None and h.done()

    def wait(self) -> Any:
        # re-enter this delta's request scope: the wait span (and the
        # flush it may force) chain to the add that minted the id
        with tracing.adopt((self.request_id, None)
                           if self.request_id else None):
            with tracing.span("client.wait"):
                self._buffer.flush_through(self._ticket)
                h = self._buffer._last_handle
                assert h is not None
                return h.wait()

    def result(self) -> Any:
        return self.wait()


class CoalescingBuffer:
    """Accumulate adds against one table; flush as ONE fused dispatch.

    One buffer holds ONE pending group at a time: a group is (op kind,
    AddOption) — an add of a different kind (dense / kv / rows / coo) or
    with a different explicit option forces the current group out first,
    preserving update order. Thread-safe.

    Flush triggers (checked on every buffered add, whichever fires
    first): ``max_deltas`` buffered adds, ``max_bytes`` of buffered
    payload, ``max_age_s`` since the group's first add (age is only
    observed at add/:meth:`maybe_flush` time — there is no timer
    thread). ``flush()`` forces; supersteps and store/load force through
    the table's ``flush_coalesced`` hook.
    """

    def __init__(self, table: Any, max_deltas: int = 8, *,
                 max_bytes: Optional[int] = None,
                 max_age_s: Optional[float] = None,
                 option: Optional[AddOption] = None) -> None:
        if max_deltas < 1:
            raise ValueError("max_deltas must be >= 1")
        self._table = table
        self.max_deltas = int(max_deltas)
        self.max_bytes = max_bytes
        self.max_age_s = max_age_s
        self._default_option = option
        self._lock = threading.RLock()
        self._kind: Optional[str] = None
        self._option: Optional[AddOption] = None
        self._count = 0
        self._bytes = 0
        self._first_ts: Optional[float] = None
        # dense accumulator / batched-op part lists
        self._acc: Optional[np.ndarray] = None
        self._ids: List[np.ndarray] = []       # kv keys / row ids / coo keys
        self._deltas: List[np.ndarray] = []
        self._flush_gen = 0
        self._last_handle = None
        lbl = f"{table.table_id}:{table.name}"
        self._lbl = lbl
        self._m_flushes = telemetry.counter("client.coalesce.flushes",
                                            table=lbl)
        self._m_deltas = telemetry.counter("client.coalesce.deltas",
                                           table=lbl)
        self._m_bytes = telemetry.counter("client.coalesce.bytes",
                                          table=lbl)
        self._h_flush = telemetry.histogram(
            "client.flush.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        # control-plane binding: _maybe_flush_locked reads max_deltas
        # per buffered add, so K moves live
        _knobs.bind("client.coalesce_k", self, "max_deltas", label=lbl)
        # occupancy as a queue gauge: buffered-delta count + group age
        self._qg = telemetry.QueueGauges(f"coalesce:{lbl}")
        # request ids riding the open group (stamped onto the flush
        # span — a coalesced flush serves MANY requests)
        self._req_ids: List[str] = []
        table._attach_coalescer(self)

    # -- state -------------------------------------------------------------

    @property
    def flush_generation(self) -> int:
        """Number of flushes dispatched so far (PendingHandle tickets
        compare against it)."""
        return self._flush_gen

    @property
    def pending_deltas(self) -> int:
        return self._count

    @property
    def pending_bytes(self) -> int:
        return self._bytes

    def _start_group(self, kind: str, option: Optional[AddOption]) -> None:
        """Flush-on-boundary: a kind or option change closes the open
        group (update order across groups is preserved)."""
        opt = option if option is not None else self._default_option
        if self._count and (self._kind != kind or self._option != opt):
            self._flush_locked()
        self._kind = kind
        self._option = opt
        if self._first_ts is None:
            self._first_ts = time.monotonic()

    def _buffered(self, nbytes: int) -> int:
        """Account one buffered add; returns its PendingHandle ticket."""
        self._count += 1
        self._bytes += int(nbytes)
        self._m_deltas.inc()
        self._m_bytes.inc(int(nbytes))
        rid = tracing.current_request()
        if rid is not None:
            self._req_ids.append(rid)
        self._qg.sample(self._count,
                        time.monotonic() - self._first_ts
                        if self._first_ts is not None else 0.0)
        return self._flush_gen

    def _maybe_flush_locked(self) -> None:
        if (self._count >= self.max_deltas
                or (self.max_bytes is not None
                    and self._bytes >= self.max_bytes)
                or (self.max_age_s is not None
                    and self._first_ts is not None
                    and time.monotonic() - self._first_ts
                    >= self.max_age_s)):
            self._flush_locked()

    # -- buffered add variants --------------------------------------------

    def add(self, delta: Any,
            option: Optional[AddOption] = None) -> PendingHandle:
        """Buffer a whole-table dense delta (``Table.add`` shape rules:
        logical or padded)."""
        arr = np.asarray(delta, dtype=self._table.dtype)
        with tracing.request("client.add", table=self._lbl,
                             kind="dense") as rid, self._lock:
            self._start_group("dense", option)
            if self._acc is None:
                self._acc = arr.copy()
            else:
                if arr.shape != self._acc.shape:
                    raise ValueError(
                        f"coalesced delta shape {arr.shape} != buffered "
                        f"{self._acc.shape} (flush between shapes)")
                self._acc += arr
            ticket = self._buffered(arr.nbytes)
            self._maybe_flush_locked()
            return PendingHandle(self, ticket, rid)

    def add_kv(self, keys: Any, deltas: Any,
               option: Optional[AddOption] = None) -> PendingHandle:
        """Buffer a KV batch; duplicate keys WITHIN and ACROSS buffered
        batches pre-sum host-side at flush (the Aggregator role)."""
        keys = np.asarray(keys, dtype=np.uint64)
        deltas = np.asarray(deltas, dtype=self._table.dtype)
        if len(deltas) != len(keys):
            raise ValueError(f"deltas length {len(deltas)} != keys "
                             f"length {len(keys)}")
        with tracing.request("client.add", table=self._lbl,
                             kind="kv") as rid, self._lock:
            self._start_group("kv", option)
            self._ids.append(keys)
            self._deltas.append(deltas)
            ticket = self._buffered(deltas.nbytes)
            self._maybe_flush_locked()
            return PendingHandle(self, ticket, rid)

    def add_rows(self, row_ids: Any, deltas: Any,
                 option: Optional[AddOption] = None) -> PendingHandle:
        """Buffer a MatrixTable row batch; duplicate row ids pre-sum at
        flush (which also satisfies the stateful-updater unique-ids
        rule)."""
        ids = np.asarray(row_ids, dtype=np.int32)
        deltas = np.asarray(deltas, dtype=self._table.dtype)
        if deltas.shape != (len(ids), self._table.num_cols):
            raise ValueError(f"deltas shape {deltas.shape} != "
                             f"({len(ids)}, {self._table.num_cols})")
        with tracing.request("client.add", table=self._lbl,
                             kind="rows") as rid, self._lock:
            self._start_group("rows", option)
            self._ids.append(ids)
            self._deltas.append(deltas)
            ticket = self._buffered(deltas.nbytes)
            self._maybe_flush_locked()
            return PendingHandle(self, ticket, rid)

    def add_sparse(self, rows: Any, cols: Any, values: Any,
                   option: Optional[AddOption] = None) -> PendingHandle:
        """Buffer a COO batch; duplicate (row, col) pairs pre-sum at
        flush."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        values = np.asarray(values, dtype=self._table.dtype)
        if not (rows.shape == cols.shape == values.shape) \
                or rows.ndim != 1:
            raise ValueError("COO arrays must be same-length 1-D")
        with tracing.request("client.add", table=self._lbl,
                             kind="coo") as rid, self._lock:
            self._start_group("coo", option)
            # flat (row, col) key — split back at flush
            self._ids.append(rows * self._table.num_cols + cols)
            self._deltas.append(values)
            ticket = self._buffered(values.nbytes)
            self._maybe_flush_locked()
            return PendingHandle(self, ticket, rid)

    # -- flush -------------------------------------------------------------

    def _summed_unique(self):
        """Concatenate the buffered (ids, deltas) parts and pre-sum
        duplicates host-side: the ONE upload the flush dispatches."""
        ids = np.concatenate(self._ids)
        deltas = np.concatenate(self._deltas, axis=0)
        uniq, inv = np.unique(ids, return_inverse=True)
        summed = np.zeros((len(uniq),) + deltas.shape[1:], deltas.dtype)
        np.add.at(summed, inv, deltas)
        return uniq, summed

    def _flush_locked(self):
        if self._count == 0:
            return None
        kind, opt = self._kind, self._option
        t0 = time.monotonic()
        # one flush serves MANY requests: the span lists every request
        # id that buffered into this group
        with tracing.span("client.flush", table=self._lbl, kind=kind,
                          n=self._count, reqs=list(self._req_ids)):
            if kind == "dense":
                handle = self._table.add(self._acc, opt)
            elif kind == "kv":
                uniq, summed = self._summed_unique()
                handle = self._table.add(uniq, summed, opt)
            elif kind == "rows":
                uniq, summed = self._summed_unique()
                handle = self._table.add_rows(uniq.astype(np.int32),
                                              summed, opt)
            else:   # coo
                uniq, summed = self._summed_unique()
                ncols = self._table.num_cols
                handle = self._table.add_sparse(
                    (uniq // ncols).astype(np.int32),
                    (uniq % ncols).astype(np.int32), summed, opt)
        self._h_flush.observe(time.monotonic() - t0)
        self._acc = None
        self._ids, self._deltas = [], []
        self._req_ids = []
        self._count = 0
        self._bytes = 0
        self._first_ts = None
        self._qg.sample(0, 0.0)
        self._flush_gen += 1
        self._last_handle = handle
        self._m_flushes.inc()
        return handle

    def flush(self):
        """Dispatch the buffered group as one fused add. Returns that
        add's table Handle (None when nothing was buffered)."""
        with self._lock:
            return self._flush_locked()

    def maybe_flush(self):
        """Apply the byte/age/count budgets without buffering anything —
        for callers that want the age trigger honored between adds."""
        with self._lock:
            self._maybe_flush_locked()

    def flush_through(self, ticket: int) -> None:
        """Ensure the flush carrying ``ticket`` has been dispatched
        (PendingHandle.wait's entry point)."""
        with self._lock:
            if self._flush_gen <= ticket:
                self._flush_locked()

    # flush-on-exit context manager
    def __enter__(self) -> "CoalescingBuffer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.flush()
