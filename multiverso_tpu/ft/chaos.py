"""Deterministic fault injection: the ``MVTPU_CHAOS`` spec.

The reference has no fault injection at all — recovery code that is
never exercised is recovery code that does not work. This module puts
named *fault points* on the paths a preemption or flaky filesystem
actually hits (stream IO, table dispatch, the barrier), and a
seedable, deterministic injector that fires faults at them according
to a spec string, so every recovery path runs in tests and a chaos CI
lane (``make chaos``) instead of only in production.

Spec grammar (semicolon-separated rules)::

    MVTPU_CHAOS = "[seed=<int>;]rule[;rule...]"
    rule        = <point-pattern>:<kind>[:key=value[,key=value...]]

- ``point-pattern`` — a fault-point name, ``fnmatch``-style globs
  allowed (``io.*`` matches ``io.write`` and ``io.read``).
- ``kind`` — one of:
  - ``error``   — raise :class:`ChaosError` (an ``OSError`` subclass,
    so IO retry policies treat it as transient),
  - ``latency`` — sleep ``ms`` milliseconds,
  - ``torn``    — for write points: make the write LOOK like a crash
    between the payload write and the commit rename (the temp bytes
    land, the rename never happens) by raising :class:`ChaosTornWrite`
    *after* the payload is on disk,
  - ``crash``   — raise :class:`ChaosCrash` (NOT an OSError: retry
    policies never swallow it — it simulates the process dying),
  - ``drop``    — for wire points: connection drop. Raises
    :class:`ChaosConnDrop` (a ``ConnectionError``, so transport retry
    policies reconnect); the wire layer closes the socket first, so
    the peer sees a real EOF/reset, not just a client-side exception.
    At ``wire.send`` a ``torn`` rule means a TORN FRAME: the transport
    puts PART of the encoded frame on the wire, then drops the
    connection — the receiver must discard the partial frame,
  - ``nan``     — VALUE corruption: poison deterministic elements of
    the tensor flowing through a :func:`chaos_corrupt` point (the
    ``table.add`` delta paths) with NaN. Nothing raises — the bad
    numbers propagate exactly like a real fused-kernel NaN, which is
    what the training-health layer (`telemetry/health.py`) must catch.
- params:
  - ``p=<float>``   — firing probability per hit (default 1.0),
  - ``after=<int>`` — skip the first N matching hits (default 0),
  - ``times=<int>`` — fire at most N times (default unlimited),
  - ``ms=<float>``  — latency milliseconds (``latency`` kind, default 1),
  - ``frac=<float>`` — fraction of elements to poison (``nan`` kind,
    default 0 = a single element).

Determinism: the injector derives every probabilistic draw from
``splitmix64(seed, point-hit-counter)`` — same spec, same call
sequence, same faults. No wall clock, no global RNG.

Examples::

    MVTPU_CHAOS="io.write:error:p=0.5,times=3"
    MVTPU_CHAOS="seed=7;io.*:latency:ms=5;ckpt.commit:torn:after=2,times=1"

Fault points in the codebase (grep ``chaos_point(`` for ground truth):

====================  =====================================================
``io.open.read``      stream open for read (`io/stream.py`)
``io.open.write``     stream open for write
``io.read``           every stream read call
``io.write``          every stream write call
``io.rename``         the atomic temp->final commit rename (torn-write
                      simulation: payload lands in the temp file, the
                      final path is never updated)
``io.mv.aside``       fsspec overwrite: the ``final -> final.bak`` move
``io.mv.replace``     fsspec overwrite: the ``tmp -> final`` move
``table.add``         dense/KV table Add dispatch (`tables/base.py`) —
                      also a :func:`chaos_corrupt` value point: ``nan``
                      rules poison the delta before it reaches devices
``table.get``         whole-table Get dispatch
``core.barrier``      the global barrier (`core.py`)
``multihost.allgather``  multihost collectives (`parallel/multihost.py`)
``ckpt.commit``       RunCheckpointManager manifest commit (`ft/checkpoint.py`)
``ckpt.gc``           RunCheckpointManager retention delete
``storage.spill``     tiered KV: bucket record spill to the cold-tier
                      file (`storage/tiers.py`) — the write itself is
                      additionally covered by ``io.write`` + retry
``storage.fill``      tiered KV: cold-tier bucket fill (ranged read,
                      CRC-verified)
``wire.send``         one frame onto a parameter-server wire socket
                      (`client/transport.py` + `server/table_server.py`)
                      — ``torn`` here = a TORN FRAME: partial bytes hit
                      the wire, then the connection drops
``wire.recv``         one frame off a wire socket (``drop`` = the
                      connection dies before/while the reply arrives)
``wire.accept``       server accept loop (`server/table_server.py`) —
                      ``drop`` closes the just-accepted connection
``wire.shm.ring``     one frame into a shared-memory ring
                      (`server/wire.py` ShmChannel over `io/shmring.py`)
                      — ``torn`` publishes HALF a ring record then
                      closes (the peer sees a producer that died
                      mid-copy); ``latency`` models a slow same-host
                      hop; ``drop`` closes the doorbell socket
``server.fuse``       one fused dispatch cycle's group execute
                      (`server/table_server.py`) — an ``error`` here
                      exercises the per-frame fallback: affected
                      requests re-run individually, the dispatch
                      thread never dies
``server.flood``      frame intake on a server reader thread
                      (`server/table_server.py`) — an ``error``/
                      ``drop`` firing injects a burst of 32 synthetic
                      ``noop`` frames from client ``chaos-flood``
                      AHEAD of the real frame, driving the admission
                      layer (token buckets, fair queue, bounded-queue
                      shedding) exactly like a real flooder; the real
                      frame is never lost
``server.dequeue``    one dispatch-cycle dequeue
                      (`server/table_server.py`) — ``latency`` stalls
                      the single dispatch thread (the overload the
                      admission layer must absorb); ``error``/``drop``
                      are contained (logged, the cycle proceeds) —
                      the dispatch thread never dies; ``crash`` still
                      models process death
``reshard.handoff``   live-reshard handoff (`server/table_server.py`):
                      fires per streamed migration chunk (donor stream
                      thread, under the migration lock — an ``error``
                      fails the stream, the admin sees ``failed`` and
                      aborts the reshard fleet-wide, v keeps serving)
                      and per forwarded in-flight write (CONTAINED:
                      logged only — the forward is already on the
                      link, and an error reply would be dedup-cached
                      and replayed to every client resend as a
                      permanent failure)
====================  =====================================================

The injector is process-global and OFF unless installed: fault points
cost one ``is None`` check when no chaos is active, so production hot
paths pay nothing.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CHAOS_ENV = "MVTPU_CHAOS"


class ChaosError(OSError):
    """Injected transient IO fault (retryable — an OSError)."""


class ChaosTornWrite(ChaosError):
    """Injected crash between payload write and commit rename."""


class ChaosConnDrop(ChaosError, ConnectionError):
    """Injected connection drop (wire points). Both a
    :class:`ChaosError` and a ``ConnectionError``: transport retry
    policies treat it exactly like a real peer reset — reconnect and
    resend."""


class ChaosCrash(BaseException):
    """Injected process death. Deliberately NOT an Exception subclass:
    retry policies and broad ``except Exception`` recovery code must
    never swallow it — it models the process being killed."""


def _splitmix64(x: int) -> int:
    """splitmix64 finalizer — the deterministic per-hit hash."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


@dataclass
class ChaosRule:
    """One parsed spec rule (see module docstring for the grammar)."""
    pattern: str
    kind: str                   # error | latency | torn | crash | nan
    p: float = 1.0
    after: int = 0
    times: Optional[int] = None
    ms: float = 1.0
    frac: float = 0.0           # nan kind: fraction poisoned (0 = one)
    # runtime state
    hits: int = 0               # matching hits seen
    fired: int = 0              # faults actually fired

    def matches(self, point: str) -> bool:
        return fnmatch.fnmatchcase(point, self.pattern)


KINDS = ("error", "latency", "torn", "crash", "nan", "drop")


def parse_chaos_spec(spec: str) -> "ChaosInjector":
    """Parse a ``MVTPU_CHAOS`` spec string into an injector (raises
    ``ValueError`` on malformed specs — a typo'd chaos spec silently
    doing nothing would defeat the test that set it)."""
    seed = 0
    rules: List[ChaosRule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if raw.startswith("seed="):
            seed = int(raw[5:])
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"chaos rule {raw!r}: expected '<point>:<kind>[:k=v,...]'")
        pattern, kind = parts[0].strip(), parts[1].strip()
        if kind not in KINDS:
            raise ValueError(
                f"chaos rule {raw!r}: kind {kind!r} not in {KINDS}")
        rule = ChaosRule(pattern=pattern, kind=kind)
        if len(parts) > 2:
            for kv in ":".join(parts[2:]).split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise ValueError(
                        f"chaos rule {raw!r}: param {kv!r} is not k=v")
                k, v = kv.split("=", 1)
                k = k.strip()
                if k == "p":
                    rule.p = float(v)
                elif k == "after":
                    rule.after = int(v)
                elif k == "times":
                    rule.times = int(v)
                elif k == "ms":
                    rule.ms = float(v)
                elif k == "frac":
                    rule.frac = float(v)
                else:
                    raise ValueError(
                        f"chaos rule {raw!r}: unknown param {k!r} "
                        "(valid: p, after, times, ms, frac)")
        rules.append(rule)
    return ChaosInjector(rules=rules, seed=seed)


@dataclass
class ChaosInjector:
    """Deterministic fault injector over a rule list."""

    rules: List[ChaosRule] = field(default_factory=list)
    seed: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def hit(self, point: str) -> None:
        """Evaluate the fault point: no-op, sleep, or raise. Called by
        :func:`chaos_point` when an injector is installed."""
        for rule in self.rules:
            # nan is a VALUE fault: it only fires through corrupt()
            # (falling through to _fire would raise ChaosCrash)
            if rule.kind == "nan" or not rule.matches(point):
                continue
            if self._account(rule):
                self._fire(rule, point)

    def _account(self, rule: ChaosRule) -> bool:
        """Shared hit accounting: after/times gating + the
        deterministic probability draw. True = the rule fires now."""
        with self._lock:
            rule.hits += 1
            n = rule.hits
            if n <= rule.after:
                return False
            if rule.times is not None and rule.fired >= rule.times:
                return False
            if rule.p < 1.0:
                # deterministic draw: hash(seed, pattern, hit index)
                # — crc32, not hash(): str hash is randomized per
                # process (PYTHONHASHSEED), which would make the
                # same spec fire differently across processes
                import zlib
                pat = zlib.crc32(rule.pattern.encode())
                h = _splitmix64(self.seed ^ _splitmix64(pat) ^ n)
                if (h / 2.0 ** 64) >= rule.p:
                    return False
            rule.fired += 1
        return True

    def corrupt(self, point: str, arr):
        """Evaluate the value-fault point: pass ``arr`` through every
        matching ``nan`` rule. Returns ``arr`` untouched (same object)
        when nothing fires; a poisoned COPY otherwise — callers hand
        the result on, they never see an exception."""
        for rule in self.rules:
            if rule.kind != "nan" or not rule.matches(point):
                continue
            if self._account(rule):
                arr = self._poison(rule, point, arr)
        return arr

    def _poison(self, rule: ChaosRule, point: str, arr):
        import zlib

        import numpy as np
        out = np.array(arr, copy=True)
        if out.size == 0 or not np.issubdtype(out.dtype, np.floating):
            return arr
        count = max(1, int(rule.frac * out.size))
        flat = out.reshape(-1)
        pat = zlib.crc32(rule.pattern.encode())
        base = self.seed ^ _splitmix64(pat) ^ (rule.fired << 20)
        for i in range(min(count, out.size)):
            flat[_splitmix64(base ^ i) % out.size] = np.nan
        self._note_fired(rule, point)
        return out

    def _note_fired(self, rule: ChaosRule, point: str) -> None:
        import sys
        m = sys.modules.get("multiverso_tpu.telemetry.metrics")
        if m is not None:
            try:
                m.counter("chaos.fired", point=point,
                          kind=rule.kind).inc()
            except Exception:
                pass

    def _fire(self, rule: ChaosRule, point: str) -> None:
        # telemetry through sys.modules only (an installed injector in
        # a jax-free process must not drag the package in)
        self._note_fired(rule, point)
        if rule.kind == "latency":
            time.sleep(rule.ms / 1000.0)
            return
        if rule.kind == "error":
            raise ChaosError(f"chaos: injected IO error at {point!r} "
                             f"(rule {rule.pattern!r}, firing "
                             f"{rule.fired})")
        if rule.kind == "torn":
            raise ChaosTornWrite(
                f"chaos: injected torn write at {point!r} — payload "
                "written, commit rename suppressed")
        if rule.kind == "drop":
            raise ChaosConnDrop(
                f"chaos: injected connection drop at {point!r}")
        raise ChaosCrash(f"chaos: injected crash at {point!r}")

    def counts(self) -> Dict[str, int]:
        """{pattern:kind: fired count} — test/report introspection."""
        return {f"{r.pattern}:{r.kind}": r.fired for r in self.rules}


# -- process-global installation -------------------------------------------

_INSTALLED: Optional[ChaosInjector] = None


def install_chaos(spec_or_injector) -> ChaosInjector:
    """Install a chaos injector process-wide (spec string or injector).
    Returns the installed injector."""
    global _INSTALLED
    inj = spec_or_injector if isinstance(spec_or_injector, ChaosInjector) \
        else parse_chaos_spec(str(spec_or_injector))
    _INSTALLED = inj
    return inj


def uninstall_chaos() -> None:
    global _INSTALLED
    _INSTALLED = None


def installed_chaos() -> Optional[ChaosInjector]:
    return _INSTALLED


def chaos_from_env() -> Optional[ChaosInjector]:
    """Install from ``MVTPU_CHAOS`` when set (idempotent per call —
    re-parses, so a changed env var takes effect); None when unset."""
    spec = os.environ.get(CHAOS_ENV, "")
    if not spec:
        return None
    return install_chaos(spec)


def chaos_point(point: str) -> None:
    """THE fault-point hook instrumented code calls. Free when no
    injector is installed (one module-global ``is None`` check)."""
    inj = _INSTALLED
    if inj is not None:
        inj.hit(point)


def chaos_corrupt(point: str, arr):
    """The VALUE fault-point hook: code holding a host tensor passes it
    through; ``nan`` rules matching ``point`` poison a copy. Same
    one-check cost as :func:`chaos_point` when chaos is off."""
    inj = _INSTALLED
    if inj is None:
        return arr
    return inj.corrupt(point, arr)
