"""Typed retry policy: jittered exponential backoff with telemetry.

Transient IO faults (flaky object store, evicted NFS lease, an
injected :class:`~multiverso_tpu.ft.chaos.ChaosError`) must not kill a
training run that a second attempt would save — and silent unlimited
retries must not hide a dead filesystem either. :class:`RetryPolicy`
is the one typed knob for both: attempt cap, wall-deadline cap,
jittered exponential backoff, and ``retry.*`` telemetry so every
retried fault is on the record.

The ad-hoc overwrite-retry in ``io/stream.py`` and the checkpoint
store/load paths (``tables/base.py`` ``savez_stream``/``loadz_stream``,
``ft/checkpoint.py``) all route through one policy —
:func:`io_retry_policy`, configured by env:

- ``MVTPU_RETRY_ATTEMPTS``   (default 3; 1 = no retry)
- ``MVTPU_RETRY_BASE_S``     (default 0.05; first backoff sleep)
- ``MVTPU_RETRY_MAX_S``      (default 2.0; backoff ceiling)
- ``MVTPU_RETRY_DEADLINE_S`` (default 30.0; total wall budget, 0 = off)

Jitter is "full jitter" (uniform in [0, backoff]) from a policy-local
``random.Random`` seeded at construction — deterministic under a fixed
seed (tests), decorrelated across workers otherwise (each process
seeds from pid+time).

What retries: ``OSError`` (and so ``ChaosError``) plus anything in
``retryable``. What NEVER retries: ``ChaosCrash`` (BaseException — a
simulated kill), ``ValueError``-class corruption (a checksum mismatch
is the same bytes on every attempt), and anything else not listed.
"""

from __future__ import annotations

import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple, Type


class _TelemetryShim:
    """Metrics through ``sys.modules`` only (the ``ft/chaos.py``
    pattern): this module is file-path loadable with ZERO package
    imports, so jax-free wire-worker processes get the real
    :class:`RetryPolicy` without dragging the package (and jax) in.
    When the registry module is loaded, counters/histograms record as
    before; when it is not, they are no-ops."""

    class _Null:
        def inc(self, n: float = 1) -> None:
            pass

        def observe(self, v: float) -> None:
            pass

    _null = _Null()

    @staticmethod
    def _mod():
        return sys.modules.get("multiverso_tpu.telemetry.metrics")

    def counter(self, name: str, **labels):
        m = self._mod()
        return m.counter(name, **labels) if m is not None else self._null

    def histogram(self, name: str, **labels):
        m = self._mod()
        return m.histogram(name, **labels) if m is not None else self._null


telemetry = _TelemetryShim()


class RetryError(Exception):
    """All attempts exhausted; ``__cause__`` is the last failure."""


@dataclass
class RetryPolicy:
    """Jittered-exponential-backoff retry with attempt/deadline caps.

    ``call(fn, *args, **kwargs)`` runs ``fn`` until it returns, a
    non-retryable exception escapes, or the caps are hit (then
    :class:`RetryError` chained to the last failure). ``wraps(fn)``
    is the decorator form.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    deadline_s: float = 30.0        # 0 = no wall deadline
    retryable: Tuple[Type[BaseException], ...] = (OSError,)
    # checked FIRST: a missing file is the same missing file on every
    # attempt — backing off on FileNotFoundError would turn every
    # "no checkpoint yet" probe into seconds of sleeps
    non_retryable: Tuple[Type[BaseException], ...] = (FileNotFoundError,)
    name: str = "io"
    seed: Optional[int] = None      # fixed seed -> deterministic jitter
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        seed = self.seed if self.seed is not None \
            else (os.getpid() << 20) ^ time.monotonic_ns()
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): full jitter over
        ``base * 2^(attempt-1)``, capped at ``max_delay_s``."""
        cap = min(self.base_delay_s * (2.0 ** (attempt - 1)),
                  self.max_delay_s)
        return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable[..., Any], *args: Any,
             **kwargs: Any) -> Any:
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            telemetry.counter("retry.attempts", policy=self.name).inc()
            try:
                result = fn(*args, **kwargs)
            except self.non_retryable:
                raise
            except self.retryable as exc:
                telemetry.counter("retry.failures",
                                  policy=self.name).inc()
                elapsed = time.monotonic() - t0
                if attempt >= self.max_attempts:
                    telemetry.counter("retry.giveups",
                                      policy=self.name,
                                      reason="attempts").inc()
                    raise RetryError(
                        f"retry policy {self.name!r}: "
                        f"{attempt} attempts exhausted "
                        f"({elapsed:.2f}s): {exc!r}") from exc
                delay = self.backoff_s(attempt)
                if self.deadline_s > 0 \
                        and elapsed + delay > self.deadline_s:
                    telemetry.counter("retry.giveups",
                                      policy=self.name,
                                      reason="deadline").inc()
                    raise RetryError(
                        f"retry policy {self.name!r}: deadline "
                        f"{self.deadline_s}s exceeded after "
                        f"{attempt} attempts: {exc!r}") from exc
                telemetry.histogram("retry.backoff.seconds",
                                    policy=self.name).observe(delay)
                if delay > 0:
                    time.sleep(delay)
                continue
            telemetry.histogram("retry.call.seconds",
                                policy=self.name).observe(
                    time.monotonic() - t0)
            if attempt > 1:
                telemetry.counter("retry.recoveries",
                                  policy=self.name).inc()
            return result

    def wraps(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        """Decorator form: ``guarded = policy.wraps(fn)``."""
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.call(fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def io_retry_policy(name: str = "io") -> RetryPolicy:
    """The env-configured policy guarding stream IO and checkpoint
    store/load (see module docstring for the knobs)."""
    return RetryPolicy(
        max_attempts=max(_env_int("MVTPU_RETRY_ATTEMPTS", 3), 1),
        base_delay_s=_env_float("MVTPU_RETRY_BASE_S", 0.05),
        max_delay_s=_env_float("MVTPU_RETRY_MAX_S", 2.0),
        deadline_s=_env_float("MVTPU_RETRY_DEADLINE_S", 30.0),
        name=name)
