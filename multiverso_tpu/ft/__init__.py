"""Fault-tolerance subsystem (SURVEY §6.3/§6.4, beyond parity).

The reference parameter server's recovery story is checkpoint/restart,
and its failure detection / fault injection are essentially absent.
This package makes crashes *survivable* on preemptible fleets:

- :mod:`multiverso_tpu.ft.checkpoint` — :class:`RunCheckpointManager`:
  a run directory of atomically-committed checkpoint generations
  covering every registered table plus app train-state, with keep-K
  retention GC, write offload to a background worker, and a resume
  scan that restores the latest *complete* generation.
- :mod:`multiverso_tpu.ft.chaos` — deterministic, seedable fault
  injection (``MVTPU_CHAOS`` spec) at named points threaded through the
  IO layer, table dispatch, and the barrier — recovery paths get
  exercised in tests and a chaos CI lane instead of only in production.
- :mod:`multiverso_tpu.ft.retry` — typed :class:`RetryPolicy`
  (jittered exponential backoff, attempt/deadline caps, ``retry.*``
  telemetry) guarding checkpoint store/load and stream IO.

Env knobs (honored by the apps): ``MVTPU_RUN_DIR`` (run directory —
enables the manager), ``MVTPU_CKPT_EVERY`` (checkpoint cadence in app
steps/sweeps), ``MVTPU_CKPT_KEEP`` (retained generations, default 3),
``MVTPU_CHAOS`` (fault spec), ``MVTPU_RETRY_ATTEMPTS`` /
``MVTPU_RETRY_BASE_S`` / ``MVTPU_RETRY_DEADLINE_S`` (IO retry policy).
"""

from multiverso_tpu.ft.chaos import (ChaosCrash, ChaosError,
                                     ChaosInjector, ChaosTornWrite,
                                     chaos_corrupt, chaos_from_env,
                                     chaos_point, install_chaos,
                                     uninstall_chaos)

_RETRY = ("RetryError", "RetryPolicy", "io_retry_policy")
_CKPT = ("CheckpointGeneration", "RestoredState", "RunCheckpointManager",
         "config_fingerprint", "define_run_flags",
         "latest_good_checkpoint", "manager_from_env", "wire_app")


def __getattr__(name):
    # PEP 562 lazy imports: io/stream.py imports ft.chaos (which pulls
    # this __init__) while tables/base.py — which ft.checkpoint needs —
    # is itself mid-import of the io package. Deferring the checkpoint/
    # retry imports breaks the cycle; chaos stays eager (stdlib-only).
    if name in _RETRY:
        from multiverso_tpu.ft import retry
        return getattr(retry, name)
    if name in _CKPT:
        from multiverso_tpu.ft import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ChaosCrash", "ChaosError", "ChaosInjector", "ChaosTornWrite",
    "chaos_corrupt", "chaos_from_env", "chaos_point", "install_chaos",
    "uninstall_chaos",
    *_RETRY, *_CKPT,
]
