"""RunCheckpointManager: run-level checkpoint/resume (SURVEY §6.3/§6.4).

The per-table ``store``/``load`` primitive (`tables/base.py`,
`io/stream.py`) checkpoints ONE table to ONE uri. A training *run* is
more: every registered table, plus the app train-state (step/sweep
counter, RNG-derivation counters, data-stream cursor, config
fingerprint), all of which must land *together* — a table file from
step 40 next to an app state from step 30 resumes into silent
corruption. This manager owns a **run directory** of checkpoint
*generations*, each committed atomically by its manifest:

    run_dir/
      gen-0000000010/
        table-logreg.npz          one file per registered table
        app.npz                   app train-state (arrays + scalars)
        MANIFEST.json             written LAST, atomic rename = commit
      gen-0000000020/
        ...

A generation is **complete** iff its ``MANIFEST.json`` parses and every
file it lists exists — a crash mid-write leaves an incomplete (ignored)
generation, never a half-trusted one. Retention keeps the last
``keep`` complete generations (older ones GC'd after each commit).

**Write overlap** follows the established client-pipeline split
(`client/cache.py`): the *dispatch half* of every table export (flush
coalescers, device-side copies of param/state so the next add's
donation can't invalidate them) runs on the CALLER's thread — the
table dispatch thread, where multi-device collectives must launch —
while the *blocking half* (D2H ``np.asarray`` waits, npz serialization,
stream writes, manifest commit, retention GC) runs on one persistent
worker thread. Training continues while the checkpoint lands.

**Resume** scans the run dir, picks the latest complete generation,
restores every table by name (through ``Table.load`` — CRC-verified by
``loadz_stream``) and returns the app train-state. A generation whose
payload fails verification falls back to the next older one
(``ft.recover.fallbacks``) — the headline guarantee, asserted in
tests: kill a run at an arbitrary step (including under an active
``MVTPU_CHAOS`` spec), resume from the run dir, and the final model
state matches the uninterrupted run.

Multi-process: exports are collective (every rank dispatches the same
fetches, like ``Table.store``); every rank writes the same paths, and
the stream layer's atomic rename keeps same-path writers safe.

Telemetry: ``ckpt.store.{ops,seconds,bytes}``, ``ckpt.last_step``,
``ckpt.generations``, ``ft.recover.{ops,fallbacks,failures}``. The
watchdog post-mortem includes :func:`latest_good_checkpoint` so a
crash report names the restart point.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu.ft.chaos import chaos_point
from multiverso_tpu.ft.retry import RetryPolicy, io_retry_policy
from multiverso_tpu.io import open_stream
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.utils import log

RUN_MAGIC = "multiverso_tpu.run_ckpt.v1"
APP_MAGIC = "multiverso_tpu.run_app_state.v1"
MANIFEST_NAME = "MANIFEST.json"
GEN_PREFIX = "gen-"

RUN_DIR_ENV = "MVTPU_RUN_DIR"
CKPT_EVERY_ENV = "MVTPU_CKPT_EVERY"
CKPT_KEEP_ENV = "MVTPU_CKPT_KEEP"
RESUME_ENV = "MVTPU_RESUME"

# the watchdog dump reads this (via sys.modules, no import) so a
# post-mortem names the restart point
_LATEST_GOOD: Optional[str] = None
_LATEST_LOCK = threading.Lock()


def latest_good_checkpoint() -> Optional[str]:
    """Path of the most recently committed or restored generation in
    this process (None when no manager has committed yet)."""
    with _LATEST_LOCK:
        return _LATEST_GOOD


def _note_good(path: str) -> None:
    global _LATEST_GOOD
    with _LATEST_LOCK:
        _LATEST_GOOD = path


def _safe_name(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)


@dataclass
class CheckpointGeneration:
    """One complete on-disk generation (scan result)."""
    step: int
    path: str
    manifest: Dict[str, Any]


@dataclass
class RestoredState:
    """What :meth:`RunCheckpointManager.resume` hands the app back."""
    step: int
    path: str
    state: Dict[str, Any] = field(default_factory=dict)   # json scalars
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)

    def get(self, key: str, default: Any = None) -> Any:
        if key in self.arrays:
            return self.arrays[key]
        return self.state.get(key, default)


class RunCheckpointManager:
    """Owns one run directory of atomically-committed generations.

    Parameters
    ----------
    run_dir:
        Local directory for the run (created on first save).
    keep:
        Complete generations retained (older GC'd). >= 1.
    every:
        App-step cadence for :meth:`maybe_save` (0 = only explicit
        :meth:`save` calls).
    tables:
        The tables covered. None = every table registered at save time
        (`tables.base` registry — includes KVTables).
    fingerprint:
        CLI-relevant config fingerprint; stamped into every manifest
        and checked on resume (a changed config resumes loudly, not
        silently wrong).
    background:
        Offload the blocking write half to the worker thread (default).
        False = synchronous writes (tests, simple tools).
    policy:
        RetryPolicy for manifest/GC IO (payload writes are retried
        inside ``savez_stream`` itself). Default: :func:`io_retry_policy`.
    """

    def __init__(self, run_dir: str, *, keep: int = 3, every: int = 0,
                 tables: Optional[Sequence[Any]] = None,
                 fingerprint: Optional[str] = None,
                 background: bool = True,
                 policy: Optional[RetryPolicy] = None) -> None:
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.run_dir = str(run_dir)
        self.keep = int(keep)
        self.every = int(every)
        self.fingerprint = fingerprint
        self._tables = list(tables) if tables is not None else None
        self._policy = policy if policy is not None \
            else io_retry_policy("ckpt")
        self._last_saved_step: Optional[int] = None
        self._error: Optional[BaseException] = None
        self._q: "queue.Queue[Optional[Tuple[int, list]]]" = \
            queue.Queue(maxsize=2)      # backpressure: at most 2 queued
        self._qg = telemetry.QueueGauges("ckpt")
        self._worker: Optional[threading.Thread] = None
        if background:
            self._worker = threading.Thread(
                target=self._work, name="mvtpu-ckpt-writer", daemon=True)
            self._worker.start()

    # -- table set ---------------------------------------------------------

    def set_tables(self, tables: Sequence[Any]) -> None:
        """Pin the covered table set (apps pass exactly their own
        tables; the default — every registered table — suits
        single-app processes and tools)."""
        self._tables = list(tables)

    def _resolve_tables(self) -> List[Any]:
        if self._tables is not None:
            return self._tables
        from multiverso_tpu.tables import base
        return [base.get_table(i) for i in range(base.num_tables())]

    # -- save --------------------------------------------------------------

    def maybe_save(self, step: int, app_state=None) -> bool:
        """Checkpoint when the cadence says so: ``every > 0`` and
        ``step`` is a positive multiple of it (and not already saved).
        ``app_state`` may be a dict or a zero-arg callable evaluated
        only when a save actually happens."""
        if self.every <= 0 or step <= 0 or step % self.every:
            return False
        if self._last_saved_step == step:
            return False
        self.save(step, app_state() if callable(app_state) else app_state)
        return True

    def save(self, step: int, app_state: Optional[Dict[str, Any]] = None
             ) -> None:
        """Checkpoint every covered table + app state as generation
        ``step``. The dispatch half runs here (caller thread); the
        blocking write half runs on the worker (or inline when
        ``background=False``)."""
        self._reraise()
        step = int(step)
        entries: List[Tuple[str, str, Callable[[], tuple]]] = []
        seen: Dict[str, int] = {}
        for t in self._resolve_tables():
            fname = f"table-{_safe_name(t.name)}.npz"
            if fname in seen:
                raise ValueError(
                    f"run checkpoint: duplicate table name {t.name!r} "
                    "— table names must be unique within a run")
            seen[fname] = 1
            entries.append((t.name, fname, self._table_export(t)))
        if app_state:
            entries.append(("", "app.npz",
                            self._app_export(step, dict(app_state))))
        job = (step, entries)
        if self._worker is None:
            self._write_generation(*job)
        else:
            self._q.put(job)
            self._qg.on_put()
        self._last_saved_step = step

    def _table_export(self, t: Any) -> Callable[[], tuple]:
        """Dispatch half NOW (device copies on this thread), return the
        blocking half as a closure for the worker."""
        if hasattr(t, "export_checkpoint_async"):
            return t.export_checkpoint_async()
        # fallback for table-likes without the split: do the whole
        # export synchronously here (no overlap, still correct)
        raise TypeError(
            f"table {t!r} has no export_checkpoint_async(); "
            "RunCheckpointManager covers Table/KVTable instances")

    def _app_export(self, step: int, state: Dict[str, Any]
                    ) -> Callable[[], tuple]:
        manifest: Dict[str, Any] = {"magic": APP_MAGIC, "step": step,
                                    "state": {}}
        payload: Dict[str, np.ndarray] = {}
        for k, v in state.items():
            if isinstance(v, np.ndarray):
                payload[k] = v
            elif isinstance(v, np.generic):     # numpy scalar
                manifest["state"][k] = v.item()
            else:
                manifest["state"][k] = v
        # scalars must survive a json round-trip — fail at save, not
        # at the resume that needed them
        json.dumps(manifest["state"])

        def finish():
            return manifest, payload
        return finish

    # -- the worker / write half -------------------------------------------

    def _work(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            self._qg.on_take()
            try:
                self._write_generation(*job)
            except BaseException as exc:   # surfaced on next save/flush
                self._error = exc
                log.error("run checkpoint write failed: %r", exc)
            finally:
                self._q.task_done()

    def _write_generation(self, step: int, entries: List[tuple]) -> None:
        t0 = time.perf_counter()
        gen_dir = os.path.join(self.run_dir, f"{GEN_PREFIX}{step:010d}")
        os.makedirs(gen_dir, exist_ok=True)
        from multiverso_tpu.tables.base import savez_stream
        files: Dict[str, int] = {}
        tables_map: Dict[str, str] = {}
        app_file: Optional[str] = None
        total = 0
        with tracing.span("ckpt.write", step=step,
                          n_entries=len(entries)):
            for name, fname, finish in entries:
                manifest, payload = finish()  # blocking D2H waits here
                nbytes = int(sum(a.nbytes for a in payload.values()))
                savez_stream(os.path.join(gen_dir, fname), manifest,
                             payload)
                files[fname] = nbytes
                total += nbytes
                if name:
                    tables_map[name] = fname
                else:
                    app_file = fname
            manifest = {
                "magic": RUN_MAGIC,
                "step": step,
                "fingerprint": self.fingerprint,
                "tables": tables_map,
                "app": app_file,
                "files": files,
                "unix_time": time.time(),
                "host": telemetry.host_index(),
            }
            # the commit: manifest lands atomically (temp+rename), LAST
            # — everything before this point is an incomplete
            # generation the resume scan ignores
            chaos_point("ckpt.commit")
            payload_json = json.dumps(manifest, indent=1).encode()

            def commit():
                with open_stream(os.path.join(gen_dir, MANIFEST_NAME),
                                 "wb") as s:
                    s.write(payload_json)
            tc = time.monotonic()
            with tracing.span("ckpt.commit", step=step):
                self._policy.call(commit)
            telemetry.histogram("ckpt.commit.seconds",
                                telemetry.LATENCY_BUCKETS).observe(
                time.monotonic() - tc)
        dt = time.perf_counter() - t0
        telemetry.counter("ckpt.store.ops").inc()
        telemetry.histogram("ckpt.store.seconds").observe(dt)
        telemetry.histogram("ckpt.store.bytes").observe(total)
        telemetry.gauge("ckpt.last_step").set(step)
        _note_good(gen_dir)
        log.info("run checkpoint: step %d committed (%d files, "
                 "%.1f MB, %.2fs)", step, len(files) + 1,
                 total / 1e6, dt)
        self._gc()

    def _gc(self) -> None:
        """Keep the last ``keep`` COMPLETE generations; delete older
        complete ones (incomplete ones too — they are dead weight from
        crashes). Failures are logged, never fatal: a GC error must not
        kill the training run that just checkpointed fine."""
        try:
            gens = self.scan()
            telemetry.gauge("ckpt.generations").set(len(gens))
            doomed = [g.path for g in gens[:-self.keep]] \
                if len(gens) > self.keep else []
            complete = {g.path for g in gens}
            # incomplete dirs older than the newest complete gen are
            # crash leftovers; ones newer may be a concurrent writer
            newest = gens[-1].step if gens else -1
            for d in self._gen_dirs():
                if d in complete:
                    continue
                try:
                    s = int(os.path.basename(d)[len(GEN_PREFIX):])
                except ValueError:
                    continue
                if s < newest:
                    doomed.append(d)
            for path in doomed:
                chaos_point("ckpt.gc")
                shutil.rmtree(path, ignore_errors=False)
        except Exception as exc:
            telemetry.counter("ckpt.gc.failures").inc()
            log.warn("run checkpoint GC failed (non-fatal): %r", exc)

    def flush(self) -> None:
        """Block until every queued write committed; re-raise a worker
        failure."""
        if self._worker is not None:
            self._q.join()
        self._reraise()

    def close(self) -> None:
        """Flush and stop the worker (idempotent)."""
        if self._worker is not None:
            self._q.join()
            self._q.put(None)
            self._worker.join(timeout=10.0)
            self._worker = None
        self._reraise()

    def _reraise(self) -> None:
        if self._error is not None:
            exc, self._error = self._error, None
            raise RuntimeError(
                "a background run-checkpoint write failed") from exc

    def __enter__(self) -> "RunCheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- scan / resume ------------------------------------------------------

    def _gen_dirs(self) -> List[str]:
        if not os.path.isdir(self.run_dir):
            return []
        out = []
        for entry in sorted(os.listdir(self.run_dir)):
            if entry.startswith(GEN_PREFIX):
                out.append(os.path.join(self.run_dir, entry))
        return out

    def scan(self) -> List[CheckpointGeneration]:
        """All COMPLETE generations, oldest first. Complete = manifest
        parses with the right magic AND every listed file exists."""
        out = []
        for d in self._gen_dirs():
            mpath = os.path.join(d, MANIFEST_NAME)
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                continue
            if manifest.get("magic") != RUN_MAGIC:
                continue
            if not all(os.path.exists(os.path.join(d, fn))
                       for fn in manifest.get("files", {})):
                continue
            out.append(CheckpointGeneration(
                step=int(manifest["step"]), path=d, manifest=manifest))
        out.sort(key=lambda g: g.step)
        return out

    def resume(self, tables: Optional[Sequence[Any]] = None, *,
               before_unix_time: Optional[float] = None,
               max_step: Optional[int] = None) -> Optional[RestoredState]:
        """Restore the latest complete generation (fall back to older
        ones when a payload fails verification). Returns the app
        train-state, or None when the run dir holds no usable
        checkpoint (a fresh run).

        ``before_unix_time`` / ``max_step`` restrict the search to
        generations committed strictly before that wall time / at or
        below that step — the health monitor's rollback uses the former
        to land on the newest generation PREDATING a divergence (a
        generation saved after the bad values entered storage would
        just restore the divergence)."""
        gens = self.scan()
        if before_unix_time is not None:
            gens = [g for g in gens
                    if float(g.manifest.get("unix_time", 0.0))
                    < before_unix_time]
        if max_step is not None:
            gens = [g for g in gens if g.step <= max_step]
        cover = list(tables) if tables is not None \
            else self._resolve_tables()
        for gen in reversed(gens):
            if self.fingerprint is not None \
                    and gen.manifest.get("fingerprint") is not None \
                    and gen.manifest["fingerprint"] != self.fingerprint:
                raise ValueError(
                    f"run checkpoint {gen.path!r} was written with "
                    f"config fingerprint {gen.manifest['fingerprint']!r}"
                    f" but this run has {self.fingerprint!r} — resuming "
                    "under a changed config silently trains wrong; "
                    "start a fresh run dir (or match the config)")
            try:
                restored = self._restore(gen, cover)
            except Exception as exc:
                telemetry.counter("ft.recover.fallbacks").inc()
                log.warn("run checkpoint %r unusable (%r); falling "
                         "back to an older generation", gen.path, exc)
                continue
            telemetry.counter("ft.recover.ops").inc()
            telemetry.gauge("ckpt.resumed_step").set(gen.step)
            _note_good(gen.path)
            log.info("run checkpoint: resumed step %d from %r",
                     gen.step, gen.path)
            return restored
        if gens:
            telemetry.counter("ft.recover.failures").inc()
        return None

    def _restore(self, gen: CheckpointGeneration,
                 cover: Sequence[Any]) -> RestoredState:
        tmap = gen.manifest.get("tables", {})
        missing = [t.name for t in cover if t.name not in tmap]
        if missing:
            raise ValueError(
                f"generation {gen.path!r} lacks tables {missing} — "
                "the run's table set changed")
        for t in cover:
            t.load(os.path.join(gen.path, tmap[t.name]))
        state: Dict[str, Any] = {}
        arrays: Dict[str, np.ndarray] = {}
        app_file = gen.manifest.get("app")
        if app_file:
            from multiverso_tpu.tables.base import loadz_stream
            manifest, data = loadz_stream(
                os.path.join(gen.path, app_file), APP_MAGIC)
            state = dict(manifest.get("state", {}))
            arrays = {k: np.asarray(data[k]) for k in data.files
                      if k != "manifest"}
        return RestoredState(step=gen.step, path=gen.path, state=state,
                             arrays=arrays)


def config_fingerprint(config: Any) -> str:
    """CLI-relevant config fingerprint: crc32 of the sorted-JSON dump
    of the app's config dataclass. Stamped into every run manifest and
    checked at resume — resuming a run dir under a changed config fails
    loudly instead of silently training wrong."""
    import dataclasses
    import zlib
    doc = json.dumps(dataclasses.asdict(config), sort_keys=True,
                     default=str)
    return f"{zlib.crc32(doc.encode()) & 0xFFFFFFFF:08x}"


def define_run_flags() -> None:
    """Register the shared fault-tolerance CLI flags (every app main
    calls this before ``core.init``): ``-run_dir``, ``-resume``,
    ``-ckpt_every`` — env fallbacks ``MVTPU_RUN_DIR`` /
    ``MVTPU_RESUME`` / ``MVTPU_CKPT_EVERY``."""
    from multiverso_tpu.utils import configure
    configure.define_string(
        "run_dir", "", "fault-tolerance run directory: enables the "
        "run-level checkpoint manager (also MVTPU_RUN_DIR)",
        overwrite=True)
    configure.define_bool(
        "resume", False, "resume from the latest complete checkpoint "
        "generation in -run_dir (also MVTPU_RESUME=1)", overwrite=True)
    configure.define_int(
        "ckpt_every", 0, "checkpoint cadence in app steps/sweeps "
        "(also MVTPU_CKPT_EVERY; 0 = no periodic checkpoints)",
        overwrite=True)


def wire_app(app: Any, tables: Sequence[Any], *,
             every_default: int = 0) -> Optional[RunCheckpointManager]:
    """The app-side wiring: build a manager from flags/env (None when
    no run dir is configured), pin it to the app's tables, attach it as
    ``app.run_ckpt``, and — when resume is requested — restore the
    latest complete generation through ``app.restore_run_state``.

    The app contract: ``app.config`` (a dataclass, fingerprinted),
    ``app.run_state()`` (dict of arrays + json scalars) and
    ``app.restore_run_state(RestoredState)``.
    """
    from multiverso_tpu.utils import configure
    mgr = manager_from_env(configure.get_flag("run_dir"),
                           int(configure.get_flag("ckpt_every") or 0)
                           or every_default,
                           fingerprint=config_fingerprint(app.config))
    if mgr is None:
        return None
    mgr.set_tables(tables)
    app.run_ckpt = mgr
    want_resume = bool(configure.get_flag("resume")) \
        or os.environ.get(RESUME_ENV, "") not in ("", "0")
    if want_resume:
        restored = mgr.resume()
        if restored is not None:
            app.restore_run_state(restored)
        else:
            log.info("ft resume: no usable checkpoint in %r — "
                     "starting fresh", mgr.run_dir)
    return mgr


def manager_from_env(run_dir: str = "", every: int = 0,
                     fingerprint: Optional[str] = None
                     ) -> Optional[RunCheckpointManager]:
    """The app-wiring helper: a manager when a run dir is configured
    (flag value or ``MVTPU_RUN_DIR``), else None. Cadence from the flag
    or ``MVTPU_CKPT_EVERY``; retention from ``MVTPU_CKPT_KEEP``."""
    rd = run_dir or os.environ.get(RUN_DIR_ENV, "")
    if not rd:
        return None

    def _int_env(name: str, default: int) -> int:
        try:
            return int(os.environ.get(name, "") or default)
        except ValueError:
            return default
    ev = every if every > 0 else _int_env(CKPT_EVERY_ENV, 0)
    keep = max(_int_env(CKPT_KEEP_ENV, 3), 1)
    return RunCheckpointManager(rd, keep=keep, every=ev,
                                fingerprint=fingerprint)
