"""Flag/config registry.

TPU-native equivalent of the reference's configure system
(`include/multiverso/util/configure.h`, `src/util/configure.cpp` in the
upstream microsoft/Multiverso layout — see SURVEY.md §3.7 / §6.6): the
reference registers flags with ``MV_DEFINE_string/int/bool(name, default,
help)`` macros into a process-global registry and parses ``-name=value``
CLI tokens inside ``MV_Init``.

This module keeps that contract — ``define_string/int/bool/float`` register
into a global registry, ``parse_flags(argv)`` consumes ``-name=value`` (and
``--name=value``) tokens and returns the unrecognised remainder, and
``get_flag(name)`` reads the current value — so reference-style run scripts
port unchanged.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence


@dataclass
class _FlagEntry:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]
    value: Any


class FlagRegistry:
    """Process-global registry of -name=value flags."""

    def __init__(self) -> None:
        self._entries: Dict[str, _FlagEntry] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help_str: str,
               parser: Callable[[str], Any],
               overwrite: bool = False) -> None:
        with self._lock:
            if name in self._entries and not overwrite:
                # Re-definition with identical default is a no-op (module
                # reloads in tests); conflicting re-definition is an error
                # unless the caller owns the flag (overwrite=True — app
                # mains redefining another app's CLI flag in-process,
                # where the reference would be separate binaries).
                existing = self._entries[name]
                if existing.default == default:
                    return
                raise ValueError(
                    f"flag {name!r} already defined with default "
                    f"{existing.default!r}, conflicting default {default!r}")
            # overwrite installs a FRESH entry: the value resets to the
            # new default so a previous app's argv cannot leak through
            self._entries[name] = _FlagEntry(name, default, help_str, parser,
                                             default)

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"unknown flag {name!r}")
            self._entries[name].value = value

    def get(self, name: str) -> Any:
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"unknown flag {name!r}")
            return self._entries[name].value

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def reset(self, name: Optional[str] = None) -> None:
        """Reset one flag (or all flags) back to default values."""
        with self._lock:
            if name is None:
                for e in self._entries.values():
                    e.value = e.default
            else:
                self._entries[name].value = self._entries[name].default

    def parse(self, argv: Sequence[str]) -> List[str]:
        """Parse ``-name=value`` / ``--name=value`` tokens.

        Recognised flags are consumed and set; everything else is returned
        in order (mirroring the reference's ParseCMDFlags, which leaves
        unknown args for the app).
        """
        remainder: List[str] = []
        for tok in argv:
            if tok.startswith("-") and "=" in tok:
                name, _, raw = tok.lstrip("-").partition("=")
                with self._lock:
                    entry = self._entries.get(name)
                if entry is not None:
                    self.set(name, entry.parser(raw))
                    continue
            remainder.append(tok)
        return remainder

    def describe(self) -> str:
        with self._lock:
            lines = []
            for e in sorted(self._entries.values(), key=lambda e: e.name):
                lines.append(f"  -{e.name}={e.value!r} (default {e.default!r})"
                             f" : {e.help}")
        return "\n".join(lines)


_REGISTRY = FlagRegistry()


def _parse_bool(raw: str) -> bool:
    low = raw.strip().lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"cannot parse bool flag value {raw!r}")


def define_string(name: str, default: str, help_str: str = "",
                  overwrite: bool = False) -> None:
    _REGISTRY.define(name, default, help_str, str, overwrite)


def define_int(name: str, default: int, help_str: str = "",
               overwrite: bool = False) -> None:
    _REGISTRY.define(name, default, help_str, int, overwrite)


def define_float(name: str, default: float, help_str: str = "",
                 overwrite: bool = False) -> None:
    _REGISTRY.define(name, default, help_str, float, overwrite)


def define_bool(name: str, default: bool, help_str: str = "",
                overwrite: bool = False) -> None:
    _REGISTRY.define(name, default, help_str, _parse_bool, overwrite)


def get_flag(name: str) -> Any:
    return _REGISTRY.get(name)


def set_flag(name: str, value: Any) -> None:
    _REGISTRY.set(name, value)


def has_flag(name: str) -> bool:
    return _REGISTRY.has(name)


def reset_flags(name: Optional[str] = None) -> None:
    _REGISTRY.reset(name)


def parse_flags(argv: Sequence[str]) -> List[str]:
    return _REGISTRY.parse(argv)


def describe_flags() -> str:
    return _REGISTRY.describe()


# Core framework flags, mirroring the reference's known set (SURVEY.md §6.6).
define_bool("sync", True, "synchronous (BSP) mode; on TPU sync DP is native")
define_string("updater_type", "default",
              "server-side updater: default|sgd|adagrad|momentum|adam")
define_string("log_level", "info", "logging level: debug|info|warn|error|fatal")
define_string("log_file", "", "optional log file sink (empty = stderr only)")
define_string("machine_file", "",
              "coordinator address list for multi-host bootstrap "
              "(reference: ZMQ machine list; here: jax.distributed)")
define_int("port", 0, "coordinator port for multi-host bootstrap")
define_int("num_processes", 0,
           "multi-host process count (0 = auto-detect from the platform; "
           "required for CPU multi-process runs)")
define_int("process_id", -1,
           "this host's process id (-1 = auto-detect from the platform; "
           "required for CPU multi-process runs)")
define_int("data_parallel", 0,
           "data-parallel mesh axis size (0 = all local devices)")
define_int("model_parallel", 1, "model-parallel mesh axis size")
