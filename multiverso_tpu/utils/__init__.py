"""Cross-cutting utilities (SURVEY.md §3.7): flags, logging, dashboard,
timers, async double-buffering."""

from multiverso_tpu.utils import async_buffer, configure, dashboard, log
from multiverso_tpu.utils.async_buffer import ASyncBuffer, prefetch_iterator
from multiverso_tpu.utils.configure import (define_bool, define_float,
                                            define_int, define_string,
                                            describe_flags, get_flag,
                                            has_flag, parse_flags,
                                            reset_flags, set_flag)
from multiverso_tpu.utils.dashboard import (Timer, emit_metric, monitor,
                                            profile, report)

__all__ = [
    "async_buffer", "configure", "dashboard", "log",
    "ASyncBuffer", "prefetch_iterator",
    "define_bool", "define_float", "define_int", "define_string",
    "describe_flags", "get_flag", "has_flag", "parse_flags", "reset_flags",
    "set_flag", "Timer", "emit_metric", "monitor", "profile", "report",
]
