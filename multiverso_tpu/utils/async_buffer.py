"""ASyncBuffer: background-filled double buffer for prefetch pipelines.

TPU-native equivalent of the reference's double-buffer utility
(`include/multiverso/util/async_buffer.h` upstream layout; SURVEY.md §3.7):
a background thread produces buffer k+1 while the caller consumes buffer k.
The reference uses this to overlap parameter prefetch / data-block IO with
trainer compute (word2vec ParameterLoader, LightLDA block streaming,
SURVEY.md §4.5); here it overlaps host-side batch production with TPU steps.

Also provides ``prefetch_iterator`` — a bounded-queue generator wrapper,
the common shape for feeding a jitted train loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")


class ASyncBuffer(Generic[T]):
    """Two-slot buffer: ``fill_fn(slot_index)`` runs on ONE persistent
    worker thread fed by a request queue (a thread create/teardown per
    fill would put ~100µs of OS work back on the per-batch path this
    buffer exists to hide).

    ``get()`` blocks until the in-flight fill completes, returns the filled
    value, and immediately kicks off the next fill — the caller always
    overlaps its consumption of buffer k with the production of buffer k+1.
    ``poll()`` is the non-blocking variant (the staleness-bounded get
    cache's absorb path): the filled value when the in-flight fill has
    completed, else ``None`` — and a completed poll kicks the next fill
    exactly like ``get()``.
    """

    def __init__(self, fill_fn: Callable[[int], T],
                 name: Optional[str] = None) -> None:
        self._fill_fn = fill_fn
        # named buffers publish queue.depth/queue.age_s gauges (lazy
        # import: this module stays importable without the telemetry
        # package initialised)
        self._qg = None
        if name is not None:
            from multiverso_tpu.telemetry.metrics import QueueGauges
            self._qg = QueueGauges(f"async:{name}")
        self._requests: "queue.Queue[Optional[int]]" = queue.Queue()
        self._results: "queue.Queue[tuple[Optional[T], Optional[BaseException]]]" = (
            queue.Queue(maxsize=1))
        self._index = 0
        self._stopped = False
        self._thread = threading.Thread(target=self._work, daemon=True)
        self._thread.start()
        self._kick()

    def _work(self) -> None:
        while True:
            idx = self._requests.get()
            if idx is None:         # stop() sentinel
                return
            if self._qg is not None:
                self._qg.on_take()
            try:
                item = (self._fill_fn(idx), None)
            except BaseException as exc:  # propagate to consumer
                item = (None, exc)
            # bounded offer: an unconditional put would wedge the worker
            # forever when the consumer stops draining after stop()
            while not self._stopped:
                try:
                    self._results.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _kick(self) -> None:
        self._requests.put(self._index)
        self._index += 1
        if self._qg is not None:
            self._qg.on_put()

    def _consume(self, value: Optional[T],
                 exc: Optional[BaseException]) -> T:
        if exc is not None:
            self._stopped = True
            raise exc
        self._kick()
        return value  # type: ignore[return-value]

    def get(self) -> T:
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        value, exc = self._results.get()
        return self._consume(value, exc)

    def poll(self) -> Optional[T]:
        """Non-blocking ``get``: the filled value when the in-flight fill
        is done (kicking the next fill), else ``None``. A fill_fn that can
        itself return ``None`` is indistinguishable from "not ready" —
        such producers should use ``get()``. Fill errors raise here just
        like ``get()``."""
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        try:
            value, exc = self._results.get_nowait()
        except queue.Empty:
            return None
        return self._consume(value, exc)

    def stop(self) -> None:
        self._stopped = True
        self._requests.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def prefetch_iterator(it: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Run ``it`` on a background thread, buffering up to ``depth`` items.

    Closing the generator (``break`` in the consumer, ``.close()``, GC)
    cancels the producer thread so the source iterator is released.
    """
    q: "queue.Queue[object]" = queue.Queue(maxsize=depth)
    _END = object()
    cancel = threading.Event()

    def _put_cancellable(item) -> bool:
        """Offer to the queue until accepted or the consumer cancels;
        an unconditional blocking put would deadlock the producer thread
        forever when the consumer stops draining with a full queue."""
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def work() -> None:
        try:
            for item in it:
                if not _put_cancellable(item):
                    return
            _put_cancellable(_END)
        except BaseException as exc:
            _put_cancellable(exc)

    thread = threading.Thread(target=work, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item  # type: ignore[misc]
    finally:
        cancel.set()
