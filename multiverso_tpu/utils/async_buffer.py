"""ASyncBuffer: background-filled double buffer for prefetch pipelines.

TPU-native equivalent of the reference's double-buffer utility
(`include/multiverso/util/async_buffer.h` upstream layout; SURVEY.md §3.7):
a background thread produces buffer k+1 while the caller consumes buffer k.
The reference uses this to overlap parameter prefetch / data-block IO with
trainer compute (word2vec ParameterLoader, LightLDA block streaming,
SURVEY.md §4.5); here it overlaps host-side batch production with TPU steps.

Also provides ``prefetch_iterator`` — a bounded-queue generator wrapper,
the common shape for feeding a jitted train loop.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")


class ASyncBuffer(Generic[T]):
    """Two-slot buffer: ``fill_fn(slot_index)`` runs on a worker thread.

    ``get()`` blocks until the in-flight fill completes, returns the filled
    value, and immediately kicks off the next fill — the caller always
    overlaps its consumption of buffer k with the production of buffer k+1.
    """

    def __init__(self, fill_fn: Callable[[int], T]) -> None:
        self._fill_fn = fill_fn
        self._results: "queue.Queue[tuple[Optional[T], Optional[BaseException]]]" = (
            queue.Queue(maxsize=1))
        self._index = 0
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._kick()

    def _kick(self) -> None:
        def work(idx: int) -> None:
            try:
                self._results.put((self._fill_fn(idx), None))
            except BaseException as exc:  # propagate to consumer
                self._results.put((None, exc))

        self._thread = threading.Thread(target=work, args=(self._index,),
                                        daemon=True)
        self._thread.start()
        self._index += 1

    def get(self) -> T:
        if self._stopped:
            raise RuntimeError("ASyncBuffer already stopped")
        value, exc = self._results.get()
        if exc is not None:
            self._stopped = True
            raise exc
        self._kick()
        return value

    def stop(self) -> None:
        self._stopped = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def prefetch_iterator(it: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Run ``it`` on a background thread, buffering up to ``depth`` items.

    Closing the generator (``break`` in the consumer, ``.close()``, GC)
    cancels the producer thread so the source iterator is released.
    """
    q: "queue.Queue[object]" = queue.Queue(maxsize=depth)
    _END = object()
    cancel = threading.Event()

    def _put_cancellable(item) -> bool:
        """Offer to the queue until accepted or the consumer cancels;
        an unconditional blocking put would deadlock the producer thread
        forever when the consumer stops draining with a full queue."""
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def work() -> None:
        try:
            for item in it:
                if not _put_cancellable(item):
                    return
            _put_cancellable(_END)
        except BaseException as exc:
            _put_cancellable(exc)

    thread = threading.Thread(target=work, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item  # type: ignore[misc]
    finally:
        cancel.set()
