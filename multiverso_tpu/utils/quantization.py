"""Delta quantization filters — the reference's optional compression of
matrix deltas before send (upstream layout
`include/multiverso/util/quantization_util.h`, SURVEY.md §3.7 [L]:
1-bit and rounding quantizers).

On TPU there is no wire to compress for the in-program collectives, but
the same filters matter for DCN-crossing transfers (multi-slice grads,
host checkpoint streams) and for memory-footprint control. Both
quantizers are pure jittable functions.

- :class:`OneBitQuantizer` — sign bit + per-block mean magnitude, with
  local error feedback (the residual is carried and added to the next
  delta, the standard 1-bit-SGD trick the reference family used).
- :class:`RoundingQuantizer` — stochastic rounding to int8/int16 with a
  per-block scale; unbiased (E[dequant] = value).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _block_view(x: jax.Array, block: int) -> Tuple[jax.Array, int]:
    """Flatten and zero-pad to whole blocks; returns ([n_blocks, block],
    original size)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    return flat.reshape(-1, block), n


@dataclasses.dataclass(frozen=True)
class OneBitQuantizer:
    """sign(delta) + per-block mean |delta|, with error feedback."""
    block: int = 512

    @partial(jax.jit, static_argnums=0)
    def quantize(self, delta: jax.Array,
                 residual: Optional[jax.Array] = None):
        """Returns (sign int8 [n_blocks, block] in {0,1} — UNPACKED, one
        byte per element; use :meth:`pack_signs` for the 1-bit wire format
        — pos/neg scales f32 [n_blocks], new_residual like delta)."""
        if residual is not None:
            delta = delta + residual
        blocks, n = _block_view(delta, self.block)
        # exclude the final block's zero pads from the sign counts —
        # they would dilute pos_scale (pads sign as positive)
        valid = (jnp.arange(blocks.size).reshape(blocks.shape) < n)
        sign = (blocks >= 0)
        pos = sign & valid
        neg = (~sign) & valid
        # one scale per block per sign-side: mean magnitude of that side
        pos_scale = jnp.sum(jnp.where(pos, blocks, 0.0), axis=1) / \
            jnp.maximum(jnp.sum(pos, axis=1), 1)
        neg_scale = jnp.sum(jnp.where(neg, -blocks, 0.0), axis=1) / \
            jnp.maximum(jnp.sum(neg, axis=1), 1)
        deq = jnp.where(sign, pos_scale[:, None], -neg_scale[:, None])
        new_residual = (blocks - deq).reshape(-1)[:n].reshape(delta.shape)
        return (sign.astype(jnp.int8), pos_scale.astype(jnp.float32),
                neg_scale.astype(jnp.float32), new_residual)

    @partial(jax.jit, static_argnums=(0, 4))
    def dequantize(self, sign, pos_scale, neg_scale, shape):
        deq = jnp.where(sign.astype(bool), pos_scale[:, None],
                        -neg_scale[:, None])
        n = int(np.prod(shape))
        return deq.reshape(-1)[:n].reshape(shape)

    @partial(jax.jit, static_argnums=0)
    def pack_signs(self, sign: jax.Array) -> jax.Array:
        """[n_blocks, block] {0,1} → uint8 [n_blocks, block//8]: the actual
        1-bit wire format (8 signs per byte, LSB-first) for DCN-crossing
        transfers. ``block`` must be a multiple of 8 (default 512 is)."""
        nb, blk = sign.shape
        grouped = sign.astype(jnp.uint8).reshape(nb, blk // 8, 8)
        shifts = jnp.arange(8, dtype=jnp.uint8)
        return jnp.sum(grouped << shifts, axis=-1).astype(jnp.uint8)

    @partial(jax.jit, static_argnums=0)
    def unpack_signs(self, packed: jax.Array) -> jax.Array:
        """uint8 [n_blocks, block//8] → int8 [n_blocks, block] {0,1}."""
        nb, nbytes = packed.shape
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (packed[..., None] >> shifts) & jnp.uint8(1)
        return bits.reshape(nb, nbytes * 8).astype(jnp.int8)


@dataclasses.dataclass(frozen=True)
class RoundingQuantizer:
    """Unbiased stochastic rounding to a fixed-point grid."""
    bits: int = 8                 # 8 -> int8, 16 -> int16
    block: int = 512

    @property
    def _qmax(self) -> int:
        return (1 << (self.bits - 1)) - 1

    @partial(jax.jit, static_argnums=0)
    def quantize(self, delta: jax.Array, key: jax.Array):
        """Returns (q int8/int16 [n_blocks, block], scales f32)."""
        blocks, n = _block_view(delta, self.block)
        scale = jnp.max(jnp.abs(blocks), axis=1) / self._qmax
        scale = jnp.maximum(scale, 1e-30)
        scaled = blocks / scale[:, None]
        low = jnp.floor(scaled)
        p_up = scaled - low                       # P(round up), unbiased
        up = jax.random.uniform(key, scaled.shape) < p_up
        q = jnp.clip(low + up, -self._qmax, self._qmax)
        dtype = jnp.int8 if self.bits <= 8 else jnp.int16
        return q.astype(dtype), scale.astype(jnp.float32)

    @partial(jax.jit, static_argnums=(0, 3))
    def dequantize(self, q, scale, shape):
        deq = q.astype(jnp.float32) * scale[:, None]
        n = int(np.prod(shape))
        return deq.reshape(-1)[:n].reshape(shape)


# -- wire-side numpy twins + error-feedback state --------------------------
#
# The parameter-server wire (server/wire.py) quantizes deltas in
# jax-free worker processes, so it carries NUMPY twins of the two
# quantizers above — bit-for-bit parity is pinned in
# tests/test_wire.py (same packed signs, same scales, same residual).
# Re-exported here so quantization users find one module.
#
# ResidualStore is also the fix for a real error-feedback hazard the
# single-residual API above leaves to the caller: OneBitQuantizer's
# ``residual`` is positional state, and a client interleaving TABLES or
# BATCH SHAPES (two dense tables, or a dense table and a KV stream)
# would feed table A's quantization error into table B's next delta —
# silent cross-contamination (or a shape error, in the lucky case).
# The store keys every residual by (table id, add kind, delta shape,
# block), so error feedback only ever flows between same-geometry
# deltas of the same table. The wire's 1-bit path refuses KV batches
# outright (their key sets change per batch, so "same geometry" does
# not mean "same keys") and falls back to the unbiased stateless int8
# path — see ``server/wire.py:encode_delta``.

from multiverso_tpu.server.wire import (      # noqa: E402,F401
    ResidualStore, one_bit_dequantize_np, one_bit_quantize_np,
    rounding_dequantize_np, rounding_quantize_np)

__all__ = [
    "OneBitQuantizer", "RoundingQuantizer", "ResidualStore",
    "one_bit_quantize_np", "one_bit_dequantize_np",
    "rounding_quantize_np", "rounding_dequantize_np",
]
