"""Dashboard / Monitor: named timing accumulators + structured metrics.

TPU-native equivalent of the reference's profiling dashboard
(`include/multiverso/dashboard.h`, `src/dashboard.cpp` upstream layout;
SURVEY.md §3.7 / §6.1): named monitors accumulate call count and elapsed
wall-clock around instrumented regions and are dumped as a table at
shutdown or on demand.

Extensions for the TPU build (SURVEY.md §6.5): a JSONL metric sink so
per-step throughput metrics (`words/sec/chip`, `doc-tokens/sec`) are
scriptable, and a context-manager / decorator API instead of
MONITOR_BEGIN/END macros.

TPU profiler integration (SURVEY.md §6.1: "per-step wall-clock dashboard
+ `jax.profiler.trace` hooks; name-tag compiled regions with
`jax.named_scope`"): ``profile(name)`` wraps the region in a
``jax.named_scope`` (host-side begin; tags device ops traced inside it)
and :func:`trace` captures a TensorBoard-loadable device trace of any
code block.

BACK-COMPAT SHIM over :mod:`multiverso_tpu.telemetry`: the Monitor API
and record shapes are unchanged, but every ``profile`` region also
observes into the process-wide metric registry (histogram
``dashboard.seconds{region=...}``) and emits a span into the telemetry
trace, and every ``emit_metric`` also sets the registry gauge of the
same name and rides the registry's JSONL sink — so legacy call sites
show up in registry snapshots, fleet aggregation, and the report CLI
without being touched.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, TextIO

from multiverso_tpu.telemetry import metrics as telemetry_metrics
from multiverso_tpu.telemetry import trace as telemetry_trace


@dataclass
class Monitor:
    name: str
    count: int = 0
    total_s: float = 0.0
    _begin: Optional[float] = field(default=None, repr=False)

    def begin(self) -> None:
        self._begin = time.perf_counter()

    def end(self) -> None:
        if self._begin is None:
            raise RuntimeError(f"Monitor {self.name!r}: end() without begin()")
        self.total_s += time.perf_counter() - self._begin
        self.count += 1
        self._begin = None

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Dashboard:
    """Process-wide registry of monitors + JSONL metric sink."""

    def __init__(self) -> None:
        self._monitors: Dict[str, Monitor] = {}
        self._lock = threading.Lock()
        self._jsonl: Optional[TextIO] = None

    def monitor(self, name: str) -> Monitor:
        with self._lock:
            mon = self._monitors.get(name)
            if mon is None:
                mon = Monitor(name)
                self._monitors[name] = mon
            return mon

    @contextlib.contextmanager
    def profile(self, name: str) -> Iterator[Monitor]:
        """Time a region AND tag any ops traced inside it: the region
        runs under a telemetry span, which enters ``jax.named_scope``
        when jax is loaded — a `jax.profiler` device trace shows the
        dashboard's monitor names on the compiled ops, and the span
        lands in the telemetry trace + latency histogram."""
        mon = self.monitor(name)
        start = time.perf_counter()
        try:
            with telemetry_trace.span(name):
                yield mon
        finally:
            dt = time.perf_counter() - start
            with self._lock:
                mon.total_s += dt
                mon.count += 1
            telemetry_metrics.histogram(
                "dashboard.seconds", region=name).observe(dt)

    @contextlib.contextmanager
    def trace(self, log_dir: str) -> Iterator[None]:
        """Capture a device profiler trace (TensorBoard / Perfetto
        loadable) for the wrapped block — the `jax.profiler.trace` hook
        the reference's Dashboard has no analog for (SURVEY.md §6.1)."""
        import jax
        with jax.profiler.trace(log_dir):
            yield

    def set_jsonl(self, path: str) -> None:
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = open(path, "a") if path else None

    def emit_metric(self, name: str, value: float, unit: str = "",
                    **extra) -> dict:
        """Emit one structured metric record (stdout-friendly JSON).

        Shim: the record also goes through the telemetry registry
        (gauge of the same name + the registry's own JSONL sink), so
        legacy emits ride snapshots and fleet aggregation."""
        rec = telemetry_metrics.emit(name, value, unit, **extra)
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(rec) + "\n")
                self._jsonl.flush()
        return rec

    def report(self) -> str:
        with self._lock:
            mons = sorted(self._monitors.values(), key=lambda m: m.name)
        if not mons:
            return "(dashboard: no monitors)"
        w = max(len(m.name) for m in mons)
        lines = [f"{'monitor'.ljust(w)}  count     total_s      mean_ms"]
        for m in mons:
            lines.append(f"{m.name.ljust(w)}  {m.count:5d}  {m.total_s:10.4f}"
                         f"  {m.mean_s * 1e3:11.4f}")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._monitors.clear()


_DASHBOARD = Dashboard()


def dashboard() -> Dashboard:
    return _DASHBOARD


def profile(name: str):
    return _DASHBOARD.profile(name)


def monitor(name: str) -> Monitor:
    return _DASHBOARD.monitor(name)


def emit_metric(name: str, value: float, unit: str = "", **extra) -> dict:
    return _DASHBOARD.emit_metric(name, value, unit, **extra)


def report() -> str:
    return _DASHBOARD.report()


def trace(log_dir: str):
    """Module-level alias for :meth:`Dashboard.trace`."""
    return _DASHBOARD.trace(log_dir)


class Timer:
    """Simple restartable stopwatch (reference `util/timer.h` equivalent)."""

    def __init__(self) -> None:
        self._start = time.perf_counter()

    def restart(self) -> None:
        self._start = time.perf_counter()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._start

    def elapsed_ms(self) -> float:
        return self.elapsed_s() * 1e3
