"""Leveled logger.

TPU-native equivalent of the reference logger
(`include/multiverso/util/log.h`, `src/util/log.cpp` upstream layout;
SURVEY.md §3.7 / §6.5): levels Debug/Info/Warn/Error/Fatal, timestamps,
optional file sink, Fatal aborts the process. Static-style API::

    from multiverso_tpu.utils import log
    log.info("loaded %d rows", n)
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional, TextIO

DEBUG, INFO, WARN, ERROR, FATAL = 0, 1, 2, 3, 4

_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", WARN: "WARN",
                ERROR: "ERROR", FATAL: "FATAL"}
_NAME_LEVELS = {v.lower(): k for k, v in _LEVEL_NAMES.items()}
_NAME_LEVELS["warning"] = WARN


def _host_index() -> int:
    """Host identity stamp — the SAME fields the telemetry aggregation
    layer puts on snapshots (metrics.host_index duplicates this lookup;
    keep them in agreement), so multihost logs, traces, and watchdog
    dumps correlate by (host, pid). Never imports jax: the logger must
    work in jax-free processes (report CLI, bench pre-probe)."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    try:
        return int(os.environ.get("MVTPU_HOST_ID", "0"))
    except ValueError:
        return 0


class Logger:
    def __init__(self, level: int = INFO, file: Optional[str] = None) -> None:
        self._level = level
        self._lock = threading.Lock()
        self._file: Optional[TextIO] = None
        if file:
            self.set_file(file)

    def set_level(self, level) -> None:
        if isinstance(level, str):
            key = level.strip().lower()
            if key not in _NAME_LEVELS:
                raise ValueError(
                    f"unknown log level {level!r}; valid: "
                    f"{sorted(_NAME_LEVELS)}")
            level = _NAME_LEVELS[key]
        self._level = level

    def level(self) -> int:
        return self._level

    def set_file(self, path: str) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(path, "a") if path else None

    def write(self, level: int, fmt: str, *args) -> None:
        if level < self._level:
            return
        msg = (fmt % args) if args else fmt
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())
        ident = f"h{_host_index()}:{os.getpid()}"
        line = f"[{_LEVEL_NAMES[level]}] [{stamp}] [{ident}] {msg}"
        with self._lock:
            print(line, file=sys.stderr, flush=True)
            if self._file is not None:
                print(line, file=self._file, flush=True)
        if level >= FATAL:
            raise SystemExit(line)

    def debug(self, fmt: str, *args) -> None:
        self.write(DEBUG, fmt, *args)

    def info(self, fmt: str, *args) -> None:
        self.write(INFO, fmt, *args)

    def warn(self, fmt: str, *args) -> None:
        self.write(WARN, fmt, *args)

    def error(self, fmt: str, *args) -> None:
        self.write(ERROR, fmt, *args)

    def fatal(self, fmt: str, *args) -> None:
        self.write(FATAL, fmt, *args)


_LOGGER = Logger()


def logger() -> Logger:
    return _LOGGER


def set_level(level) -> None:
    _LOGGER.set_level(level)


def set_file(path: str) -> None:
    _LOGGER.set_file(path)


def debug(fmt: str, *args) -> None:
    _LOGGER.debug(fmt, *args)


def info(fmt: str, *args) -> None:
    _LOGGER.info(fmt, *args)


def warn(fmt: str, *args) -> None:
    _LOGGER.warn(fmt, *args)


def error(fmt: str, *args) -> None:
    _LOGGER.error(fmt, *args)


def fatal(fmt: str, *args) -> None:
    _LOGGER.fatal(fmt, *args)
