"""Version-portable jax API shims.

``shard_map`` moved from ``jax.experimental.shard_map`` (jax < 0.6,
``check_rep=``) to top-level ``jax.shard_map`` (``check_vma=``). Every
in-repo user goes through this wrapper so the codebase carries the new
spelling while still importing on the older jax this image ships.

The sharded kernel engine (``ops/table_kernels.py``) wraps every
per-shard Pallas grid in this shard_map with ``check_vma=False``:
interpret-mode pallas_call with scalar prefetch + input/output aliasing
does not carry the varying-manual-axes annotations the checker wants,
and the kernels are closed over per-shard operands by construction (no
cross-shard collectives inside the body)."""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    try:
        from jax import shard_map as _sm        # jax >= 0.6
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=check_vma)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=check_vma)
