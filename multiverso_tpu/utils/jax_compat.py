"""Version-portable jax API shims.

``shard_map`` moved from ``jax.experimental.shard_map`` (jax < 0.6,
``check_rep=``) to top-level ``jax.shard_map`` (``check_vma=``). Every
in-repo user goes through this wrapper so the codebase carries the new
spelling while still importing on the older jax this image ships."""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    try:
        from jax import shard_map as _sm        # jax >= 0.6
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=check_vma)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=check_vma)
