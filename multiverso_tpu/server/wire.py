"""Wire protocol: length-prefixed frames with zero-copy numpy payloads
and optional quantized delta encoding.

This is the codec both ends of the parameter-server wire speak —
:class:`~multiverso_tpu.server.table_server.TableServer` on the server
side, :mod:`multiverso_tpu.client.transport` on the worker side. It is
the analog of the reference's ZeroMQ message layer + its
``quantization_util.h`` delta filters, collapsed into one module.

Frame layout (little-endian)::

    | "MVW1" | u32 body_len | u32 header_len |  ← 12-byte prefix
    | header JSON (header_len bytes)         |
    | pad to 8 | payload 0 | pad to 8 | payload 1 | ...

- The header is small JSON (op, request id, table id, quant metadata,
  and the dtype/shape of every payload). Payload offsets are NOT
  stored: both ends derive them from the same rule (each payload
  8-byte aligned, in header order), which keeps the header free of a
  circular offsets-change-header-length dependency.
- An optional ``deadline`` header field carries a client-stamped
  absolute expiry in **epoch seconds** (``time.time()`` — wall-clock,
  the only base comparable across processes; monotonic clocks are
  per-process). The server drops already-expired requests at dispatch
  dequeue instead of doing dead work (:func:`stamp_deadline` /
  :func:`deadline_expired` are the shared convention).
- Payloads are raw array bytes. **Encoding** gather-writes the header
  and each array's buffer straight to the socket (``sendmsg`` — no
  join copy); **decoding** reads the body into ONE buffer and returns
  ``np.frombuffer`` views into it — zero-copy on both sides.

Quantized delta frames (``MVTPU_WIRE_QUANT=1bit|int8``): a delta
payload may ride the wire as

- ``1bit`` — sign bits (packed 8/byte) + per-block pos/neg mean
  magnitudes, with client-side error feedback: the quantization error
  is carried in a :class:`ResidualStore` keyed per **(table, kind,
  block geometry)** and added to the next same-geometry delta. Biased
  per step, convergent over steps (the 1-bit-SGD trick). Dense adds
  only: a KV batch's key set changes frame to frame, so a geometry
  residual would be fed back to *different keys'* deltas — for KV this
  mode silently uses int8 instead.
- ``int8`` — stochastic rounding to int8 with a per-block scale.
  Unbiased per element (E[dequant] = value) and stateless, so it is
  safe for any payload, including variable-key KV batches.

The server dequantizes BEFORE apply: tables always see float deltas.

This module is stdlib + numpy only and file-path loadable standalone
(the ``telemetry/watchdog.py`` convention): worker processes load the
client transport without importing the package, so a fleet of workers
never pays the jax import. Dependencies resolve through
:func:`_dep` — already-loaded module, else normal import when the
package is up, else a file-path load registered under the canonical
module name (so chaos/retry/metrics state stays process-global either
way).
"""

from __future__ import annotations

import json
import os
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _dep(modname: str, *relpath: str):
    """Resolve a sibling module without forcing the package (and jax)
    in: sys.modules hit → that module; package already imported →
    normal import; else file-path load registered under the canonical
    name."""
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    if "multiverso_tpu" in sys.modules:
        import importlib
        return importlib.import_module(modname)
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, *relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(modname, None)
        raise
    return mod


_chaos = _dep("multiverso_tpu.ft.chaos", "ft", "chaos.py")
_metrics = _dep("multiverso_tpu.telemetry.metrics", "telemetry",
                "metrics.py")
wiresock = _dep("multiverso_tpu.io.wiresock", "io", "wiresock.py")
shmring = _dep("multiverso_tpu.io.shmring", "io", "shmring.py")

MAGIC = b"MVW1"
_PREFIX = struct.Struct("<4sII")
PREFIX_BYTES = _PREFIX.size
_ALIGN = 8
_PAD = b"\0" * _ALIGN

QUANT_ENV = "MVTPU_WIRE_QUANT"
BLOCK_ENV = "MVTPU_WIRE_BLOCK"
QUANT_MODES = ("1bit", "int8")
#: payloads smaller than this ship raw — block scales would outweigh
#: the savings and tiny frames are latency- not bandwidth-bound
MIN_QUANT_ELEMS = 64


class WireProtocolError(RuntimeError):
    """Corrupt or non-protocol bytes on the wire. Deliberately NOT an
    OSError: a desynced stream is the same desynced stream on every
    attempt — retry policies must reconnect, not re-read."""


def quant_mode_from_env() -> Optional[str]:
    """``MVTPU_WIRE_QUANT`` → "1bit" | "int8" | None (off). A typo'd
    mode raises — silently shipping fp32 would fake the bench."""
    raw = os.environ.get(QUANT_ENV, "").strip().lower()
    if raw in ("", "0", "none", "off", "raw"):
        return None
    if raw not in QUANT_MODES:
        raise ValueError(f"{QUANT_ENV}={raw!r}: expected one of "
                         f"{QUANT_MODES} (or unset)")
    return raw


def wire_block() -> int:
    """Quantizer block length (``MVTPU_WIRE_BLOCK``, default 512 —
    must be a multiple of 8 for the packed sign format)."""
    try:
        block = int(os.environ.get(BLOCK_ENV, "") or 512)
    except ValueError:
        block = 512
    return max(8, (block // 8) * 8)


# -- deadline propagation --------------------------------------------------
# Client-stamped request expiry in the frame header. Epoch seconds on
# purpose: a deadline must compare across processes (client stamps,
# server checks), and time.monotonic() bases differ per process. Clock
# skew between same-host processes is microseconds — far below any
# useful request deadline.

DEADLINE_KEY = "deadline"
DEADLINE_ENV = "MVTPU_WIRE_DEADLINE_S"


def stamp_deadline(header: Dict[str, Any], timeout_s: float,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """Stamp an absolute expiry ``timeout_s`` from now into ``header``
    (no-op if the caller already stamped one — a resend must keep its
    original bytes)."""
    if DEADLINE_KEY not in header:
        header[DEADLINE_KEY] = (time.time() if now is None else now) \
            + float(timeout_s)
    return header


def deadline_expired(header: Dict[str, Any],
                     now: Optional[float] = None) -> bool:
    """True when the header carries a deadline that has passed.
    Unparseable deadlines count as absent (a malformed field must not
    turn into silent request drops)."""
    raw = header.get(DEADLINE_KEY)
    if raw is None:
        return False
    try:
        return (time.time() if now is None else now) > float(raw)
    except (TypeError, ValueError):
        return False


# -- trace context propagation ---------------------------------------------
# Client-stamped trace context in the frame header: request id, parent
# span id, and the client's (host, pid) identity. The server adopts it
# (telemetry.trace.adopt_remote) so server-side spans parent-link under
# the originating client request across the process boundary. Default
# ON; MVTPU_WIRE_TRACE=0 turns stamping off entirely — the key is then
# never added, so a disabled wire ships zero extra header bytes.

TRACE_KEY = "trace"
TRACE_ENV = "MVTPU_WIRE_TRACE"


def trace_enabled() -> bool:
    """``MVTPU_WIRE_TRACE`` knob — default on; "0"/"off"/"false"/"no"
    disable header trace stamping."""
    raw = os.environ.get(TRACE_ENV, "").strip().lower()
    return raw not in ("0", "off", "false", "no")


def stamp_trace(header: Dict[str, Any],
                ctx: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Stamp a trace context into ``header`` (no-op if one is already
    stamped — a resend must keep its original bytes — or ctx is
    falsy)."""
    if ctx and TRACE_KEY not in header:
        header[TRACE_KEY] = ctx
    return header


def trace_ctx(header: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The frame's trace context, or None. Malformed values (anything
    but a dict) count as absent — a bad field must not break serving."""
    raw = header.get(TRACE_KEY)
    return raw if isinstance(raw, dict) else None


# -- frame codec -----------------------------------------------------------

def encode_frame(header: Dict[str, Any],
                 arrays: Sequence[np.ndarray] = ()
                 ) -> Tuple[List[Any], int]:
    """Encode one frame → (buffer list for a gather-write, total
    bytes). The buffer list references each array's memory directly —
    no join copy; callers must not mutate the arrays until sent."""
    header = dict(header)
    arrs = [np.ascontiguousarray(a) for a in arrays]
    header["arrays"] = [{"dtype": a.dtype.str, "shape": list(a.shape)}
                        for a in arrs]
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    bufs: List[Any] = [None, hbytes]        # prefix patched below
    off = len(hbytes)
    for a in arrs:
        pad = (-off) % _ALIGN
        if pad:
            bufs.append(_PAD[:pad])
        bufs.append(memoryview(a).cast("B"))
        off += pad + a.nbytes
    if off > wiresock.MAX_FRAME_BYTES:
        raise WireProtocolError(f"frame body {off} bytes exceeds "
                                f"MAX_FRAME_BYTES")
    bufs[0] = _PREFIX.pack(MAGIC, off, len(hbytes))
    return bufs, PREFIX_BYTES + off


def decode_frame_body(body: bytearray, header_len: int
                      ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Parse a received frame body; the returned arrays are ZERO-COPY
    ``np.frombuffer`` views into ``body``."""
    try:
        header = json.loads(bytes(memoryview(body)[:header_len]))
    except (ValueError, UnicodeDecodeError) as exc:
        raise WireProtocolError(f"undecodable frame header: {exc}") \
            from exc
    arrays: List[np.ndarray] = []
    off = header_len
    for spec in header.get("arrays", ()):
        off += (-off) % _ALIGN
        dt = np.dtype(str(spec["dtype"]))
        shape = tuple(int(s) for s in spec["shape"])
        count = int(np.prod(shape)) if shape else 1
        end = off + count * dt.itemsize
        if end > len(body):
            raise WireProtocolError(
                f"frame payload overruns body ({end} > {len(body)})")
        arrays.append(np.frombuffer(body, dtype=dt, count=count,
                                    offset=off).reshape(shape))
        off = end
    return header, arrays


def _count(name: str, n: float = 1, **labels) -> None:
    try:
        _metrics.counter(name, **labels).inc(n)
    except Exception:
        pass


def send_frame(sock, header: Dict[str, Any],
               arrays: Sequence[np.ndarray] = (), *,
               role: str = "client") -> int:
    """Encode + gather-write one frame. Returns bytes put on the wire.
    Chaos point ``wire.send``: ``torn`` puts HALF the frame on the
    wire then drops the connection (the receiver sees a torn frame);
    ``drop`` closes before anything is sent."""
    bufs, nbytes = encode_frame(header, arrays)
    try:
        _chaos.chaos_point("wire.send")
    except _chaos.ChaosTornWrite as exc:
        flat = b"".join(bytes(b) for b in bufs)
        try:
            sock.sendall(flat[:max(1, len(flat) // 2)])
        except OSError:
            pass
        _close_socket(sock)
        raise ConnectionError(f"wire: torn frame ({exc})") from exc
    except _chaos.ChaosConnDrop:
        _close_socket(sock)
        raise
    wiresock.send_buffers(sock, bufs)
    _count("wire.tx.bytes", nbytes, role=role)
    _count("wire.tx.frames", role=role)
    return nbytes


def recv_frame(sock, *, role: str = "client"
               ) -> Tuple[Dict[str, Any], List[np.ndarray], int]:
    """Read one frame → (header, zero-copy arrays, bytes read).
    Raises ``ConnectionError`` on EOF / peer death mid-frame,
    :class:`WireProtocolError` on non-protocol bytes."""
    try:
        _chaos.chaos_point("wire.recv")
    except (_chaos.ChaosConnDrop, _chaos.ChaosTornWrite) as exc:
        _close_socket(sock)
        if isinstance(exc, _chaos.ChaosConnDrop):
            raise
        raise ConnectionError(f"wire: torn read ({exc})") from exc
    prefix = wiresock.recv_exact(sock, PREFIX_BYTES)
    magic, body_len, header_len = _PREFIX.unpack(bytes(prefix))
    if magic != MAGIC:
        raise WireProtocolError(f"bad frame magic {magic!r}")
    if body_len > wiresock.MAX_FRAME_BYTES or header_len > body_len:
        raise WireProtocolError(
            f"implausible frame lengths body={body_len} "
            f"header={header_len}")
    body = bytearray(body_len)
    wiresock.recv_exact_into(sock, memoryview(body))
    header, arrays = decode_frame_body(body, header_len)
    nbytes = PREFIX_BYTES + body_len
    _count("wire.rx.bytes", nbytes, role=role)
    _count("wire.rx.frames", role=role)
    return header, arrays, nbytes


def _close_socket(sock) -> None:
    """Shutdown-then-close. The shutdown matters: plain ``close()`` on
    an fd another thread is blocked in ``recv`` on does NOT wake that
    thread — the kernel socket stays referenced by the blocked syscall,
    so the peer never sees EOF and both ends hang. ``shutdown`` tears
    the connection down immediately for everyone."""
    try:
        sock.shutdown(2)            # SHUT_RDWR
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


# -- channels: one send/recv surface over sockets OR shm rings -------------
#
# `WireClient` and the server's per-connection loops talk to a Channel,
# not a socket: `send(header, arrays) -> nbytes`, `recv() -> (header,
# arrays, nbytes)`, `close()`. The socket channel is the frame calls
# above; the shm channel moves the SAME encoded frames through
# `io/shmring.py` rings and keeps the socket as doorbell + liveness.
# Everything above the channel (CoalescingBuffer, DeltaBatcher, dedup,
# retry) is transport-agnostic and runs unchanged on either.

class SocketChannel:
    """Frames over a stream socket (the PR-11 wire, unchanged)."""

    transport = "socket"

    def __init__(self, sock, *, role: str = "client",
                 first: Optional[tuple] = None) -> None:
        self.sock = sock
        self.role = role
        self._first = first     # a frame consumed during accept

    def send(self, header: Dict[str, Any],
             arrays: Sequence[np.ndarray] = ()) -> int:
        return send_frame(self.sock, header, arrays, role=self.role)

    def recv(self) -> Tuple[Dict[str, Any], List[np.ndarray], int]:
        if self._first is not None:
            first, self._first = self._first, None
            return first
        return recv_frame(self.sock, role=self.role)

    def close(self) -> None:
        _close_socket(self.sock)


class ShmChannel:
    """Frames through a shared-memory ring pair (same host only).

    Chaos point ``wire.shm.ring`` fires on every ring send next to the
    generic ``wire.send``: ``torn`` publishes HALF a record then closes
    (the peer sees a dead producer, exactly a SIGKILL mid-copy);
    ``latency`` stalls inside the chaos hook; ``drop`` closes before
    anything lands in the ring."""

    transport = "shm"

    def __init__(self, endpoint, *, role: str = "client") -> None:
        self.endpoint = endpoint
        self.role = role

    def send(self, header: Dict[str, Any],
             arrays: Sequence[np.ndarray] = ()) -> int:
        bufs, nbytes = encode_frame(header, arrays)
        try:
            _chaos.chaos_point("wire.send")
            _chaos.chaos_point("wire.shm.ring")
        except _chaos.ChaosTornWrite as exc:
            try:
                self.endpoint.send_torn(bufs, nbytes)
            except OSError:
                pass
            self.close()
            raise ConnectionError(
                f"wire: torn shm record ({exc})") from exc
        except _chaos.ChaosConnDrop:
            self.close()
            raise
        try:
            self.endpoint.send_bytes(bufs, nbytes,
                                     wiresock.io_timeout_s())
        except TimeoutError as exc:
            # ring full past the IO timeout == dead/stuck consumer:
            # same retry class as a socket that stopped acking
            self.close()
            raise ConnectionError(str(exc)) from exc
        _count("wire.tx.bytes", nbytes, role=self.role)
        _count("wire.tx.frames", role=self.role)
        _count("wire.shm.frames", role=self.role)
        return nbytes

    def recv(self) -> Tuple[Dict[str, Any], List[np.ndarray], int]:
        try:
            _chaos.chaos_point("wire.recv")
        except (_chaos.ChaosConnDrop, _chaos.ChaosTornWrite) as exc:
            self.close()
            if isinstance(exc, _chaos.ChaosConnDrop):
                raise
            raise ConnectionError(f"wire: torn read ({exc})") from exc
        buf = self.endpoint.recv_bytes()
        if len(buf) < PREFIX_BYTES:
            raise WireProtocolError(f"shm record too short ({len(buf)})")
        magic, body_len, header_len = _PREFIX.unpack_from(buf, 0)
        if magic != MAGIC:
            raise WireProtocolError(f"bad frame magic {magic!r}")
        if body_len != len(buf) - PREFIX_BYTES or header_len > body_len:
            raise WireProtocolError(
                f"implausible shm frame lengths body={body_len} "
                f"header={header_len} record={len(buf)}")
        header, arrays = decode_frame_body(
            memoryview(buf)[PREFIX_BYTES:], header_len)
        nbytes = PREFIX_BYTES + body_len
        _count("wire.rx.bytes", nbytes, role=self.role)
        _count("wire.rx.frames", role=self.role)
        return header, arrays, nbytes

    def close(self) -> None:
        self.endpoint.close()


def dial_channel(address: str, *, timeout: float = 10.0,
                 role: str = "client"):
    """Dial an address → a Channel. For ``shm://`` the client offers a
    ring pair over the unix socket at the path; a server that does not
    take the offer (plain unix listener at the same path) gets a
    normal :class:`SocketChannel` on the very same socket — graceful
    fallback, frames and semantics identical."""
    parsed = wiresock.parse_address(address)
    sock = wiresock.connect_socket(address, timeout=timeout)
    if parsed[0] != "shm":
        return SocketChannel(sock, role=role)
    try:
        try:
            c2s, s2c, cap = shmring.create_ring_pair(parsed[1])
        except OSError:
            # can't place ring files next to the socket (perms/quota):
            # the unix socket still works — fall back
            return SocketChannel(sock, role=role)
        try:
            send_frame(sock, {"op": "shm.map", "c2s": c2s, "s2c": s2c,
                              "bytes": cap}, role=role)
            header, _, _ = recv_frame(sock, role=role)
            if header.get("ok") and header.get("op") == "shm.ok":
                ep = shmring.open_endpoint(sock, tx_path=c2s,
                                           rx_path=s2c)
                return ShmChannel(ep, role=role)
            return SocketChannel(sock, role=role)
        finally:
            shmring.unlink_quiet(c2s, s2c)
    except BaseException:
        _close_socket(sock)
        raise


def accept_channel(sock, scheme: str, *, listen_path: Optional[str] = None,
                   role: str = "server"):
    """Server half: wrap an accepted socket in a Channel. On an shm
    listener the FIRST frame decides — an ``shm.map`` offer maps the
    client's rings (paths are pinned to the listen socket's directory)
    and acks; anything else is a plain-socket client that dialed the
    same path, served over a :class:`SocketChannel` with that first
    frame stashed for the read loop."""
    if scheme != "shm":
        return SocketChannel(sock, role=role)
    first = recv_frame(sock, role=role)
    header = first[0]
    if header.get("op") != "shm.map":
        return SocketChannel(sock, role=role, first=first)
    expect_dir = os.path.dirname(os.path.abspath(listen_path)) \
        if listen_path else None
    try:
        ep = shmring.open_endpoint(sock, tx_path=str(header["s2c"]),
                                   rx_path=str(header["c2s"]),
                                   expect_dir=expect_dir)
    except (OSError, ValueError, KeyError) as exc:
        send_frame(sock, {"ok": False, "op": "shm.ok",
                          "error": f"{type(exc).__name__}: {exc}"},
                   role=role)
        return SocketChannel(sock, role=role)
    send_frame(sock, {"ok": True, "op": "shm.ok", "bytes": ep.tx.cap},
               role=role)
    return ShmChannel(ep, role=role)


# -- numpy delta quantizers (jax twins live in utils/quantization.py) ------

def _block_view_np(x: np.ndarray, block: int) -> Tuple[np.ndarray, int]:
    """Flatten + zero-pad to whole blocks → ([n_blocks, block], n)."""
    flat = np.asarray(x, np.float32).reshape(-1)
    n = flat.size
    pad = (-n) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(-1, block), n


def one_bit_quantize_np(delta: np.ndarray,
                        residual: Optional[np.ndarray] = None,
                        block: int = 512):
    """1-bit quantization with error feedback — numpy twin of
    :class:`multiverso_tpu.utils.quantization.OneBitQuantizer` (bit-
    level parity asserted in tests). Returns (packed signs uint8
    [n_blocks, block//8] LSB-first, pos/neg scales f32 [n_blocks],
    new_residual shaped like ``delta``)."""
    delta = np.asarray(delta, np.float32)
    if residual is not None:
        delta = delta + residual
    blocks, n = _block_view_np(delta, block)
    valid = np.arange(blocks.size).reshape(blocks.shape) < n
    sign = blocks >= 0
    pos = sign & valid
    neg = (~sign) & valid
    pos_scale = (np.where(pos, blocks, 0.0).sum(axis=1)
                 / np.maximum(pos.sum(axis=1), 1)).astype(np.float32)
    neg_scale = (np.where(neg, -blocks, 0.0).sum(axis=1)
                 / np.maximum(neg.sum(axis=1), 1)).astype(np.float32)
    deq = np.where(sign, pos_scale[:, None], -neg_scale[:, None])
    new_residual = (blocks - deq).reshape(-1)[:n] \
        .reshape(delta.shape).astype(np.float32)
    packed = np.packbits(sign, axis=1, bitorder="little")
    return packed, pos_scale, neg_scale, new_residual


def one_bit_dequantize_np(packed: np.ndarray, pos_scale: np.ndarray,
                          neg_scale: np.ndarray, shape: Tuple[int, ...],
                          block: int = 512) -> np.ndarray:
    sign = np.unpackbits(packed, axis=1, count=block,
                         bitorder="little").astype(bool)
    deq = np.where(sign, pos_scale[:, None],
                   -neg_scale[:, None]).astype(np.float32)
    n = int(np.prod(shape)) if shape else 1
    return deq.reshape(-1)[:n].reshape(shape)


def rounding_quantize_np(delta: np.ndarray, rng: np.random.Generator,
                         bits: int = 8, block: int = 512):
    """Unbiased stochastic rounding — numpy twin of
    :class:`multiverso_tpu.utils.quantization.RoundingQuantizer`.
    Returns (q int8/int16 [n_blocks, block], scales f32)."""
    qmax = (1 << (bits - 1)) - 1
    blocks, _ = _block_view_np(delta, block)
    scale = np.maximum(np.abs(blocks).max(axis=1) / qmax,
                       1e-30).astype(np.float32)
    scaled = blocks / scale[:, None]
    low = np.floor(scaled)
    up = rng.random(scaled.shape) < (scaled - low)
    q = np.clip(low + up, -qmax, qmax)
    return q.astype(np.int8 if bits <= 8 else np.int16), scale


def rounding_dequantize_np(q: np.ndarray, scale: np.ndarray,
                           shape: Tuple[int, ...]) -> np.ndarray:
    deq = q.astype(np.float32) * scale[:, None]
    n = int(np.prod(shape)) if shape else 1
    return deq.reshape(-1)[:n].reshape(shape)


class ResidualStore:
    """Error-feedback residual state keyed per **(table, kind, block
    geometry)**.

    The naive EF pattern — one ``residual`` variable threaded through
    successive ``quantize`` calls — silently cross-contaminates the
    moment a client interleaves tables or batch shapes: table A's
    quantization error gets added to table B's next delta (or to a
    differently-shaped batch, where it is outright shape-invalid).
    This store makes the keying explicit: a residual is taken and
    replaced under ``(table_id, kind, delta shape, block)``, so only
    the *next same-geometry delta to the same table* ever sees it.
    Thread-safe.
    """

    def __init__(self) -> None:
        self._store: Dict[tuple, np.ndarray] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(table: int, kind: str, shape, block: int) -> tuple:
        return (int(table), str(kind),
                tuple(int(s) for s in shape), int(block))

    def take(self, table: int, kind: str, shape,
             block: int) -> Optional[np.ndarray]:
        """Pop the residual for this geometry (None on first use)."""
        with self._lock:
            return self._store.pop(self._key(table, kind, shape, block),
                                   None)

    def put(self, table: int, kind: str, shape, block: int,
            residual: np.ndarray) -> None:
        with self._lock:
            self._store[self._key(table, kind, shape, block)] = residual

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()


# -- delta payload codec ---------------------------------------------------

def encode_delta(delta: np.ndarray, mode: Optional[str], *,
                 table: int, kind: str,
                 residuals: Optional[ResidualStore] = None,
                 rng: Optional[np.random.Generator] = None,
                 block: Optional[int] = None
                 ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """One delta payload → (quant header metadata, wire arrays).

    ``kind`` is the add kind ("dense" | "kv"): 1-bit error feedback is
    dense-only (see module docstring) — KV batches under ``1bit`` ship
    int8. Small / non-float payloads always ship raw."""
    delta = np.asarray(delta)
    if (mode not in QUANT_MODES or delta.size < MIN_QUANT_ELEMS
            or delta.dtype.kind != "f"):
        return {"mode": "raw"}, [delta]
    block = int(block) if block else wire_block()
    meta = {"mode": mode, "shape": list(delta.shape), "block": block,
            "dtype": delta.dtype.str}
    if mode == "1bit" and kind == "dense":
        res = residuals.take(table, kind, delta.shape, block) \
            if residuals is not None else None
        packed, pos, neg, new_res = one_bit_quantize_np(delta, res,
                                                        block)
        if residuals is not None:
            residuals.put(table, kind, delta.shape, block, new_res)
        return meta, [packed, pos, neg]
    meta["mode"] = "int8"
    if rng is None:
        rng = np.random.default_rng()
    q, scale = rounding_quantize_np(delta, rng, bits=8, block=block)
    return meta, [q, scale]


def decode_delta(meta: Optional[Dict[str, Any]],
                 arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Inverse of :func:`encode_delta` — dequant-before-apply on the
    server side."""
    mode = (meta or {}).get("mode", "raw")
    if mode == "raw":
        return np.asarray(arrays[0])
    shape = tuple(int(s) for s in meta["shape"])
    block = int(meta["block"])
    if mode == "1bit":
        out = one_bit_dequantize_np(arrays[0], arrays[1], arrays[2],
                                    shape, block)
    elif mode == "int8":
        out = rounding_dequantize_np(arrays[0], arrays[1], shape)
    else:
        raise WireProtocolError(f"unknown delta encoding {mode!r}")
    return out.astype(np.dtype(str(meta.get("dtype", "<f4"))),
                      copy=False)


def decoded_nbytes(meta: Optional[Dict[str, Any]],
                   arrays: Sequence[np.ndarray]) -> int:
    """Byte size of the DECODED delta a payload carries — what a
    full-state/full-precision sync would have shipped. The replication
    tap uses decoded/encoded as its compression ratio without paying
    for an actual dequantize."""
    mode = (meta or {}).get("mode", "raw")
    if mode == "raw":
        return sum(int(np.asarray(a).nbytes) for a in arrays)
    n = 1
    for s in meta.get("shape", ()):
        n *= int(s)
    return n * np.dtype(str(meta.get("dtype", "<f4"))).itemsize


# -- replication frames ----------------------------------------------------
#
# A primary forwards each APPLIED mutation to its followers as one
# ``op="repl"`` frame: the original header rides verbatim under
# ``orig`` (same quant metadata, same option — the arrays pass through
# untouched, so the follower's dequant+apply is bit-identical to the
# primary's), plus the bookkeeping a follower needs for exactly-once
# promotion replay:
#
#   origin   original client id (single-frame forwards)
#   origins  [[client, rid], ...] for a FUSED group forwarded as one
#            pre-summed frame (1 apply = 1 generation on both sides)
#   pgen     the primary's table generation AFTER the apply — the
#            follower's staleness reference
#   tid      server-assigned table id for streamed creates (follower
#            creates with the SAME id so table-id spaces stay aligned)

REPL_OP = "repl"


def repl_wrap(orig_header: Dict[str, Any], *, origin: str,
              pgen: Optional[int] = None,
              origins: Optional[Sequence[Tuple[str, Any]]] = None,
              tid: Optional[int] = None) -> Dict[str, Any]:
    """Wrap one applied op's header as a replication frame header."""
    out: Dict[str, Any] = {"op": REPL_OP, "orig": dict(orig_header),
                           "origin": str(origin)}
    if pgen is not None:
        out["pgen"] = int(pgen)
    if origins:
        out["origins"] = [[str(c), r] for c, r in origins]
    if tid is not None:
        out["tid"] = int(tid)
    return out


def repl_unwrap(header: Dict[str, Any]) -> Tuple[
        Dict[str, Any], List[Tuple[str, Any]], Optional[int],
        Optional[int]]:
    """``(orig_header, origins, pgen, tid)`` off a replication frame.
    ``origins`` is always a list of (client, rid) pairs — the single-
    frame ``origin`` collapses into a one-entry list."""
    orig = dict(header.get("orig") or {})
    origins = [(str(c), r) for c, r in (header.get("origins") or [])]
    if not origins and header.get("origin") is not None:
        origins = [(str(header["origin"]), orig.get("rid"))]
    pgen = header.get("pgen")
    tid = header.get("tid")
    return (orig, origins,
            int(pgen) if pgen is not None else None,
            int(tid) if tid is not None else None)


# -- migration frames (live resharding v→v+1) ------------------------------
#
# A reshard streams ONLY the ranges :func:`partition.map_diff` says
# change hands, over the same MVW1 wire as everything else. Frame
# roles, all dispatched through the server's ``_execute``:
#
#   migrate_begin     admin → every member: the new map + member
#                     addresses; donors start streaming, everyone
#                     stages new-geometry shards
#   migrate_state     admin → member poll: phase, shipped/forwarded
#                     counters, whether this donor has drained
#   migrate_commit    admin → member: swap staging in, flip the
#                     member's map to v+1 (the fleet FILE flips after
#                     every member acks — atomically, via os.replace)
#   migrate_abort     admin → member: drop staging, keep serving v
#   migrate_manifest  donor → recipient: table specs so a brand-new
#                     member can create the tables (force_tid keeps
#                     table-id spaces aligned, like streamed creates)
#   migrate_chunk     donor → recipient: one moved range's raw values
#                     (dense: the value slice; kv: key/value rows),
#                     CRC32-stamped — a torn chunk aborts loudly
#   migrate_fwd       donor → recipient: a write that landed in an
#                     already-shipped range, forwarded with its
#                     (client, rid) origins so the recipient's dedup
#                     window keeps it exactly-once (the repl-stream
#                     trick, pointed sideways)
#   migrate_fin       donor → recipient: end of this donor's stream
#                     (chunk count + byte total for the recipient's
#                     own accounting)

MIGRATE_BEGIN = "migrate_begin"
MIGRATE_STATE = "migrate_state"
MIGRATE_COMMIT = "migrate_commit"
MIGRATE_ABORT = "migrate_abort"
MIGRATE_MANIFEST = "migrate_manifest"
MIGRATE_CHUNK = "migrate_chunk"
MIGRATE_FWD = "migrate_fwd"
MIGRATE_FIN = "migrate_fin"

#: every migrate frame op, for dispatch-completeness lint and the
#: admission layer's op classification
MIGRATE_OPS = (MIGRATE_BEGIN, MIGRATE_STATE, MIGRATE_COMMIT,
               MIGRATE_ABORT, MIGRATE_MANIFEST, MIGRATE_CHUNK,
               MIGRATE_FWD, MIGRATE_FIN)


def migrate_crc(arrays: Sequence[np.ndarray]) -> int:
    """CRC32 chained over every payload array's raw bytes — the chunk
    integrity stamp (same codec as checkpoint payload CRCs)."""
    import zlib
    crc = 0
    for a in arrays:
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return int(crc)


def migrate_chunk_header(plan: str, *, table: int, kind: str,
                         lo: int, hi: int, seq: int, from_rank: int,
                         arrays: Sequence[np.ndarray]) -> Dict[str, Any]:
    """One moved-range chunk's header. ``kind`` is "dense" (arrays =
    [values] for GLOBAL element range [lo, hi)) or "kv" (arrays =
    [keys u64, value rows] for keys whose logical bucket falls in
    [lo, hi))."""
    return {"op": MIGRATE_CHUNK, "plan": str(plan), "table": int(table),
            "kind": str(kind), "range": [int(lo), int(hi)],
            "seq": int(seq), "from_rank": int(from_rank),
            "crc": migrate_crc(arrays)}


def migrate_fwd_wrap(orig_header: Dict[str, Any], *, plan: str,
                     from_rank: int,
                     origins: Sequence[Tuple[str, Any]]) -> Dict[str, Any]:
    """Wrap a forwarded write's header (the donor-decoded moved
    portion) for the recipient, carrying the originating (client, rid)
    pairs for the dedup window."""
    return {"op": MIGRATE_FWD, "plan": str(plan),
            "from_rank": int(from_rank), "orig": dict(orig_header),
            "origins": [[str(c), r] for c, r in origins]}


def migrate_fwd_unwrap(header: Dict[str, Any]) -> Tuple[
        Dict[str, Any], List[Tuple[str, Any]]]:
    """``(orig_header, origins)`` off a forwarded-write frame."""
    orig = dict(header.get("orig") or {})
    origins = [(str(c), r) for c, r in (header.get("origins") or [])]
    return orig, origins
