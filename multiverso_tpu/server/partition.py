"""PartitionMap: which server process owns which slice of every table.

The reference framework's defining scale shape is a *fleet* of server
processes, each owning a partition of every table, with workers
scattering requests by ownership (`src/server.cpp`: rank r serves the
rows `ProcessGet`/`ProcessAdd` hash to it). This module is that
ownership function for the wire stack: a versioned
:class:`PartitionMap` shared by the launcher, every
:class:`~multiverso_tpu.server.table_server.TableServer` in the fleet,
and the client-side router (:mod:`multiverso_tpu.client.router`).

Ownership is **contiguous blocks**, the same invariant
``tables/hashing.shard_lane_slices`` exploits on-device:

- a dense table of ``size`` elements splits into N contiguous element
  ranges — rank r owns ``[r*size//n, (r+1)*size//n)`` — so a scatter
  is a plain slice and a gather a plain concat, both zero-index-math;
- a KV key hashes (splitmix64, the table layer's own mix) into a
  fleet-wide **logical bucket space** of ``kv_buckets`` buckets
  (fixed at map creation and held FIXED across reshards, so keys
  never re-hash), and rank r owns the contiguous floor-division
  block ``[r*kv_buckets//n, (r+1)*kv_buckets//n)`` — the same split
  rule as the dense bounds, and bit-identical to the historical
  equal-block rule whenever ``kv_buckets % n == 0`` (true for every
  map the launcher ever wrote).

Contiguity is not an aesthetic: it is the substrate live resharding
(:func:`map_diff`) stands on — moving ownership v→v+1 is "reassign a
range, bump ``version``", the moved ranges are closed-form interval
intersections of the old and new bounds, and the version handshake
below is what makes a stale map refuse loudly instead of silently
mis-routing. Every server process checks the client's claimed
``(n, version, kv_buckets)`` at ``hello`` and refuses a mismatch
before any data op flows.

jax-free BY DESIGN (stdlib + numpy + the numpy-only hashing module):
the client router runs in bare worker processes, and the fleet-statusz
scraper runs on the statusz HTTP thread of a possibly-wedged process.
File-path loadable like ``server/wire.py``.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _dep(modname: str, *relpath: str):
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    if "multiverso_tpu" in sys.modules:
        import importlib
        return importlib.import_module(modname)
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, *relpath)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(modname, None)
        raise
    return mod


hashing = _dep("multiverso_tpu.tables.hashing", "tables", "hashing.py")

#: logical KV bucket space floor. Plenty of granularity for reshard
#: range moves without bloating the map; held fixed across v→v+1 so a
#: grow/shrink never re-hashes keys — only contiguous bucket ranges
#: change hands.
DEFAULT_KV_BUCKETS = 8192

#: hello/statusz wire fields of a partition claim; ``replicas`` joined
#: the geometry in the replication PR, so claims from older routers
#: (no ``replicas`` key) read as the pre-replication default of 1
_WIRE_FIELDS = ("n", "version", "kv_buckets", "replicas")
_WIRE_DEFAULTS = {"replicas": 1}


class PartitionMap:
    """The fleet-wide ownership function (see module docstring).

    Immutable; equality and the ``hello`` handshake compare the full
    ``(n, version, kv_buckets)`` triple — any change to the geometry
    must bump ``version`` (item 3's reshard contract)."""

    __slots__ = ("n", "version", "kv_buckets", "replicas")

    def __init__(self, n: int, *, version: int = 1,
                 kv_buckets: Optional[int] = None,
                 replicas: int = 1) -> None:
        n = int(n)
        if n < 1:
            raise ValueError(f"partition map needs n >= 1, got {n}")
        replicas = int(replicas)
        if replicas < 1:
            raise ValueError(f"partition map needs replicas >= 1, "
                             f"got {replicas}")
        base = int(kv_buckets) if kv_buckets else DEFAULT_KV_BUCKETS
        if base < n:
            base = n
        self.n = n
        self.version = int(version)
        self.replicas = replicas
        # NOT rounded to a multiple of n: ownership is floor-division
        # bounds (kv_bounds), so any kv_buckets >= n splits cleanly —
        # the invariant that lets a reshard keep the bucket space
        # fixed while n changes (keys never re-hash)
        self.kv_buckets = base

    # -- dense ownership ---------------------------------------------------

    def dense_bounds(self, size: int) -> List[int]:
        """N+1 offsets: rank r owns elements [bounds[r], bounds[r+1])
        of a dense table with ``size`` elements. Balanced to within one
        element, covering, disjoint."""
        size = int(size)
        if size < self.n:
            raise ValueError(
                f"dense table of {size} elements cannot split across "
                f"{self.n} servers (every rank must own >= 1 element)")
        return [r * size // self.n for r in range(self.n + 1)]

    def dense_range(self, size: int, rank: int) -> Tuple[int, int]:
        b = self.dense_bounds(size)
        return b[rank], b[rank + 1]

    # -- KV ownership ------------------------------------------------------

    @property
    def buckets_per_rank(self) -> int:
        """Floor of the per-rank bucket share. With floor-division
        bounds ranks may own this or this+1 buckets; kept as the
        capacity-sizing heuristic and for the historical name."""
        return self.kv_buckets // self.n

    def kv_bounds(self) -> List[int]:
        """N+1 offsets into the logical bucket space: rank r owns
        buckets [bounds[r], bounds[r+1]). Same floor-division rule as
        :meth:`dense_bounds` — balanced to within one bucket, covering,
        disjoint, and bit-identical to the historical equal-block rule
        whenever ``kv_buckets % n == 0``."""
        return [r * self.kv_buckets // self.n for r in range(self.n + 1)]

    def kv_bucket(self, keys: np.ndarray) -> np.ndarray:
        """Logical fleet bucket per key (splitmix64 mod kv_buckets) —
        the one hash every router and server must agree on."""
        keys = np.asarray(keys, np.uint64)
        return (hashing._hash_u64(keys)
                % np.uint64(self.kv_buckets)).astype(np.int64)

    def kv_owner(self, keys: np.ndarray) -> np.ndarray:
        """Owning rank per key: searchsorted over the contiguous
        bucket bounds (identical to ``bucket // buckets_per_rank``
        when the space divides evenly)."""
        bounds = np.asarray(self.kv_bounds()[1:], np.int64)
        return np.searchsorted(bounds, self.kv_bucket(keys),
                               side="right").astype(np.int64)

    def bucket_range(self, rank: int) -> Tuple[int, int]:
        b = self.kv_bounds()
        return b[rank], b[rank + 1]

    # -- wire form ---------------------------------------------------------

    def to_wire(self) -> Dict[str, int]:
        return {"n": self.n, "version": self.version,
                "kv_buckets": self.kv_buckets,
                "replicas": self.replicas}

    @classmethod
    def from_wire(cls, doc: Dict[str, Any]) -> "PartitionMap":
        return cls(int(doc["n"]), version=int(doc.get("version", 1)),
                   kv_buckets=int(doc["kv_buckets"]),
                   replicas=int(doc.get("replicas", 1)))

    def mismatch(self, claim: Optional[Dict[str, Any]]) -> Optional[str]:
        """None when ``claim`` (a to_wire dict off the hello header)
        names this exact map, else the human-readable refusal."""
        if not isinstance(claim, dict):
            return f"partition claim is not a map: {claim!r}"
        theirs = tuple(claim.get(k, _WIRE_DEFAULTS.get(k))
                       for k in _WIRE_FIELDS)
        ours = tuple(getattr(self, k) for k in _WIRE_FIELDS)
        if theirs != ours:
            return ("partition map mismatch: server has "
                    f"{dict(zip(_WIRE_FIELDS, ours))}, client claims "
                    f"{dict(zip(_WIRE_FIELDS, theirs))}")
        return None

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, PartitionMap) \
            and other.to_wire() == self.to_wire()

    def __repr__(self) -> str:
        return (f"PartitionMap(n={self.n}, version={self.version}, "
                f"kv_buckets={self.kv_buckets})")


class PartitionMember:
    """One rank's view of the map: what THIS server process owns."""

    __slots__ = ("map", "rank")

    def __init__(self, pmap: PartitionMap, rank: int) -> None:
        rank = int(rank)
        if not 0 <= rank < pmap.n:
            raise ValueError(f"rank {rank} outside fleet of {pmap.n}")
        self.map = pmap
        self.rank = rank

    def dense_range(self, size: int) -> Tuple[int, int]:
        return self.map.dense_range(size, self.rank)

    def local_dense_size(self, size: int) -> int:
        lo, hi = self.dense_range(size)
        return hi - lo

    def bucket_range(self) -> Tuple[int, int]:
        return self.map.bucket_range(self.rank)

    def local_kv_capacity(self, capacity: int) -> int:
        """This rank's slot budget: the global capacity split by owned
        bucket share (ceil — a shard must never hold fewer slots than
        its share of keys; KVTable rounds its bucket count up anyway).
        Identical to ``ceil(capacity / n)`` when the bucket space
        divides evenly."""
        lo, hi = self.bucket_range()
        return max(-(-int(capacity) * (hi - lo) // self.map.kv_buckets),
                   1)

    def describe(self) -> Dict[str, Any]:
        lo, hi = self.bucket_range()
        return {"rank": self.rank, "buckets": [lo, hi],
                **self.map.to_wire()}

    def __repr__(self) -> str:
        return f"PartitionMember(rank={self.rank}, map={self.map!r})"


# -- reshard diff ----------------------------------------------------------
#
# What moves on a map change v→v+1 is computable in closed form: both
# dense ranges and KV bucket ranges are contiguous floor-division
# splits, so the moved set per (donor, recipient) pair is the interval
# intersection of the old and new bounds — segments whose old owner
# differs from their new owner. Migration cost is therefore
# proportional to MOVED bytes, never table bytes: growing N→N+1 moves
# ~1/(N+1) of each table, shrinking moves the evicted rank's share.


def _bound_moves(old_bounds: List[int],
                 new_bounds: List[int]) -> List[Tuple[int, int, int, int]]:
    """``(donor, recipient, lo, hi)`` segments where ownership changes
    between two bounds lists over the same total span. Closed form:
    split the span at every old/new boundary; each piece has exactly
    one old owner and one new owner."""
    if old_bounds[-1] != new_bounds[-1] or old_bounds[0] != new_bounds[0]:
        raise ValueError(
            "bounds cover different spans: "
            f"{old_bounds[0]}..{old_bounds[-1]} vs "
            f"{new_bounds[0]}..{new_bounds[-1]}")
    import bisect
    edges = sorted(set(old_bounds) | set(new_bounds))
    moves = []
    for lo, hi in zip(edges[:-1], edges[1:]):
        donor = bisect.bisect_right(old_bounds, lo) - 1
        rcpt = bisect.bisect_right(new_bounds, lo) - 1
        if donor != rcpt:
            moves.append((donor, rcpt, lo, hi))
    return moves


class MapDiff:
    """The exact moved ranges of a reshard ``old``→``new``.

    ``bucket_moves`` is the list of ``(donor, recipient, lo, hi)``
    logical-KV-bucket segments changing hands; :meth:`dense_moves`
    computes the element-range counterpart for a dense table of a
    given size. Both are disjoint, covering exactly the moved set."""

    __slots__ = ("old", "new", "bucket_moves")

    def __init__(self, old: PartitionMap, new: PartitionMap) -> None:
        if new.kv_buckets != old.kv_buckets:
            raise ValueError(
                "reshard must keep the logical bucket space fixed "
                f"(old kv_buckets={old.kv_buckets}, new "
                f"{new.kv_buckets}) — changing it re-hashes every key")
        if new.version <= old.version:
            raise ValueError(
                f"reshard must bump the map version (old "
                f"{old.version}, new {new.version})")
        self.old = old
        self.new = new
        self.bucket_moves = _bound_moves(old.kv_bounds(), new.kv_bounds())

    def dense_moves(self, size: int) -> List[Tuple[int, int, int, int]]:
        """``(donor, recipient, lo, hi)`` GLOBAL element ranges of a
        dense table of ``size`` elements that change hands."""
        return _bound_moves(self.old.dense_bounds(size),
                            self.new.dense_bounds(size))

    def moved_buckets(self) -> int:
        return sum(hi - lo for _, _, lo, hi in self.bucket_moves)

    def moved_dense(self, size: int) -> int:
        return sum(hi - lo for _, _, lo, hi in self.dense_moves(size))

    def donor_ranks(self) -> List[int]:
        """Ranks that ship at least one range. Size-free: evaluated on
        a synthetic large dense size (the floor-division rule makes
        the donor set scale-invariant above ~n² elements) plus the
        bucket moves."""
        big = max(self.old.n, self.new.n) << 20
        out = set(d for d, _, _, _ in self.dense_moves(big))
        out.update(d for d, _, _, _ in self.bucket_moves)
        return sorted(out)


def map_diff(old: PartitionMap, new: PartitionMap) -> MapDiff:
    """The exact moved element/bucket ranges of a reshard — see
    :class:`MapDiff`."""
    return MapDiff(old, new)


# -- fleet file ------------------------------------------------------------
#
# The launcher (``python -m multiverso_tpu.server --fleet N``) writes
# one JSON document after every member reports ready; members read it
# LAZILY (first /statusz?fleet=1 scrape) so startup has no ordering
# cycle. Shape:
#
#   {"kind": "mvtpu.fleet.v1",
#    "map": {n, version, kv_buckets, replicas},
#    "members": [{"rank", "name", "addresses": [...],
#                 "statusz_port": int|null, "pid": int,
#                 "replicas": [{"idx", "name", "addresses": [...],
#                               "statusz_port": int|null, "pid": int},
#                              ...]},
#                ...]}
#
# ``replicas`` lists rank r's FOLLOWER processes (``--replicas R``
# spawns R-1 of them per rank); a follower promotion rewrites the doc
# through :func:`promote_in_doc` — the promoted follower becomes the
# member row and the map version bumps, so routers that re-read the
# file route to the new primary while stale claims refuse at hello.

FLEET_FILE_KIND = "mvtpu.fleet.v1"


def write_fleet_file(path: str, pmap: PartitionMap,
                     members: List[Dict[str, Any]]) -> None:
    doc = {"kind": FLEET_FILE_KIND, "map": pmap.to_wire(),
           "members": members}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def read_fleet_file(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("kind") != FLEET_FILE_KIND:
        return None
    return doc


def promote_in_doc(doc: Dict[str, Any], rank: int,
                   idx: int) -> Dict[str, Any]:
    """A fleet doc after follower ``idx`` of ``rank`` is promoted to
    primary: the follower's row replaces the member row, it leaves the
    replica list, and the map version bumps v→v+1 (stale routers now
    refuse at hello and refresh). Pure function — the caller owns the
    atomic rewrite through :func:`write_fleet_file`."""
    out = json.loads(json.dumps(doc))
    m = out.setdefault("map", {})
    m["version"] = int(m.get("version", 1)) + 1
    for member in out.get("members", []):
        if member.get("rank") != rank:
            continue
        reps = member.get("replicas") or []
        rep = next((r for r in reps if r.get("idx") == idx), None)
        if rep is not None:
            member["name"] = rep.get("name", member.get("name"))
            member["addresses"] = rep.get("addresses",
                                          member.get("addresses"))
            member["statusz_port"] = rep.get("statusz_port")
            member["pid"] = rep.get("pid")
            member["promoted_from"] = idx
        member["replicas"] = [r for r in reps if r.get("idx") != idx]
    return out


# -- fleet-aggregated introspection ----------------------------------------

def member_summary(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-partition digest of one member's /statusz document: the
    owned row/bucket ranges, queue depth, and fuse/admission counters
    — the fields an operator triages a lopsided fleet with."""
    out = []
    transport = doc.get("transport") or {}
    for row in transport.get("servers") or []:
        part = row.get("partition")
        if not part:
            continue
        adm = row.get("admission") or {}
        queue = adm.get("queue") or {}
        out.append({
            "server": row.get("name"),
            "address": row.get("address"),
            "rank": part.get("rank"),
            "map": {k: part.get(k) for k in _WIRE_FIELDS},
            "tables": part.get("tables"),
            "ops": row.get("ops"),
            "queued": row.get("queued"),
            "queue_bound": queue.get("bound"),
            "fused": row.get("fused"),
            "admission": {"shed": adm.get("shed"),
                          "expired": adm.get("expired"),
                          "degraded": adm.get("degraded")},
        })
    return out


def fleet_status(fleet_file: str, *, self_rank: Optional[int] = None,
                 self_doc: Optional[Dict[str, Any]] = None,
                 timeout: float = 2.0) -> Dict[str, Any]:
    """Aggregate the whole fleet's partition state by scraping each
    member's statusz port (``/statusz?fleet=1`` serves this). A dead
    or portless peer degrades to an ``error`` entry — introspecting a
    half-up fleet is exactly when this matters."""
    import urllib.request
    doc = read_fleet_file(fleet_file)
    if doc is None:
        return {"kind": "mvtpu.statusz.fleet.v1", "error":
                f"fleet file {fleet_file!r} missing or malformed",
                "partitions": []}
    partitions: List[Dict[str, Any]] = []
    for member in doc.get("members", []):
        rank = member.get("rank")
        entry: Dict[str, Any] = {"rank": rank,
                                 "name": member.get("name"),
                                 "pid": member.get("pid")}
        if self_rank is not None and rank == self_rank \
                and self_doc is not None:
            entry["partitions"] = member_summary(self_doc)
            partitions.append(entry)
            continue
        port = member.get("statusz_port")
        if not port:
            entry["error"] = "member has no statusz port"
            partitions.append(entry)
            continue
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/statusz",
                    timeout=timeout) as r:
                peer = json.loads(r.read())
            entry["partitions"] = member_summary(peer)
        except Exception as exc:    # noqa: BLE001 — a dead peer is data
            entry["error"] = f"{type(exc).__name__}: {exc}"
        partitions.append(entry)
    return {"kind": "mvtpu.statusz.fleet.v1", "map": doc.get("map"),
            "fleet_file": fleet_file, "partitions": partitions}
