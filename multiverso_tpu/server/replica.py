"""Snapshot read replicas: staleness-bounded reads off the dispatch
thread.

On the wire server every table op funnels into ONE dispatch thread (the
single-dispatch-thread contract), so under a write-heavy load every
``get`` queues behind every ``add`` — reads pay for writes. A
:class:`TableReplica` breaks that coupling for clients that can tolerate
bounded staleness: a ``get``/``kv_get`` frame carrying a ``staleness``
header (max generations behind) is answered directly on the
connection's READER thread from a host-side snapshot, never entering
the dispatch queue at all.

The two halves respect the threading contract strictly:

- **dispatch half** (``_on_table_update``, via the table's
  ``_attach_view`` hook — notifications run on the add's thread, which
  on a server IS the dispatch thread): dispatches an async device copy
  (dense: ``get_jax``; KV: ``snapshot_kv_async``) and hands the futures
  to the worker. One snapshot in flight at a time — under an add storm
  the replica refreshes at the rate D2H can drain, not per add.
- **publisher thread** (one daemon per replica): blocks on the device
  futures (the D2H the dispatch thread must never wait on), builds the
  servable form, publishes ``(generation, payload)`` under the lock.
  For KV that form is (sorted live uint64 keys, row-matched values):
  reader threads then serve lookups with ``np.searchsorted`` — no jax
  anywhere near a reader thread.

A replica starts DORMANT (zero overhead on the write path) and is
armed by the first staleness-tolerant read, which itself is served
fresh through the dispatch queue. Freshness check at serve time is two
plain int reads — ``table.generation - snapshot_generation <= bound``;
a miss (no snapshot yet, bound exceeded, in-flight refresh) falls back
to the dispatch queue, where the miss handler kicks another refresh.
Tiered KV tables are not replicated: their device arrays hold only the
resident tier, so a device snapshot would serve wrong (tier-partial)
reads.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.tables.hashing import _join_keys
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.utils import log


class TableReplica:
    """One table's read replica (see module docstring)."""

    def __init__(self, table: Any, kind: str, *,
                 server: str = "tables", stream: Any = None,
                 tid: Optional[int] = None) -> None:
        if kind not in ("array", "kv"):
            raise ValueError(f"no replica for table kind {kind!r}")
        self.table = table
        self.kind = kind
        # on a FOLLOWER the honest staleness reference is not the
        # local generation but the newest primary generation the repl
        # stream has ANNOUNCED at intake (frames noted but not yet
        # applied are real lag the local generation can't see):
        # ``stream`` is the server's FollowerState, or None on a
        # primary. ``tid`` is the WIRE table id the stream keys on
        # (the registry id on ``table`` is a different id space).
        self.stream = stream
        self.tid = int(tid) if tid is not None else None
        self._lock = threading.Lock()
        self._gen = -1              # generation of the published snapshot
        self._value: Any = None     # dense: ndarray; kv: (keys64, values)
        self._armed = False
        self._inflight = False
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        lbl = f"{table.table_id}:{table.name}"
        self._g_gen = telemetry.gauge("server.replica.generation",
                                      server=server, table=lbl)
        self._g_stale = telemetry.gauge("server.replica.staleness",
                                        server=server, table=lbl)
        self._c_hits = telemetry.counter("server.replica.hits",
                                         server=server)
        self._c_misses = telemetry.counter("server.replica.misses",
                                           server=server)
        self._c_degraded = telemetry.counter(
            "server.replica.degraded_hits", server=server)
        self._c_relaxed = telemetry.counter(
            "server.replica.relaxed_hits", server=server)
        # control-plane staleness slack: extra generations a snapshot
        # may lag past the CLIENT-requested bound and still be served
        # (a relaxed reply carries the real staleness). 0 = strict.
        self.slack = _knobs.initial("server.replica.slack")
        _knobs.bind("server.replica.slack", self, "slack",
                    label=f"{server}:{lbl}")

    # -- dispatch-thread half ----------------------------------------------

    def arm(self) -> None:
        """First staleness-tolerant read arms the replica (idempotent;
        dispatch thread only — ``_attach_view`` and the first snapshot
        dispatch both require it)."""
        if self._armed:
            return
        self._armed = True
        self._thread = threading.Thread(
            target=self._publisher, daemon=True,
            name=f"replica-{self.table.name}")
        self._thread.start()
        self.table._attach_view(self)
        self._on_table_update()

    def refresh(self) -> None:
        """Re-kick after a bound miss (dispatch thread): if the last
        notification's snapshot was dropped because one was already in
        flight, this closes the gap. No-op while armed + in flight."""
        self._on_table_update()

    def _on_table_update(self) -> None:
        # the table's view hook: runs on the thread that applied the
        # add == the server dispatch thread. Dispatch-only: the D2H
        # wait lives on the publisher thread.
        if not self._armed:
            return
        with self._lock:
            if self._inflight:
                return
            self._inflight = True
        gen = self.table.generation
        try:
            if self.kind == "kv":
                fut = self.table.snapshot_kv_async()
            else:
                fut = self.table.get_jax()
        except Exception as exc:    # noqa: BLE001 — replica must not
            with self._lock:        # take the dispatch thread down
                self._inflight = False
            log.warn("replica %r: snapshot dispatch failed: %s",
                     self.table.name, exc)
            return
        self._q.put((gen, fut))

    # -- publisher thread --------------------------------------------------

    def _publisher(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            gen, fut = item
            try:
                if self.kind == "kv":
                    value = self._host_kv(fut)
                else:
                    value = np.ascontiguousarray(np.asarray(fut))
            except Exception as exc:    # noqa: BLE001
                log.warn("replica %r: snapshot publish failed: %s",
                         self.table.name, exc)
                value = None
            with self._lock:
                if value is not None and gen > self._gen:
                    self._gen = gen
                    self._value = value
                self._inflight = False
            if value is not None:
                self._g_gen.set(float(gen))

    @staticmethod
    def _host_kv(fut) -> Tuple[np.ndarray, np.ndarray]:
        keys_fut, vals_fut = fut
        host_keys = np.asarray(keys_fut)        # (B, S, 2) uint32
        host_vals = np.asarray(vals_fut)
        live = ~(host_keys == np.uint32(0xFFFFFFFF)).all(-1)
        k64 = _join_keys(host_keys[live])
        vals = host_vals[live]
        order = np.argsort(k64, kind="stable")
        return k64[order], np.ascontiguousarray(vals[order])

    # -- reader-thread half ------------------------------------------------

    def serve(self, header: Dict[str, Any], arrays: List[np.ndarray],
              relax: bool = False) -> Optional[tuple]:
        """Serve one staleness-tolerant read on a READER thread, or
        return ``None`` (miss — the frame takes the dispatch queue and
        its handler calls :meth:`arm`/:meth:`refresh`). Never touches
        jax.

        ``relax=True`` is degraded-mode routing (the admission layer is
        shedding writes): a snapshot PAST the requested bound is served
        anyway rather than queueing the read behind the very overload
        being shed — the reply carries the real ``staleness`` plus a
        ``degraded`` marker so the client can see the bound was
        relaxed. No snapshot at all is still a miss."""
        try:
            bound = max(int(header.get("staleness")), 0)
        except (TypeError, ValueError):
            return None
        with self._lock:
            gen, value = self._gen, self._value
        if value is None:
            self._c_misses.inc()
            return None
        lag = max(self.table.generation - gen, 0)   # plain int reads
        if self.stream is not None and self.tid is not None:
            # follower: lag vs the stream's noted primary generation
            # (>= local generation — frames noted at intake but not
            # yet applied are real lag the local generation can't see)
            lag = max(lag, self.stream.lag(self.tid, gen))
        degraded = False
        relaxed = False
        if lag > bound:
            slack = max(int(self.slack), 0)
            if relax:
                degraded = True
                self._c_degraded.inc()
            elif lag <= bound + slack:
                # within the control plane's staleness slack: serve
                # past the requested bound, marked, rather than
                # queueing the read behind the writes it lags
                relaxed = True
                self._c_relaxed.inc()
            else:
                self._c_misses.inc()
                return None
        self._c_hits.inc()
        self._g_stale.set(float(lag))
        head = {"ok": True, "gen": gen, "replica": True,
                "staleness": lag}
        if self.stream is not None:
            # follower-served replies carry the same markers the
            # dispatch-path follower serve annotates
            head["follower"] = True
            head["lag"] = lag
        # trace echo (the wire's TRACE_KEY, read raw — this module
        # never imports the codec): a replica-served reply names the
        # request it answered, like shed/expired replies do
        tr = header.get("trace")
        if isinstance(tr, dict) and tr.get("req") is not None:
            head["req"] = tr["req"]
        if degraded:
            head["degraded"] = True
        if relaxed:
            head["relaxed"] = True
        if self.kind == "array":
            return (head, [value])
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        skeys, svals = value
        n = len(keys)
        if len(skeys):
            idx = np.clip(np.searchsorted(skeys, keys), 0,
                          len(skeys) - 1)
            found = skeys[idx] == keys
        else:
            idx = np.zeros(n, np.intp)
            found = np.zeros(n, bool)
        vd = int(getattr(self.table, "value_dim", 0) or 0)
        out = np.full((n, vd) if vd else (n,),
                      self.table.default_value, dtype=self.table.dtype)
        if found.any():
            out[found] = svals[idx[found]]
        return (head, [out, found])

    # -- lifecycle / observability -----------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            gen = self._gen
            have = self._value is not None
        return {"table": self.table.name, "kind": self.kind,
                "armed": self._armed, "generation": gen,
                "lag": max(self.table.generation - gen, 0) if have
                else None}

    def stop(self) -> None:
        self._q.put(None)
