"""Server process: the table fleet behind a wire (PAPER.md §1).

The reference framework's defining shape is worker *processes* talking
to server *processes* over MPI/ZeroMQ. This package is that shape for
the TPU port: :class:`TableServer` owns the table fleet (single
dispatch thread + the existing table / tiered-storage / telemetry
layers + statusz) and speaks the length-prefixed, batched Get/Add
frame protocol in :mod:`multiverso_tpu.server.wire` over unix-domain
or TCP sockets; N worker processes drive it through
:mod:`multiverso_tpu.client.transport`.

Run one as its own process::

    python -m multiverso_tpu.server --address unix:/tmp/mvtpu.sock

``TableServer`` is imported lazily (PEP 562): :mod:`.wire` must stay
importable by jax-free worker processes, and pulling the table layer
in at package import would drag jax along.
"""

from multiverso_tpu.server import wire  # noqa: F401  (jax-free codec)

__all__ = ["TableServer", "wire"]


def __getattr__(name: str):
    if name == "TableServer":
        from multiverso_tpu.server.table_server import TableServer
        return TableServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
