"""TableServer: one process owning the table fleet behind a wire.

The reference framework's server role (`src/server.cpp`: ZeroMQ/MPI
recv loop → ProcessGet/ProcessAdd on the owned table shards) mapped
onto this port: a :class:`TableServer` listens on one wire address,
worker *processes* connect through
:mod:`multiverso_tpu.client.transport`, and every table op funnels into
ONE dispatch thread — the same single-dispatch-thread contract the rest
of the repo keeps for multi-device collectives (`benchmarks/serving.py`
has the in-process version of this exact loop).

Thread topology per server::

    accept thread ──► per-conn reader ──┐
                      per-conn reader ──┼──► dispatch queue ─► ONE
                      per-conn reader ──┘    dispatch thread (table ops)
                                              │ replies
                      per-conn writer ◄───────┘ (per-conn send queues)

Fault containment is the design center, not an afterthought:

- A connection dying (worker SIGKILL, chaos ``drop``/``torn``) kills
  its reader/writer pair and nothing else — the dispatch thread and
  every other connection keep going.
- A handler error (bad table id, shape mismatch) becomes an
  ``{ok: false, error: ...}`` reply; the dispatch thread never dies on
  a request.
- Mutating ops are **deduplicated** by ``(client id, request id)``: the
  client transport resends unacked adds after a reconnect
  (at-least-once delivery), and this table keeps replay from becoming
  double-apply (exactly-once effect) — the property the chaos-storm
  bit-identical test pins down.
"""

from __future__ import annotations

import collections
import queue
import socket
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import core
from multiverso_tpu.ft import chaos as _chaos
from multiverso_tpu.io import wiresock
from multiverso_tpu.server import wire
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log

#: AddOption fields a client may set over the wire (``step`` stays
#: server-owned: each table's option advances it per applied add)
_OPTION_FIELDS = ("learning_rate", "momentum", "rho", "lam")

#: replies cached per client for dedup replay; must exceed the client
#: transport's max pipelined-unacked window (64) with slack
_DEDUP_CACHE = 256

#: live servers in this process, for the /statusz transport section
_SERVERS: List["TableServer"] = []


def status_all() -> List[Dict[str, Any]]:
    """One status row per live server (statusz hook)."""
    return [s.status() for s in list(_SERVERS)]


class _Conn:
    """One client connection: socket + its writer queue + dedup state."""

    _ids = iter(range(1, 1 << 62))
    _ids_lock = threading.Lock()

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        with _Conn._ids_lock:
            self.conn_id = next(_Conn._ids)
        self.client_id: str = f"conn{self.conn_id}"
        self.sendq: "queue.Queue" = queue.Queue()
        self.alive = True

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class TableServer:
    """Serve the table fleet over one wire address.

    ``start()`` binds + spins the threads and returns the dialable
    address (resolving ``tcp:host:0``'s ephemeral port); ``stop()``
    drains everything. Usable in-process (tests run a TableServer on a
    thread next to the pytest client) or as its own process via
    ``python -m multiverso_tpu.server``.
    """

    def __init__(self, address: str, *, name: str = "tables") -> None:
        self.name = name
        self.address = address
        self._listener: Optional[socket.socket] = None
        self._conns: Dict[int, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._dispatchq: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._tables: Dict[int, Any] = {}
        self._by_name: Dict[str, int] = {}
        self._next_table = 0
        # (client_id) -> OrderedDict(rid -> reply) for mutation replay
        self._dedup: Dict[str, "collections.OrderedDict"] = {}
        self._g_conns = telemetry.gauge("wire.connections",
                                        server=self.name)
        self._ops = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> str:
        core.init()     # idempotent; tables need the mesh
        self._listener = wiresock.listen_socket(self.address)
        self.address = wiresock.bound_address(self._listener,
                                              self.address)
        self._spawn(self._accept_loop, "wire-accept")
        self._spawn(self._dispatch_loop, "wire-dispatch")
        _SERVERS.append(self)
        log.info("table server %r listening on %s", self.name,
                 self.address)
        return self.address

    def _spawn(self, fn, name: str, *args) -> threading.Thread:
        t = threading.Thread(target=fn, args=args,
                             name=f"{name}-{self.name}", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._listener is not None:
            # shutdown-then-close (wire._close_socket rationale): a
            # plain close does NOT wake a thread blocked in accept()
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            conn.sendq.put(None)
            conn.close()
        self._dispatchq.put(None)
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        if self in _SERVERS:
            _SERVERS.remove(self)
        log.info("table server %r stopped (%d ops served)", self.name,
                 self._ops)

    def serve_forever(self) -> None:
        """Block until :meth:`stop` (signal handlers call it)."""
        self._stop.wait()

    def status(self) -> Dict[str, Any]:
        with self._conns_lock:
            n_conns = len(self._conns)
        return {"name": self.name, "address": self.address,
                "connections": n_conns, "tables": len(self._tables),
                "ops": self._ops,
                "queued": self._dispatchq.qsize()}

    # -- accept / read / write threads -------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._stop.is_set():
                    return
                continue
            try:
                _chaos.chaos_point("wire.accept")
            except _chaos.ChaosError as exc:
                # injected accept fault: the worker's dial dies at the
                # handshake and its RetryPolicy redials — the server
                # just sheds the connection
                log.warn("wire.accept chaos: %s", exc)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if sock.family == socket.AF_INET:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            conn = _Conn(sock)
            with self._conns_lock:
                self._conns[conn.conn_id] = conn
                self._g_conns.set(len(self._conns))
            self._spawn(self._read_loop, f"wire-read{conn.conn_id}",
                        conn)
            self._spawn(self._write_loop, f"wire-write{conn.conn_id}",
                        conn)

    def _drop_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            live = self._conns.pop(conn.conn_id, None)
            self._g_conns.set(len(self._conns))
        if live is not None:
            conn.sendq.put(None)
            conn.close()

    def _read_loop(self, conn: _Conn) -> None:
        """Reader: frames off this connection into the dispatch queue.
        ANY wire failure here is this connection's problem only."""
        while conn.alive and not self._stop.is_set():
            try:
                header, arrays, _ = wire.recv_frame(conn.sock,
                                                    role="server")
            except (ConnectionError, wire.WireProtocolError, OSError,
                    ValueError) as exc:
                if conn.alive and not self._stop.is_set():
                    log.debug("conn %d reader closing: %s",
                              conn.conn_id, exc)
                break
            self._dispatchq.put((conn, header, arrays))
        self._drop_conn(conn)

    def _write_loop(self, conn: _Conn) -> None:
        while True:
            item = conn.sendq.get()
            if item is None:
                return
            header, arrays = item
            try:
                wire.send_frame(conn.sock, header, arrays,
                                role="server")
            except (ConnectionError, OSError) as exc:
                if conn.alive and not self._stop.is_set():
                    log.debug("conn %d writer closing: %s",
                              conn.conn_id, exc)
                self._drop_conn(conn)
                return

    # -- the single dispatch thread ----------------------------------------

    def _dispatch_loop(self) -> None:
        h_dispatch = telemetry.histogram("wire.dispatch.seconds",
                                         telemetry.LATENCY_BUCKETS,
                                         server=self.name)
        import time as _time
        while True:
            item = self._dispatchq.get()
            if item is None:
                return
            conn, header, arrays = item
            op = str(header.get("op", "?"))
            rid = header.get("rid")
            t0 = _time.monotonic()
            try:
                reply = self._execute(conn, op, header, arrays)
            except Exception as exc:      # noqa: BLE001 — reply, don't die
                telemetry.counter("wire.server.errors", op=op).inc()
                log.warn("wire op %s failed: %s: %s", op,
                            type(exc).__name__, exc)
                reply = ({"ok": False, "rid": rid,
                          "error": f"{type(exc).__name__}: {exc}"}, [])
            h_dispatch.observe(_time.monotonic() - t0)
            self._ops += 1
            telemetry.counter("wire.requests", op=op).inc()
            if reply is not None and conn.alive:
                rheader, rarrays = reply
                rheader.setdefault("rid", rid)
                conn.sendq.put((rheader, rarrays))

    def _execute(self, conn: _Conn, op: str, header: Dict[str, Any],
                 arrays: List[np.ndarray]
                 ) -> Optional[Tuple[Dict[str, Any], list]]:
        if op == "hello":
            requested = str(header.get("client") or conn.client_id)
            conn.client_id = requested
            self._dedup.setdefault(requested,
                                   collections.OrderedDict())
            return ({"ok": True, "client_id": requested,
                     "server": self.name,
                     "quant": wire.quant_mode_from_env()}, [])
        if op == "ping":
            return ({"ok": True}, [])
        if op == "stats":
            return ({"ok": True, "status": self.status()}, [])
        if op == "shutdown":
            # reply first (queued), then stop — the writer drains the
            # queue before the socket closes under it
            conn.sendq.put(({"ok": True, "rid": header.get("rid")}, []))
            threading.Thread(target=self.stop, daemon=True).start()
            return None

        # mutating ops replay from the dedup cache: a resend after a
        # reconnect must not re-apply
        mutating = op in ("create", "add", "kv_add")
        if mutating:
            cached = self._dedup_get(conn.client_id, header.get("rid"))
            if cached is not None:
                telemetry.counter("wire.dedup.replays", op=op).inc()
                return cached

        if op == "create":
            reply = self._op_create(header)
        elif op == "get":
            reply = self._op_get(header)
        elif op == "kv_get":
            reply = self._op_kv_get(header, arrays)
        elif op == "add":
            reply = self._op_add(header, arrays)
        elif op == "kv_add":
            reply = self._op_kv_add(header, arrays)
        else:
            raise ValueError(f"unknown wire op {op!r}")
        if mutating:
            self._dedup_put(conn.client_id, header.get("rid"), reply)
        return reply

    # -- dedup cache -------------------------------------------------------

    def _dedup_get(self, client: str, rid) -> Optional[tuple]:
        if rid is None:
            return None
        cache = self._dedup.setdefault(client,
                                       collections.OrderedDict())
        entry = cache.get(int(rid))
        if entry is not None:
            header, arrays = entry
            return (dict(header), list(arrays))
        return None

    def _dedup_put(self, client: str, rid, reply: tuple) -> None:
        if rid is None:
            return
        cache = self._dedup.setdefault(client,
                                       collections.OrderedDict())
        cache[int(rid)] = reply
        while len(cache) > _DEDUP_CACHE:
            cache.popitem(last=False)

    # -- table ops ---------------------------------------------------------

    def _table(self, header: Dict[str, Any]):
        tid = int(header.get("table", -1))
        table = self._tables.get(tid)
        if table is None:
            raise KeyError(f"no table {tid} on this server")
        return table

    def _op_create(self, header: Dict[str, Any]) -> tuple:
        name = str(header["name"])
        kind = str(header.get("kind", "array"))
        spec = dict(header.get("spec") or {})
        if name in self._by_name:
            # idempotent by name: N workers all issue the same creates
            # at startup; first one builds, the rest attach
            tid = self._by_name[name]
            table = self._tables[tid]
        else:
            table = self._build_table(name, kind, spec)
            tid = self._next_table
            self._next_table += 1
            self._tables[tid] = table
            self._by_name[name] = tid
            log.info("server %r created table %d %r kind=%s", self.name,
                     tid, name, kind)
        meta = {"ok": True, "table": tid, "name": name, "kind": kind,
                "dtype": np.dtype(table.dtype).str}
        value_dim = getattr(table, "value_dim", None)
        if value_dim is not None:
            meta["value_dim"] = int(value_dim)
        size = getattr(table, "size", None)
        if size is not None:
            meta["size"] = int(size)
        return (meta, [])

    def _build_table(self, name: str, kind: str, spec: Dict[str, Any]):
        common = {"name": name}
        for key in ("dtype", "updater"):
            if key in spec:
                common[key] = spec[key]
        if kind == "array":
            from multiverso_tpu.tables.array_table import ArrayTable
            return ArrayTable(int(spec["size"]),
                              init_value=spec.get("init_value", 0),
                              **common)
        if kind == "kv":
            from multiverso_tpu.tables.kv_table import KVTable
            return KVTable(int(spec["capacity"]),
                           int(spec.get("value_dim", 0)), **common)
        if kind == "tiered_kv":
            from multiverso_tpu.storage.tiered_kv import TieredKVTable
            return TieredKVTable(int(spec["capacity"]),
                                 int(spec.get("value_dim", 0)),
                                 **common)
        raise ValueError(f"unknown table kind {kind!r} "
                         "(array | kv | tiered_kv)")

    @staticmethod
    def _option(header: Dict[str, Any]) -> Optional[AddOption]:
        raw = header.get("option")
        if not raw:
            return None
        fields = {k: float(raw[k]) for k in _OPTION_FIELDS if k in raw}
        return AddOption(**fields)

    def _op_get(self, header: Dict[str, Any]) -> tuple:
        table = self._table(header)
        values = table.get()
        return ({"ok": True}, [np.ascontiguousarray(values)])

    def _op_kv_get(self, header: Dict[str, Any],
                   arrays: List[np.ndarray]) -> tuple:
        table = self._table(header)
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        values, found = table.get(keys)
        return ({"ok": True}, [np.ascontiguousarray(values),
                               np.ascontiguousarray(found)])

    def _op_add(self, header: Dict[str, Any],
                arrays: List[np.ndarray]) -> tuple:
        table = self._table(header)
        # dequant-before-apply: the table layer only ever sees floats
        delta = wire.decode_delta(header.get("quant"), arrays)
        handle = table.add(delta, self._option(header),
                           sync=bool(header.get("sync")))
        return ({"ok": True, "gen": handle.generation}, [])

    def _op_kv_add(self, header: Dict[str, Any],
                   arrays: List[np.ndarray]) -> tuple:
        table = self._table(header)
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        delta = wire.decode_delta(header.get("quant"), arrays[1:])
        handle = table.add(keys, delta, self._option(header),
                           sync=bool(header.get("sync")))
        return ({"ok": True, "gen": handle.generation}, [])
