"""TableServer: one process owning the table fleet behind a wire.

The reference framework's server role (`src/server.cpp`: ZeroMQ/MPI
recv loop → ProcessGet/ProcessAdd on the owned table shards) mapped
onto this port: a :class:`TableServer` listens on one or more wire
addresses, worker *processes* connect through
:mod:`multiverso_tpu.client.transport`, and every table op funnels into
ONE dispatch thread — the same single-dispatch-thread contract the rest
of the repo keeps for multi-device collectives (`benchmarks/serving.py`
has the in-process version of this exact loop).

Thread topology per server::

    accept thread ──► per-conn reader ──┬─(staleness get: replica hit,
                      per-conn reader ──┤  answered right here)
                      per-conn reader ──┼─► ADMISSION ─► fair dispatch
                                        │   (classify,     queue ─► ONE
                                        │    bucket,        dispatch
                                        │    bound —        thread (table
                                        │    shed replies   ops, FUSED up
                                        │    answered       to MVTPU_
                                        │    right here)    SERVER_FUSE)
                      per-conn writer ◄─┴──── replies (per-conn queues)

Overload is a first-class state, not a failure (see
:mod:`multiverso_tpu.server.admission`): reader threads run every data
frame through the admission controller — per-client token buckets and
a bounded queue shed excess load with a structured
``{ok:false, shed:true, retry_after_ms}`` reply the client transport
honors (sleep, resend identical bytes, dedup keeps it exactly-once) —
and the dispatch queue itself is weighted-fair across QoS classes
(``MVTPU_SERVER_QOS``), so one flooding client saturates its own lane
while well-behaved classes keep their share of the dispatch thread.
Client-stamped ``deadline`` headers are checked at dequeue: an expired
request is answered ``{ok:false, expired:true}`` instead of executed.
While mutations are being shed the server runs *degraded*:
bounded-staleness reads divert to the replica path even past their
bound (stale beats shed).

The hot path is batched like the reference's server loop processes its
message queue: each dispatch cycle drains up to ``MVTPU_SERVER_FUSE``
queued frames (default 1 = off), groups compatible ops by (table, op
kind, AddOption, sync), concatenates the payloads host-side with
cross-request duplicate pre-summing (the CoalescingBuffer grouping
rules; only for linear updaters — stateful-updater groups run per-frame
inside the cycle so fusion never changes their math), executes ONE
``apply``/``lookup`` per group, and fans per-request replies back — K
workers' small adds become one device dispatch. Reads that carry a
``staleness`` bound never enter the queue at all: they are served from
per-table snapshot replicas on the reader threads
(:mod:`multiverso_tpu.server.replica`).

Fault containment is the design center, not an afterthought:

- A connection dying (worker SIGKILL, chaos ``drop``/``torn``) kills
  its reader/writer pair and nothing else — the dispatch thread and
  every other connection keep going. This holds on the shm transport
  too: the doorbell socket's EOF is the death signal.
- A handler error (bad table id, shape mismatch) becomes an
  ``{ok: false, error: ...}`` reply; the dispatch thread never dies on
  a request. A fault mid-fusion-cycle (chaos ``server.fuse``) falls
  back to per-frame execution, so only genuinely-failing requests fail.
- Mutating ops are **deduplicated** by ``(client id, request id)``: the
  client transport resends unacked adds after a reconnect
  (at-least-once delivery), and this table keeps replay from becoming
  double-apply (exactly-once effect) — the property the chaos-storm
  bit-identical test pins down. Both dedup layers are bounded LRUs
  (``MVTPU_WIRE_DEDUP`` replies per client, floor ``96`` so the window
  always exceeds the client's 64-deep pipeline;
  ``MVTPU_WIRE_DEDUP_CLIENTS`` client entries) so a long-lived server
  cannot grow without limit.
"""

from __future__ import annotations

import collections
import contextlib
import heapq
import os
import queue
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu import core
from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.ft import chaos as _chaos
from multiverso_tpu.io import wiresock
from multiverso_tpu.server import admission as _admission_mod
from multiverso_tpu.server import partition as _partition_mod
from multiverso_tpu.server import replication as _replication
from multiverso_tpu.server import wire
from multiverso_tpu.server.replica import TableReplica
from multiverso_tpu.telemetry import attribution as _attribution
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as _trace
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log

#: AddOption fields a client may set over the wire (``step`` stays
#: server-owned: each table's option advances it per applied add)
_OPTION_FIELDS = ("learning_rate", "momentum", "rho", "lam")

FUSE_ENV = "MVTPU_SERVER_FUSE"
DEDUP_ENV = "MVTPU_WIRE_DEDUP"
DEDUP_CLIENTS_ENV = "MVTPU_WIRE_DEDUP_CLIENTS"
EXEMPLARS_ENV = "MVTPU_SERVER_EXEMPLARS"

#: default size of the slow-request exemplar ring: the top-N slowest
#: fully-settled requests (queue + execute), kept per server so a p999
#: violation names the actual requests and stages behind it
_EXEMPLARS = 8

#: default replies cached per client for dedup replay
_DEDUP_CACHE = 256
#: hard floor for ``MVTPU_WIRE_DEDUP``: the replay window must exceed
#: the client transport's max pipelined-unacked window (64) with slack,
#: or a plain reconnect resend would fall outside it
_DEDUP_FLOOR = 96
#: default bound on distinct clients carrying a dedup cache
_DEDUP_CLIENTS = 1024

#: ops the dispatch thread may fuse across requests
_FUSABLE = ("add", "kv_add", "get", "kv_get")

#: updaters whose apply is linear in the delta: pre-summing K requests
#: into one apply is exact for them (the CoalescingBuffer dense rule).
#: Stateful updaters (adagrad/adam/momentum/ftrl) are nonlinear — their
#: groups execute per-frame inside the cycle instead, so fusion never
#: changes their math
_PRESUM_UPDATERS = ("default", "sgd")

#: frames-per-cycle histogram bounds (server.fuse.batch)
_FUSE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

#: synthetic frames one ``server.flood`` chaos firing injects ahead of
#: the real frame (each is a ``noop`` from client ``chaos-flood``, so a
#: QoS class can target and shed them like any real flooder)
_FLOOD_BURST = 32
_FLOOD_CLIENT = "chaos-flood"

#: live-reshard chunking: elements per dense ``migrate_chunk`` (1 MiB
#: at fp32) and key rows per KV chunk — sized so the
#: ``server.migrate.rate`` knob's unit (chunks/s) maps to a
#: predictable wire rate
_MIG_DENSE_CHUNK = 1 << 18
_MIG_KV_CHUNK = 4096

#: sentinel for :meth:`TableServer._build_table`'s member override
_DEFAULT_MEMBER = object()


class _FloodConn:
    """Stand-in connection for chaos-injected synthetic frames: never
    alive, so replies (and shed replies) to the phantom are skipped."""

    conn_id = 0
    client_id = _FLOOD_CLIENT
    alive = False

#: live servers in this process, for the /statusz transport section
_SERVERS: List["TableServer"] = []


def status_all() -> List[Dict[str, Any]]:
    """One status row per live server (statusz hook)."""
    return [s.status() for s in list(_SERVERS)]


def fleet_info() -> Optional[Tuple[str, int]]:
    """(fleet_file, rank) of the first live fleet-member server in this
    process — the ``/statusz?fleet=1`` aggregator's anchor. None when
    no server here belongs to a fleet."""
    for s in list(_SERVERS):
        if s._fleet_file and s._partition is not None:
            return s._fleet_file, s._partition.rank
    return None


class _Conn:
    """One client connection: its channel + writer queue + identity."""

    _ids = iter(range(1, 1 << 62))
    _ids_lock = threading.Lock()

    def __init__(self, sock: socket.socket, scheme: str,
                 listen_path: Optional[str]) -> None:
        self.sock = sock
        self.scheme = scheme
        self.listen_path = listen_path
        self.chan: Optional[Any] = None     # set by the conn thread's
        # accept_channel handshake, before the read/write loops run
        with _Conn._ids_lock:
            self.conn_id = next(_Conn._ids)
        self.client_id: str = f"conn{self.conn_id}"
        self.sendq: "queue.Queue" = queue.Queue()
        self.alive = True

    def close(self) -> None:
        self.alive = False
        chan = self.chan
        if chan is not None:
            try:
                chan.close()
            except OSError:
                pass
            return
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _Unit:
    """One executable unit of a fusion cycle: either a singleton
    (control op / unfusable) or a group of same-(table, op, option,
    sync) frames."""

    __slots__ = ("key", "items")

    def __init__(self, key: Optional[tuple], item: tuple) -> None:
        self.key = key
        self.items = [item]     # (batch_idx, conn, header, arrays)


class _Migration:
    """Live state of one v→v+1 reshard on this member (the elastic-
    fleet tentpole; frame contract in ``server/wire.py``).

    One re-entrant lock serializes the donor's streaming thread
    against the dispatch thread's apply+forward path. The exactly-once
    invariant it buys: every write either lands BEFORE its range's
    chunk is extracted (the chunk carries it) or is forwarded AFTER
    the chunk, on the same FIFO link — never both, never neither."""

    def __init__(self, plan: str, old_map, new_map,
                 members: Dict[int, str], rank: int,
                 ctx: Optional[Dict[str, Any]] = None) -> None:
        self.plan = str(plan)
        self.old = old_map          # None on a member born at v+1
        self.new = new_map
        self.members = dict(members)    # rank -> wire address (NEW fleet)
        self.rank = int(rank)
        self.ctx = ctx              # the begin frame's trace context
        self.lock = threading.RLock()
        # begin -> streaming|shipped -> committed, or failed/aborted
        self.state = "begin"
        self.error: Optional[str] = None
        self.donor = False
        self.staging: Dict[int, Any] = {}       # tid -> new-geometry shard
        self.dense_segs: Dict[int, list] = {}   # tid -> [(rcpt, lo, hi)]
        self.kv_segs: Dict[int, list] = {}      # tid -> [(rcpt, blo, bhi)]
        self.shipped: Dict[int, list] = {}      # tid -> [(lo, hi)] handed off
        self.links: Dict[int, Any] = {}         # recipient rank -> WireClient
        self.seq = 0
        self.chunks = 0
        self.chunks_in = 0
        self.forwards = 0
        self.forwards_in = 0
        self.moved_bytes = 0
        self.t0 = time.time()

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def mark_shipped(self, tid: int, lo: int, hi: int) -> None:
        self.shipped.setdefault(tid, []).append((int(lo), int(hi)))

    def shipped_overlaps(self, tid: int, lo: int,
                         hi: int) -> List[Tuple[int, int]]:
        out = []
        for a, b in self.shipped.get(tid, ()):
            x, y = max(a, lo), min(b, hi)
            if x < y:
                out.append((x, y))
        return out

    def status(self) -> Dict[str, Any]:
        return {"plan": self.plan, "state": self.state,
                "from": self.old.version if self.old is not None
                else None,
                "to": self.new.version, "donor": self.donor,
                "chunks": self.chunks, "chunks_in": self.chunks_in,
                "forwards": self.forwards,
                "forwards_in": self.forwards_in,
                "moved_bytes": self.moved_bytes,
                "elapsed_s": round(time.time() - self.t0, 3),
                "error": self.error}


class TableServer:
    """Serve the table fleet over one or more wire addresses.

    ``address`` may be a comma-separated list (e.g.
    ``"unix:/run/a.sock,tcp:127.0.0.1:0,shm:///run/b.sock"``) — one
    listener each, one shared dispatch thread. ``start()`` binds + spins
    the threads and returns the dialable address list (resolving
    ``tcp:host:0``'s ephemeral ports); ``stop()`` drains everything.
    ``fuse`` (default: ``MVTPU_SERVER_FUSE``, else 1 = off) caps how
    many queued frames one dispatch cycle may drain and fuse. Usable
    in-process (tests run a TableServer on a thread next to the pytest
    client) or as its own process via ``python -m multiverso_tpu.server``.
    """

    def __init__(self, address: str, *, name: str = "tables",
                 fuse: Optional[int] = None,
                 qos: Optional[str] = None,
                 queue_bound: Optional[int] = None,
                 partition: Optional[Any] = None,
                 fleet_file: Optional[str] = None,
                 follower: bool = False,
                 replica_idx: Optional[int] = None,
                 replicate_to: Optional[List[str]] = None) -> None:
        self.name = name
        # fleet membership: a server/partition.PartitionMember makes
        # this process rank r of an N-server fleet — every create
        # instantiates only the local shard, and hello refuses clients
        # claiming a different map (see _execute). None = the whole
        # table lives here (every pre-fleet deployment).
        self._partition = partition
        self._fleet_file = fleet_file
        self._table_parts: Dict[int, Dict[str, Any]] = {}
        self._addresses = [a.strip() for a in str(address).split(",")
                           if a.strip()]
        if not self._addresses:
            raise ValueError("TableServer needs at least one address")
        self.address = ",".join(self._addresses)
        self._listeners: List[socket.socket] = []
        self._conns: Dict[int, _Conn] = {}
        self._conns_lock = threading.Lock()
        # the dispatch queue IS the admission controller: per-class
        # weighted-fair lanes + token buckets + the MVTPU_SERVER_QUEUE
        # bound, with the plain-Queue surface the dispatch loop drains
        self._admission = _admission_mod.AdmissionController(
            qos=qos, queue_bound=queue_bound, server=name)
        self._dispatchq = self._admission
        self._flood_conn = _FloodConn()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._tables: Dict[int, Any] = {}
        self._by_name: Dict[str, int] = {}
        self._replicas: Dict[int, TableReplica] = {}
        self._next_table = 0
        self._fuse = max(int(fuse) if fuse is not None
                         else _knobs.initial("server.fuse"), 1)
        self._dedup_depth = max(_knobs.initial("server.dedup",
                                               _DEDUP_CACHE),
                                _DEDUP_FLOOR)
        self._dedup_clients = max(
            _knobs.initial("server.dedup_clients", _DEDUP_CLIENTS), 1)
        # the dispatch loop re-reads self._fuse every drain cycle, so
        # a controller write takes effect on the next batch
        _knobs.bind("server.fuse", self, "_fuse", label=self.name)
        # LRU of LRUs: client_id -> OrderedDict(rid -> reply)
        self._dedup: "collections.OrderedDict[str, collections.OrderedDict]" \
            = collections.OrderedDict()
        self._g_conns = telemetry.gauge("wire.connections",
                                        server=self.name)
        self._g_depth = telemetry.gauge("server.queue.depth",
                                        server=self.name)
        self._h_batch = telemetry.histogram("server.fuse.batch",
                                            _FUSE_BUCKETS,
                                            server=self.name)
        self._h_age = telemetry.histogram("server.queue.age",
                                          telemetry.LATENCY_BUCKETS,
                                          server=self.name)
        self._c_fuse_groups = telemetry.counter("server.fuse.groups",
                                                server=self.name)
        self._c_fuse_frames = telemetry.counter("server.fuse.frames",
                                                server=self.name)
        # slow-request exemplars: a min-heap of (total_s, seq, row)
        # keeps the top-N slowest settled requests with their per-stage
        # breakdown (surfaced via status() -> /statusz)
        self._exemplar_cap = max(
            _knobs.initial("server.exemplars", _EXEMPLARS), 1)
        self._exemplars: List[tuple] = []
        self._exemplar_seq = 0
        self._exemplar_lock = threading.Lock()
        self._ops = 0
        # usage attribution: who (client, table, op) and where (range
        # heat) — None when killed via MVTPU_TOPK_K=0
        self._attr = _attribution.plane()
        # -- cross-process shard replication (server/replication.py) --
        # follower=True makes this process a read-only replica of its
        # rank's primary: mutations arrive only as op="repl" stream
        # frames, client reads are staleness-gated against the stream,
        # and "promote" flips it to primary on failover. A PRIMARY in
        # a fleet with replicas>1 (or with an explicit replicate_to
        # override) owns a ReplicationTap that forwards every applied
        # mutation and drains follower acks before client acks.
        self._follower = bool(follower)
        self._replica_idx = replica_idx
        self._repl_slack = _knobs.initial("server.repl.slack")
        _knobs.bind("server.repl.slack", self, "_repl_slack",
                    label=self.name)
        # -- live resharding (elastic fleet) ---------------------------
        # one in-flight _Migration at most; _table_specs remembers each
        # create's (name, kind, spec) so migrate_begin can build the
        # new-geometry staging shard and manifest-create on recipients
        self._migration: Optional[_Migration] = None
        self._table_specs: Dict[int, Tuple[str, str, Dict[str, Any]]] = {}
        self._migrate_rate = _knobs.initial("server.migrate.rate")
        _knobs.bind("server.migrate.rate", self, "_migrate_rate",
                    label=self.name)
        self._c_mig_bytes = telemetry.counter("reshard.moved_bytes",
                                              server=self.name)
        self._c_mig_chunks = telemetry.counter("reshard.chunks",
                                               server=self.name)
        self._c_mig_fwds = telemetry.counter("reshard.forwards",
                                             server=self.name)
        self._c_mig_aborts = telemetry.counter("reshard.aborts",
                                               server=self.name)
        self._fstate = _replication.FollowerState(self.name) \
            if self._follower else None
        self._tap: Optional[_replication.ReplicationTap] = None
        if not self._follower and (replicate_to or
                                   (fleet_file is not None
                                    and partition is not None)):
            self._tap = _replication.ReplicationTap(
                self.name, member=partition, fleet_file=fleet_file,
                replicate_to=replicate_to)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> str:
        core.init()     # idempotent; tables need the mesh
        bound = []
        for addr in self._addresses:
            parsed = wiresock.parse_address(addr)
            listener = wiresock.listen_socket(addr)
            self._listeners.append(listener)
            bound.append(wiresock.bound_address(listener, addr))
            path = parsed[1] if parsed[0] in ("unix", "shm") else None
            self._spawn(self._accept_loop,
                        f"wire-accept{len(bound)}", listener,
                        parsed[0], path)
        self.address = ",".join(bound)
        self._spawn(self._dispatch_loop, "wire-dispatch")
        _SERVERS.append(self)
        log.info("table server %r listening on %s (fuse=%d)",
                 self.name, self.address, self._fuse)
        return self.address

    def _spawn(self, fn, name: str, *args) -> threading.Thread:
        t = threading.Thread(target=fn, args=args,
                             name=f"{name}-{self.name}", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        for listener in self._listeners:
            # shutdown-then-close (wire._close_socket rationale): a
            # plain close does NOT wake a thread blocked in accept()
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            conn.sendq.put(None)
            conn.close()
        for rep in self._replicas.values():
            rep.stop()
        if self._tap is not None:
            self._tap.close()
        mig = self._migration
        if mig is not None:
            for link in list(mig.links.values()):
                with contextlib.suppress(Exception):
                    link.abort()
                with contextlib.suppress(Exception):
                    link.close()
        self._dispatchq.put(None)
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        if self in _SERVERS:
            _SERVERS.remove(self)
        log.info("table server %r stopped (%d ops served)", self.name,
                 self._ops)

    def serve_forever(self) -> None:
        """Block until :meth:`stop` (signal handlers call it)."""
        self._stop.wait()

    def status(self) -> Dict[str, Any]:
        with self._conns_lock:
            n_conns = len(self._conns)
        part = None
        if self._partition is not None:
            part = self._partition.describe()
            part["tables"] = list(self._table_parts.values())
        repl = None
        if self._tap is not None:
            repl = self._tap.status()
        elif self._fstate is not None:
            repl = self._fstate.status()
        if repl is not None:
            repl["follower"] = self._follower
            repl["slack"] = int(self._repl_slack)
            if not self._follower:
                # a promoted ex-follower reports its NEW role (its
                # FollowerState survives as the apply history)
                repl["role"] = "primary"
        mig = self._migration
        return {"name": self.name, "address": self.address,
                "connections": n_conns, "tables": len(self._tables),
                "migration": mig.status() if mig is not None else None,
                "ops": self._ops, "fuse": self._fuse,
                "fused": {"groups": int(self._c_fuse_groups.value),
                          "frames": int(self._c_fuse_frames.value)},
                "queued": self._dispatchq.qsize(),
                "partition": part,
                "replication": repl,
                "admission": self._admission.status(),
                "replicas": [rep.status()
                             for rep in self._replicas.values()],
                "slow": self.slow_exemplars(),
                # top talkers + range heat ride the stats wire op, so
                # an operator probe sees attribution without an HTTP
                # port (the flood smoke's scorer path)
                "topk": (self._attr.topk_doc(n=8)
                         if self._attr is not None else None)}

    def slow_exemplars(self) -> List[Dict[str, Any]]:
        """The exemplar ring, slowest first: one row per settled
        request with its per-stage (queue/execute) breakdown."""
        with self._exemplar_lock:
            entries = sorted(self._exemplars, key=lambda e: -e[0])
        return [row for _total, _seq, row in entries]

    def _note_exemplar(self, total_s: float,
                       row: Dict[str, Any]) -> None:
        with self._exemplar_lock:
            self._exemplar_seq += 1
            entry = (total_s, self._exemplar_seq, row)
            if len(self._exemplars) < self._exemplar_cap:
                heapq.heappush(self._exemplars, entry)
            elif total_s > self._exemplars[0][0]:
                heapq.heapreplace(self._exemplars, entry)

    # -- accept / read / write threads -------------------------------------

    def _accept_loop(self, listener: socket.socket, scheme: str,
                     listen_path: Optional[str]) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = listener.accept()
            except OSError:
                if self._stop.is_set():
                    return
                continue
            try:
                _chaos.chaos_point("wire.accept")
            except _chaos.ChaosError as exc:
                # injected accept fault: the worker's dial dies at the
                # handshake and its RetryPolicy redials — the server
                # just sheds the connection
                log.warn("wire.accept chaos: %s", exc)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if sock.family == socket.AF_INET:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            conn = _Conn(sock, scheme, listen_path)
            with self._conns_lock:
                self._conns[conn.conn_id] = conn
                self._g_conns.set(len(self._conns))
            self._spawn(self._conn_main, f"wire-read{conn.conn_id}",
                        conn)

    def _conn_main(self, conn: _Conn) -> None:
        """Per-connection thread: channel handshake (shm listeners
        negotiate rings off the accept thread, so a stalled client
        cannot block other accepts), then the read loop."""
        try:
            conn.chan = wire.accept_channel(
                conn.sock, conn.scheme, listen_path=conn.listen_path,
                role="server")
        except (ConnectionError, wire.WireProtocolError, OSError,
                ValueError) as exc:
            if not self._stop.is_set():
                log.debug("conn %d handshake failed: %s", conn.conn_id,
                          exc)
            self._drop_conn(conn)
            return
        self._spawn(self._write_loop, f"wire-write{conn.conn_id}",
                    conn)
        self._read_loop(conn)

    def _drop_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            live = self._conns.pop(conn.conn_id, None)
            self._g_conns.set(len(self._conns))
        if live is not None:
            conn.sendq.put(None)
            conn.close()

    def _read_loop(self, conn: _Conn) -> None:
        """Reader: frames off this connection into the dispatch queue —
        except staleness-tolerant reads, answered HERE from the table's
        replica when fresh enough (never a jax call; see replica.py).
        ANY wire failure here is this connection's problem only."""
        while conn.alive and not self._stop.is_set():
            try:
                header, arrays, _ = conn.chan.recv()
            except (ConnectionError, wire.WireProtocolError, OSError,
                    ValueError) as exc:
                if conn.alive and not self._stop.is_set():
                    log.debug("conn %d reader closing: %s",
                              conn.conn_id, exc)
                break
            if self._fstate is not None \
                    and header.get("op") == "repl":
                # follower staleness reference advances at INTAKE: repl
                # frames ride the strict-FIFO control lane, so by the
                # time a read dispatches, every frame noted ahead of it
                # is already applied
                self._fstate.note(header)
            # a follower answers on the reader thread too: its
            # replicas carry the FollowerState stream, so the
            # snapshot's staleness is measured against the newest
            # primary generation the stream has announced at intake
            # (never the local one). Unbounded reads (staleness None)
            # still go to dispatch, where a follower refuses them
            # structurally.
            if header.get("staleness") is not None \
                    and header.get("op") in ("get", "kv_get") \
                    and self._relay_mode(header) is None:
                t_rep = time.time()
                try:
                    # degraded-mode routing: while writes are being
                    # shed, serve from the replica even past the
                    # requested bound — a stale read beats a shed one
                    reply = self._serve_replica(
                        header, arrays,
                        relax=self._admission.degraded())
                except Exception:   # noqa: BLE001 — containment: a
                    reply = None    # replica bug degrades to dispatch
                ctx = wire.trace_ctx(header)
                if ctx is not None and _trace.active():
                    # reader-thread replica span, parented under the
                    # originating client request (hit -> answered
                    # here; miss -> the dispatch spans follow)
                    with _trace.adopt_remote(ctx):
                        _trace.emit_span(
                            "server.replica.get", t_rep,
                            time.time() - t_rep, server=self.name,
                            op=str(header.get("op")),
                            hit=reply is not None)
                if reply is not None:
                    rheader, rarrays = reply
                    rheader.setdefault("rid", header.get("rid"))
                    conn.sendq.put((rheader, rarrays))
                    continue
            self._intake(conn, header, arrays)
        self._drop_conn(conn)

    def _intake(self, conn: _Conn, header: Dict[str, Any],
                arrays: List[np.ndarray]) -> None:
        """Admission front-end for one frame (reader thread): chaos
        flood injection, then classify → bucket → bound. Admitted
        frames enter the fair queue; shed frames are answered right
        here with the structured retry-after reply — the dispatch
        thread never sees them."""
        try:
            _chaos.chaos_point("server.flood")
        except _chaos.ChaosError as exc:
            log.warn("server.flood chaos: %d synthetic frames ahead "
                     "of conn %d: %s", _FLOOD_BURST, conn.conn_id, exc)
            for _ in range(_FLOOD_BURST):
                fh = {"op": "noop", "flood": True}
                self._admission.offer(
                    _FLOOD_CLIENT, fh,
                    (self._flood_conn, fh, [], time.monotonic()))
        shed = self._admission.offer(
            conn.client_id, header,
            (conn, header, arrays, time.monotonic()))
        if shed is not None:
            if self._attr is not None:
                self._attr.shed(conn.client_id,
                                self._table_name(header),
                                str(header.get("op", "?")))
            shed["rid"] = header.get("rid")
            # shed replies name the shedder and echo the trace id, so
            # the client's retry-wait span says which server/class
            # shed it
            shed.setdefault("server", self.name)
            ctx = wire.trace_ctx(header)
            if ctx is not None and ctx.get("req") is not None:
                shed.setdefault("req", ctx["req"])
            if conn.alive:
                conn.sendq.put((shed, []))

    def _serve_replica(self, header: Dict[str, Any],
                       arrays: List[np.ndarray],
                       relax: bool = False) -> Optional[tuple]:
        rep = self._replicas.get(int(header.get("table", -1)))
        if rep is None:
            return None
        return rep.serve(header, arrays, relax=relax)

    def _write_loop(self, conn: _Conn) -> None:
        while True:
            item = conn.sendq.get()
            if item is None:
                return
            header, arrays = item
            try:
                conn.chan.send(header, arrays)
            except (ConnectionError, OSError) as exc:
                if conn.alive and not self._stop.is_set():
                    log.debug("conn %d writer closing: %s",
                              conn.conn_id, exc)
                self._drop_conn(conn)
                return

    # -- the single dispatch thread ----------------------------------------

    def _dispatch_loop(self) -> None:
        h_dispatch = telemetry.histogram("wire.dispatch.seconds",
                                         telemetry.LATENCY_BUCKETS,
                                         server=self.name)
        while True:
            item = self._dispatchq.get()
            if item is None:
                return
            try:
                # latency here models a slow dispatch thread (the
                # overload the admission layer absorbs); error/drop
                # are contained — a chaos fault at dequeue must never
                # kill the one dispatch thread
                _chaos.chaos_point("server.dequeue")
            except _chaos.ChaosError as exc:
                log.warn("server.dequeue chaos contained: %s", exc)
            batch = [item]
            stop_after = False
            while len(batch) < self._fuse:
                try:
                    nxt = self._dispatchq.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop_after = True
                    break
                batch.append(nxt)
            self._g_depth.set(float(self._dispatchq.qsize()))
            self._h_batch.observe(float(len(batch)))
            now = time.monotonic()
            for _, _, _, enq_ts in batch:
                self._h_age.observe(max(now - enq_ts, 0.0))
            # client-stamped deadlines check at DEQUEUE: an expired
            # request is dead work — answer it, don't execute it
            batch = [it for it in batch if not self._drop_expired(it)]
            if len(batch) == 1:
                conn, header, arrays, enq_ts = batch[0]
                op = str(header.get("op", "?"))
                t0 = time.monotonic()
                reply = self._safe_execute(conn, op, header, arrays)
                # zero-loss invariant: follower acks drain BEFORE the
                # client's ack is queued, so an acked write is on
                # every live follower (no-op without a tap)
                if self._tap is not None:
                    self._tap.barrier()
                self._finish(conn, op, header, reply, t0,
                             h_dispatch, enq_ts,
                             n_bytes=sum(int(a.nbytes)
                                         for a in arrays))
            elif batch:
                self._run_fused_batch(batch, h_dispatch)
            if stop_after:
                return

    def _drop_expired(self, item: tuple) -> bool:
        """Drop one already-expired frame at dequeue: reply a
        structured expired error (never applied, never cached — a
        resend with a fresh deadline would be a NEW request to the
        dedup layer only if the client re-rids it; the transport does
        not resend expired requests at all)."""
        conn, header, _arrays, _ts = item
        if not wire.deadline_expired(header):
            return False
        self._admission.note_expired()
        if conn.alive:
            reply = {"ok": False, "expired": True,
                     "rid": header.get("rid"),
                     "server": self.name,
                     "error": "deadline exceeded before "
                              "dispatch (op "
                              f"{header.get('op')!r})"}
            # expired replies echo the trace id like shed replies do:
            # the client can pin the loss to this server's queue
            ctx = wire.trace_ctx(header)
            if ctx is not None and ctx.get("req") is not None:
                reply["req"] = ctx["req"]
            conn.sendq.put((reply, []))
        return True

    def _safe_execute(self, conn: _Conn, op: str,
                      header: Dict[str, Any], arrays: List[np.ndarray],
                      force_sync: bool = False) -> Optional[tuple]:
        try:
            return self._execute(conn, op, header, arrays,
                                 force_sync=force_sync)
        except Exception as exc:      # noqa: BLE001 — reply, don't die
            telemetry.counter("wire.server.errors", op=op).inc()
            log.warn("wire op %s failed: %s: %s", op,
                     type(exc).__name__, exc)
            return ({"ok": False, "rid": header.get("rid"),
                     "error": f"{type(exc).__name__}: {exc}"}, [])

    def _finish(self, conn: _Conn, op: str, header: Dict[str, Any],
                reply: Optional[tuple], t0: float, h_dispatch,
                enq_ts: Optional[float] = None,
                n_bytes: int = 0) -> None:
        now = time.monotonic()
        h_dispatch.observe(now - t0)
        self._ops += 1
        telemetry.counter("wire.requests", op=op).inc()
        rid = header.get("rid")
        rheader = rarrays = None
        if reply is not None:
            rheader, rarrays = reply
        exec_s = max(now - t0, 0.0)
        wait_s = max(t0 - enq_ts, 0.0) if enq_ts is not None else 0.0
        ctx = wire.trace_ctx(header)
        if ctx is not None and _trace.active():
            # server-side spans for this settled request, parent-linked
            # under the originating client request: the queue wait
            # (measured at dequeue, so emitted retroactively) and the
            # dispatch/execute stage (fused cycles span the group).
            # Sink-gated: with nowhere to write, the record assembly
            # is pure tax on the dispatch thread.
            fused = (rheader or {}).get("fused")
            with _trace.adopt_remote(ctx):
                t_wall = time.time()
                if enq_ts is not None:
                    _trace.emit_span("server.queue.wait",
                                     t_wall - exec_s - wait_s, wait_s,
                                     server=self.name, op=op)
                attrs = {"server": self.name, "op": op}
                if fused:
                    attrs["fused"] = int(fused)
                _trace.emit_span(f"server.dispatch.{op}",
                                 t_wall - exec_s, exec_s, **attrs)
        if self._attr is not None \
                and op not in _admission_mod.CONTROL_OPS:
            if rarrays:
                n_bytes += sum(int(a.nbytes) for a in rarrays)
            self._attr.record(conn.client_id, self._table_name(header),
                              op, n_bytes=n_bytes,
                              queue_ms=wait_s * 1e3)
        if op not in _admission_mod.CONTROL_OPS:
            row = {"rid": rid, "op": op, "client": conn.client_id,
                   "class": self._admission.class_name(conn.client_id,
                                                       header),
                   "ts": time.time(),
                   "total_ms": round((wait_s + exec_s) * 1e3, 3),
                   "stages": {"queue_ms": round(wait_s * 1e3, 3),
                              "execute_ms": round(exec_s * 1e3, 3)}}
            if ctx is not None and ctx.get("req") is not None:
                row["req"] = ctx["req"]
            if (rheader or {}).get("fused"):
                row["fused"] = int(rheader["fused"])
            if rheader is not None and not rheader.get("ok", True):
                row["error"] = str(rheader.get("error", ""))[:120]
            self._note_exemplar(wait_s + exec_s, row)
        if reply is not None and conn.alive:
            rheader.setdefault("rid", rid)
            conn.sendq.put((rheader, rarrays))

    # -- request fusion ----------------------------------------------------

    def _run_fused_batch(self, batch: List[tuple],
                         h_dispatch) -> None:
        """One fusion cycle: plan units in arrival order, execute each
        (groups get ONE table op), then fan replies back in arrival
        order — per-connection reply order is what the client's
        in-order ack matching relies on."""
        t0 = time.monotonic()
        replies: Dict[int, Optional[tuple]] = {}
        for unit in self._plan_units(batch):
            if unit.key is None or len(unit.items) == 1:
                for idx, conn, header, arrays in unit.items:
                    op = str(header.get("op", "?"))
                    replies[idx] = self._safe_execute(conn, op, header,
                                                      arrays)
            else:
                replies.update(self._execute_group(unit))
        # sync-before-ack (see _dispatch_loop): one barrier per fusion
        # cycle covers every forwarded frame in it
        if self._tap is not None:
            self._tap.barrier()
        for idx, (conn, header, arrays, enq_ts) in enumerate(batch):
            self._finish(conn, str(header.get("op", "?")),
                         header, replies.get(idx), t0,
                         h_dispatch, enq_ts,
                         n_bytes=sum(int(a.nbytes) for a in arrays))

    def _plan_units(self, batch: List[tuple]) -> List[_Unit]:
        """Group the cycle's frames. A frame may only join a group that
        is still OPEN for its table — any interleaved different op /
        option / sync on the same table seals the group — so per-table
        op order is preserved exactly (frames only ever execute
        *earlier* than they would have, never later than a subsequent
        same-table op). Control ops are singleton units in sequence."""
        units: List[_Unit] = []
        open_by_table: Dict[int, _Unit] = {}
        for idx, (conn, header, arrays, _ts) in enumerate(batch):
            op = str(header.get("op", "?"))
            item = (idx, conn, header, arrays)
            tid = header.get("table")
            # follower reads stay singleton units: each carries its
            # own staleness bound, checked (and annotated) per frame
            if op in _FUSABLE and tid is not None \
                    and not (self._follower
                             and op in ("get", "kv_get")) \
                    and self._relay_mode(header) is None:
                try:
                    tid = int(tid)
                    key = self._group_key(op, tid, header)
                except (TypeError, ValueError):
                    units.append(_Unit(None, item))
                    continue
                unit = open_by_table.get(tid)
                if unit is not None and unit.key == key:
                    unit.items.append(item)
                    continue
                unit = _Unit(key, item)
                open_by_table[tid] = unit
                units.append(unit)
            else:
                units.append(_Unit(None, item))
        return units

    @staticmethod
    def _group_key(op: str, tid: int, header: Dict[str, Any]) -> tuple:
        opt = header.get("option") or {}
        return (op, tid, bool(header.get("sync")),
                tuple(sorted((str(k), float(v))
                             for k, v in opt.items())))

    def _execute_group(self, unit: _Unit) -> Dict[int, tuple]:
        """Execute one fused group. Dedup replays answer from the
        cache first (a resend inside a fusion cycle must not
        re-apply); a fault mid-group falls back to per-frame execution
        so only genuinely-failing requests fail."""
        op = unit.key[0]
        mutating = op in ("add", "kv_add")
        out: Dict[int, tuple] = {}
        fresh: List[tuple] = []
        for item in unit.items:
            idx, conn, header, _arrays = item
            if mutating:
                cached = self._dedup_get(conn.client_id,
                                         header.get("rid"))
                if cached is not None:
                    telemetry.counter("wire.dedup.replays",
                                      op=op).inc()
                    out[idx] = cached
                    continue
            fresh.append(item)
        if not fresh:
            return out
        if len(fresh) == 1:
            idx, conn, header, arrays = fresh[0]
            out[idx] = self._safe_execute(conn, op, header, arrays)
            return out
        if mutating:
            try:
                upd = self._table(fresh[0][2]).updater.name
            except Exception:   # noqa: BLE001 — bad table id etc.:
                upd = None      # per-frame path replies the error
            if upd not in _PRESUM_UPDATERS:
                # Nonlinear updater state: a merged delta is NOT K
                # sequential applies. Run the group per-frame — same
                # cycle, zero semantic drift.
                telemetry.counter("server.fuse.stateful_bypass",
                                  op=op).inc()
                for idx, conn, header, arrays in fresh:
                    out[idx] = self._safe_execute(conn, op, header,
                                                  arrays)
                return out
        try:
            _chaos.chaos_point("server.fuse")
            fused = self._apply_group(op, fresh)
            self._c_fuse_groups.inc()
            self._c_fuse_frames.inc(len(fresh))
        except Exception as exc:    # noqa: BLE001 — containment
            telemetry.counter("server.fuse.fallbacks", op=op).inc()
            log.warn("fused %s x%d fell back to per-frame: %s: %s",
                     op, len(fresh), type(exc).__name__, exc)
            # kv_add fallback forces sync so every request gets its OWN
            # commit/overflow verdict (a fused overflow names no
            # culprit)
            for idx, conn, header, arrays in fresh:
                out[idx] = self._safe_execute(
                    conn, op, header, arrays,
                    force_sync=(op == "kv_add"))
            return out
        for idx, conn, header, _arrays in fresh:
            reply = fused[idx]
            if mutating:
                self._dedup_put(conn.client_id, header.get("rid"),
                                reply)
            out[idx] = reply
        return out

    def _apply_group(self, op: str,
                     items: List[tuple]) -> Dict[int, tuple]:
        """The fused table op for one group: K compatible frames, ONE
        device dispatch."""
        header0 = items[0][2]
        table = self._table(header0)
        option = self._option(header0)
        sync = bool(header0.get("sync"))
        k = len(items)
        if op == "add":
            # CoalescingBuffer dense rule: pre-sum the deltas in table
            # dtype, apply once
            total: Optional[np.ndarray] = None
            for _idx, _conn, header, arrays in items:
                delta = wire.decode_delta(header.get("quant"), arrays) \
                    .astype(table.dtype, copy=False)
                if total is None:
                    total = delta.astype(table.dtype, copy=True)
                elif delta.shape != total.shape:
                    raise ValueError(
                        f"fused add shape mismatch {delta.shape} vs "
                        f"{total.shape}")
                else:
                    total += delta
            self._heat_touch_dense(header0, table, weight=float(k))
            mig = self._mig_forwarding()
            if mig is not None:
                # donor mid-reshard: apply + forward under the
                # migration lock so the fused delta can never fall
                # between a shipped chunk and its forward
                with mig.lock:
                    handle = table.add(total, option, sync=sync)
                    self._mig_forward_dense(
                        mig, int(header0["table"]), total,
                        header0.get("option"),
                        [(c.client_id, h.get("rid"))
                         for _i, c, h, _a in items])
            else:
                handle = table.add(total, option, sync=sync)
            if self._tap is not None:
                # a fused group forwards as its ONE pre-summed apply:
                # K original frames would desync generation counts and
                # float rounding on the follower
                self._tap.forward_fused(
                    "add", int(header0["table"]), [total],
                    origins=[(c.client_id, h.get("rid"))
                             for _i, c, h, _a in items],
                    pgen=handle.generation,
                    option=header0.get("option"))
            reply = {"ok": True, "gen": handle.generation, "fused": k}
            return {idx: (dict(reply), []) for idx, *_ in items}
        if op == "kv_add":
            all_keys, all_deltas = [], []
            for _idx, _conn, header, arrays in items:
                keys = np.ascontiguousarray(arrays[0]) \
                    .astype(np.uint64, copy=False)
                delta = np.asarray(
                    wire.decode_delta(header.get("quant"), arrays[1:]),
                    dtype=table.dtype)
                if len(delta) != len(keys):
                    raise ValueError(
                        f"kv_add keys/delta length mismatch "
                        f"{len(keys)} vs {len(delta)}")
                all_keys.append(keys)
                all_deltas.append(delta)
            cat_keys = np.concatenate(all_keys)
            cat_deltas = np.concatenate(all_deltas, axis=0)
            self._heat_touch_keys(header0, cat_keys)
            # CoalescingBuffer KV rule: cross-request duplicates
            # pre-sum so the stateful-updater unique-ids contract
            # holds for the ONE fused batch
            uniq, inverse = np.unique(cat_keys, return_inverse=True)
            summed = np.zeros((len(uniq),) + cat_deltas.shape[1:],
                              cat_deltas.dtype)
            np.add.at(summed, inverse, cat_deltas)
            mig = self._mig_forwarding()
            if mig is not None:
                with mig.lock:
                    handle = table.add(uniq, summed, option, sync=sync)
                    table._check_overflow()
                    self._mig_forward_kv(
                        mig, int(header0["table"]), uniq, summed,
                        header0.get("option"),
                        [(c.client_id, h.get("rid"))
                         for _i, c, h, _a in items])
            else:
                handle = table.add(uniq, summed, option, sync=sync)
                # per-request overflow verdict: the fused batch drops
                # atomically on overflow, so ONE readback per cycle
                # buys a truthful reply for every request in it (the
                # raise lands in _execute_group's fallback, which
                # re-runs per frame)
                table._check_overflow()
            if self._tap is not None:
                # forwarded AFTER the overflow check: a batch the
                # primary dropped must never reach a follower
                self._tap.forward_fused(
                    "kv_add", int(header0["table"]), [uniq, summed],
                    origins=[(c.client_id, h.get("rid"))
                             for _i, c, h, _a in items],
                    pgen=handle.generation,
                    option=header0.get("option"))
            reply = {"ok": True, "gen": handle.generation, "fused": k}
            return {idx: (dict(reply), []) for idx, *_ in items}
        if op == "get":
            for _idx, _conn, header, _arrays in items:
                self._maybe_arm_replica(header)
            self._heat_touch_dense(header0, table, weight=float(k))
            values = np.ascontiguousarray(table.get())
            return {idx: ({"ok": True, "fused": k}, [values])
                    for idx, *_ in items}
        if op == "kv_get":
            lens = []
            all_keys = []
            for _idx, _conn, header, arrays in items:
                self._maybe_arm_replica(header)
                keys = np.ascontiguousarray(arrays[0]) \
                    .astype(np.uint64, copy=False)
                all_keys.append(keys)
                lens.append(len(keys))
            cat_keys = np.concatenate(all_keys)
            self._heat_touch_keys(header0, cat_keys)
            values, found = table.get(cat_keys)
            out: Dict[int, tuple] = {}
            off = 0
            for (idx, *_), n in zip(items, lens):
                out[idx] = ({"ok": True, "fused": k},
                            [np.ascontiguousarray(values[off:off + n]),
                             np.ascontiguousarray(found[off:off + n])])
                off += n
            return out
        raise ValueError(f"unfusable op {op!r}")

    # -- request execution (single-frame path) ------------------------------

    def _execute(self, conn: _Conn, op: str, header: Dict[str, Any],
                 arrays: List[np.ndarray], force_sync: bool = False
                 ) -> Optional[Tuple[Dict[str, Any], list]]:
        if op == "hello":
            requested = str(header.get("client") or conn.client_id)
            claim = header.get("partition")
            if self._partition is not None and claim is not None:
                # fleet handshake: a client claiming a DIFFERENT map
                # would silently route rows to the wrong owner — refuse
                # before any data op flows. (A claimless client is
                # operator tooling — stats, smoke probes — and may
                # talk to the shard directly.)
                err = self._partition.map.mismatch(claim)
                if err is not None:
                    telemetry.counter("wire.hello.refused",
                                      server=self.name).inc()
                    log.warn("server %r refused hello from %r: %s",
                             self.name, requested, err)
                    return ({"ok": False, "error": err,
                             "partition":
                                 self._partition.map.to_wire()}, [])
            conn.client_id = requested
            self._dedup_cache(requested)
            reply = {"ok": True, "client_id": requested,
                     "server": self.name,
                     "quant": wire.quant_mode_from_env()}
            if self._partition is not None:
                reply["partition"] = self._partition.describe()
            return (reply, [])
        if op == "ping":
            # the clock-alignment probe: echo this process's wall
            # clock + identity; the client puts t_server at the RTT
            # midpoint to estimate the per-connection offset
            return ({"ok": True, "t_server": time.time(),
                     "host": telemetry.host_index(),
                     "pid": os.getpid()}, [])
        if op == "noop":
            # admission-controlled no-op: what the server.flood chaos
            # point injects (a control op would jump the fair queue)
            return ({"ok": True}, [])
        if op == "stats":
            return ({"ok": True, "status": self.status()}, [])
        if op == "shutdown":
            # reply first (queued), then stop — the writer drains the
            # queue before the socket closes under it
            conn.sendq.put(({"ok": True, "rid": header.get("rid")}, []))
            threading.Thread(target=self.stop, daemon=True).start()
            return None

        if op == "promote":
            return self._op_promote(header)
        if op == "adopt":
            return self._op_adopt(header)
        # a follower is read-only to clients: its state is the primary's
        # delta stream, verbatim — a direct client mutation would fork it
        if self._follower and op in ("create", "add", "kv_add"):
            return ({"ok": False, "follower": True,
                     "server": self.name,
                     "error": "follower replica is read-only: "
                              "mutations go to the primary"}, [])
        follower_lag: Optional[int] = None
        if self._follower and op in ("get", "kv_get"):
            refused, follower_lag = self._follower_read_check(header)
            if refused is not None:
                return refused

        # mutating ops replay from the dedup cache: a resend after a
        # reconnect must not re-apply ("repl" included: the tap's link
        # replays its unacked window after a reconnect like any
        # client; migrate chunk/fwd/manifest for the same reason — a
        # donor's link redial replays its unacked window)
        mutating = op in ("create", "add", "kv_add", "repl",
                          wire.MIGRATE_CHUNK, wire.MIGRATE_FWD,
                          wire.MIGRATE_MANIFEST)
        if mutating:
            cached = self._dedup_get(conn.client_id, header.get("rid"))
            if cached is not None:
                telemetry.counter("wire.dedup.replays", op=op).inc()
                return cached

        if op == "create":
            reply = self._op_create(header)
        elif op == "get":
            reply = self._op_get(header)
        elif op == "kv_get":
            reply = self._op_kv_get(header, arrays)
        elif op == "add":
            reply = self._op_add(header, arrays, force_sync=force_sync,
                                 origin=conn.client_id)
        elif op == "kv_add":
            reply = self._op_kv_add(header, arrays,
                                    force_sync=force_sync,
                                    origin=conn.client_id)
        elif op == "repl":
            reply = self._op_repl(header, arrays)
        elif op in wire.MIGRATE_OPS:
            reply = self._op_migrate(op, header, arrays)
        else:
            raise ValueError(f"unknown wire op {op!r}")
        if follower_lag is not None and reply[0].get("ok"):
            # a follower-served read names its real lag so clients
            # (and tests) can hold the staleness bound to account
            reply[0]["follower"] = True
            reply[0]["lag"] = follower_lag
        if self._tap is not None and reply[0].get("ok") \
                and (op in ("create", "add", "kv_add")
                     or (op in wire.MIGRATE_OPS
                         and op != wire.MIGRATE_STATE)):
            # migrate frames replicate too (state polls excepted): a
            # follower builds/fills the same staging shard and swaps
            # it in lockstep at commit, so failover composes with a
            # mid-flight reshard
            self._tap.forward(conn.client_id, header, arrays, reply[0])
        if mutating:
            self._dedup_put(conn.client_id, header.get("rid"), reply)
        return reply

    # -- dedup cache (bounded LRU of bounded LRUs) --------------------------

    def _dedup_cache(self, client: str) -> "collections.OrderedDict":
        cache = self._dedup.get(client)
        if cache is None:
            cache = self._dedup[client] = collections.OrderedDict()
            while len(self._dedup) > self._dedup_clients:
                self._dedup.popitem(last=False)
        else:
            self._dedup.move_to_end(client)
        return cache

    def _dedup_get(self, client: str, rid) -> Optional[tuple]:
        if rid is None:
            return None
        entry = self._dedup_cache(client).get(int(rid))
        if entry is not None:
            header, arrays = entry
            return (dict(header), list(arrays))
        return None

    def _dedup_put(self, client: str, rid, reply: tuple) -> None:
        if rid is None:
            return
        cache = self._dedup_cache(client)
        cache[int(rid)] = reply
        while len(cache) > self._dedup_depth:
            cache.popitem(last=False)

    # -- replication ops (see server/replication.py) -------------------------

    def _follower_read_check(self, header: Dict[str, Any]
                             ) -> Tuple[Optional[tuple], int]:
        """Staleness gate for a client read on a FOLLOWER: serve iff
        this table lags the stream's newest primary generation by at
        most ``staleness + server.repl.slack``. Returns
        ``(refusal_reply | None, lag)``."""
        try:
            tid = int(header.get("table", -1))
        except (TypeError, ValueError):
            tid = -1
        table = self._tables.get(tid)
        local_gen = int(getattr(table, "generation", 0) or 0) \
            if table is not None else 0
        lag = self._fstate.lag(tid, local_gen) \
            if self._fstate is not None else 0
        staleness = header.get("staleness")
        if staleness is None:
            # an unbounded (read-your-writes) read cannot be answered
            # honestly here: structured refusal, router uses the primary
            return ({"ok": False, "stale": True, "follower": True,
                     "server": self.name,
                     "error": "follower serves bounded-staleness "
                              "reads only"}, []), lag
        bound = max(int(staleness), 0) + max(int(self._repl_slack), 0)
        if lag > bound:
            telemetry.counter("replication.stale_refusals",
                              server=self.name).inc()
            return ({"ok": False, "stale": True, "follower": True,
                     "lag": lag, "server": self.name,
                     "error": f"follower lags {lag} generations, "
                              f"past the bound {bound}"}, []), lag
        return None, lag

    def _op_repl(self, header: Dict[str, Any],
                 arrays: List[np.ndarray]) -> tuple:
        """Apply one replicated mutation: the original frame's bytes,
        decoded and applied exactly as the primary did (bit parity),
        then recorded under every ORIGINATING (client, rid) — the
        promotion replay window that keeps a post-failover client
        resend exactly-once."""
        if not self._follower:
            raise ValueError("repl frame at a non-follower server")
        orig, origins, pgen, tid = wire.repl_unwrap(header)
        op = str(orig.get("op", "?"))
        t0 = time.time()
        if op == "create":
            reply = self._op_create(orig, force_tid=tid)
        elif op == "add":
            reply = self._op_add(orig, arrays)
        elif op == "kv_add":
            reply = self._op_kv_add(orig, arrays)
        elif op in wire.MIGRATE_OPS:
            # a mid-reshard primary streams its migrate frames too: the
            # follower mirrors begin/chunks/forwards into its own
            # staging and swaps at commit in lockstep (it never donates
            # or forwards itself — _mig_forwarding gates on donor)
            reply = self._op_migrate(op, orig, arrays)
        else:
            raise ValueError(f"unknown replicated op {op!r}")
        # FRESH dicts per replay key: _finish bakes the STREAMER's rid
        # into the reply object it returns, and a shared dict would
        # leak that rid into the origin-keyed replay entries
        for oc, orid in origins:
            if orid is not None:
                self._dedup_put(oc, orid,
                                (dict(reply[0]), list(reply[1])))
        t = tid
        if t is None:
            try:
                t = int(orig.get("table"))
            except (TypeError, ValueError):
                t = None
        if self._fstate is not None and t is not None:
            self._fstate.applied(t, int(reply[0].get("gen") or 0))
        ctx = wire.trace_ctx(orig)
        if ctx is not None and _trace.active():
            # the apply span chains under the ORIGINATING client
            # request, so a traced write shows its replication hop
            with _trace.adopt_remote(ctx):
                _trace.emit_span("server.repl.apply", t0,
                                 time.time() - t0, server=self.name,
                                 op=op, origins=len(origins))
        return reply

    def _op_promote(self, header: Dict[str, Any]) -> tuple:
        """Flip this FOLLOWER to primary for its rank (failover). Bumps
        the partition map version — the hello-refusal machinery then
        refuses every router still claiming the old map, whose refresh
        (via the refusal's map + the rewritten fleet file) lands on
        this server. Idempotent: a second promote reports the map."""
        if not self._follower:
            wire_map = self._partition.map.to_wire() \
                if self._partition is not None else None
            return ({"ok": True, "already": True,
                     "partition": wire_map, "server": self.name}, [])
        self._follower = False
        # the snapshot replicas' staleness reference reverts to the
        # LOCAL generation: the repl stream is over, and a frozen
        # stream high-water mark would clamp their lag to zero while
        # direct writes advance the table underneath them
        for rep in self._replicas.values():
            rep.stream = None
        wire_map = None
        if self._partition is not None:
            old = self._partition.map
            new_map = _partition_mod.PartitionMap(
                old.n, version=old.version + 1,
                kv_buckets=old.kv_buckets, replicas=old.replicas)
            self._partition = _partition_mod.PartitionMember(
                new_map, self._partition.rank)
            wire_map = new_map.to_wire()
            if self._fleet_file:
                try:
                    doc = _partition_mod.read_fleet_file(
                        self._fleet_file)
                    if doc is not None:
                        new_doc = _partition_mod.promote_in_doc(
                            doc, self._partition.rank,
                            self._replica_idx or 0)
                        _partition_mod.write_fleet_file(
                            self._fleet_file, new_map,
                            new_doc["members"])
                except Exception as exc:    # noqa: BLE001 — promotion
                    log.warn("server %r: fleet-file rewrite failed "
                             "on promote: %s", self.name, exc)
            # R>2: the new primary keeps streaming to the remaining
            # followers of this rank (the rewritten fleet file no
            # longer lists us; with none left the tap stays dormant)
            if self._tap is None and self._fleet_file:
                self._tap = _replication.ReplicationTap(
                    self.name, member=self._partition,
                    fleet_file=self._fleet_file)
        telemetry.counter("replication.promotions",
                          server=self.name).inc()
        log.info("server %r PROMOTED to primary (map v%s)", self.name,
                 self._partition.map.version
                 if self._partition is not None else "-")
        return ({"ok": True, "promoted": True, "server": self.name,
                 "partition": wire_map}, [])

    def _op_adopt(self, header: Dict[str, Any]) -> tuple:
        """Adopt a newer partition map in place (broadcast to the
        surviving members after a promotion): monotonic and idempotent;
        live connections are untouched — the version only gates future
        hellos."""
        wire_map = header.get("map")
        if self._partition is None or not isinstance(wire_map, dict):
            return ({"ok": True, "ignored": True}, [])
        new = _partition_mod.PartitionMap.from_wire(wire_map)
        cur = self._partition.map
        if new.version > cur.version:
            self._partition = _partition_mod.PartitionMember(
                new, self._partition.rank)
            if self._tap is not None:
                self._tap.update_claim(new.to_wire())
            telemetry.counter("wire.map.adopted",
                              server=self.name).inc()
            log.info("server %r adopted partition map v%d", self.name,
                     new.version)
        return ({"ok": True,
                 "version": self._partition.map.version}, [])

    # -- live resharding (elastic fleet; frame contract in wire.py) --------

    def _op_migrate(self, op: str, header: Dict[str, Any],
                    arrays: List[np.ndarray]) -> tuple:
        if op == wire.MIGRATE_BEGIN:
            return self._op_migrate_begin(header)
        if op == wire.MIGRATE_STATE:
            return self._op_migrate_state(header)
        if op == wire.MIGRATE_COMMIT:
            return self._op_migrate_commit(header)
        if op == wire.MIGRATE_ABORT:
            return self._op_migrate_abort(header)
        if op == wire.MIGRATE_MANIFEST:
            return self._op_migrate_manifest(header)
        if op == wire.MIGRATE_CHUNK:
            return self._op_migrate_chunk(header, arrays)
        if op == wire.MIGRATE_FWD:
            return self._op_migrate_fwd(header, arrays)
        if op == wire.MIGRATE_FIN:
            return self._op_migrate_fin(header)
        raise ValueError(f"unknown migrate op {op!r}")

    def _op_migrate_begin(self, header: Dict[str, Any]) -> tuple:
        plan = str(header.get("plan", ""))
        mig = self._migration
        if mig is not None and mig.plan != plan \
                and mig.state not in ("committed", "aborted"):
            return ({"ok": False, "server": self.name,
                     "error": f"reshard {mig.plan!r} already in "
                              "flight"}, [])
        if self._partition is None:
            return ({"ok": False, "server": self.name,
                     "error": "reshard needs a fleet member "
                              "(no partition)"}, [])
        new_map = _partition_mod.PartitionMap.from_wire(header["map"])
        cur = self._partition.map
        if mig is not None and mig.plan == plan:
            if mig.old is None or mig.state != "receiving":
                # a redelivered begin (admin retry) is a no-op
                return ({"ok": True, "already": True,
                         "state": mig.state}, [])
            # else: the donor's manifest beat the admin's begin here
            # (streams start as soon as each donor hears begin) —
            # upgrade the receive-only stub in place, keeping its
            # staging and whatever chunks already landed
        elif new_map.version != cur.version + 1:
            return ({"ok": False, "server": self.name,
                     "error": f"reshard targets v{new_map.version}, "
                              f"this member serves v{cur.version}"},
                    [])
        else:
            mig = _Migration(plan, cur, new_map, {},
                             self._partition.rank,
                             ctx=wire.trace_ctx(header))
        mig.members = {int(r): str(a) for r, a
                       in (header.get("members") or {}).items()}
        diff = _partition_mod.map_diff(cur, new_map)
        rank = mig.rank
        mig.donor = rank in diff.donor_ranks() and not self._follower
        if mig.donor:
            for tid, (_name, kind, spec) in sorted(
                    self._table_specs.items()):
                if kind == "array":
                    segs = [(r, lo, hi) for d, r, lo, hi
                            in diff.dense_moves(int(spec["size"]))
                            if d == rank]
                    if segs:
                        mig.dense_segs[tid] = segs
                else:
                    segs = [(r, lo, hi) for d, r, lo, hi
                            in diff.bucket_moves if d == rank]
                    if segs:
                        mig.kv_segs[tid] = segs
        if rank < new_map.n:
            new_member = _partition_mod.PartitionMember(new_map, rank)
            for tid in sorted(self._table_specs):
                if tid not in mig.staging:
                    mig.staging[tid] = self._mig_build_staging(
                        tid, new_member)
        self._migration = mig
        mig.state = "streaming" if mig.donor else "shipped"
        if mig.donor:
            self._spawn(self._mig_stream, "mig-stream", mig)
        log.info("server %r: reshard %r begin v%d→v%d donor=%s "
                 "(%d dense segs, %d kv segs)", self.name, plan,
                 cur.version, new_map.version, mig.donor,
                 sum(len(v) for v in mig.dense_segs.values()),
                 sum(len(v) for v in mig.kv_segs.values()))
        return ({"ok": True, "plan": plan, "donor": mig.donor,
                 "state": mig.state}, [])

    def _op_migrate_manifest(self, header: Dict[str, Any]) -> tuple:
        plan = str(header.get("plan", ""))
        new_map = _partition_mod.PartitionMap.from_wire(header["map"])
        mig = self._migration
        if mig is None or mig.state in ("committed", "aborted"):
            if self._partition is None:
                return ({"ok": False, "server": self.name,
                         "error": "manifest at a partitionless "
                                  "server"}, [])
            cur = self._partition.map
            if cur.version == new_map.version:
                # a member BORN at v+1: its live tables already have
                # the new geometry; chunks/forwards apply directly
                old = None
            elif cur.version + 1 == new_map.version:
                # existing member, donor's stream raced ahead of the
                # admin's begin: stage now, merge when begin arrives
                old = cur
            else:
                return ({"ok": False, "server": self.name,
                         "error": f"manifest targets v"
                                  f"{new_map.version}, this member "
                                  f"serves v{cur.version}"}, [])
            mig = _Migration(plan, old, new_map, {},
                             self._partition.rank,
                             ctx=wire.trace_ctx(header))
            mig.state = "receiving"
            self._migration = mig
        elif mig.plan != plan:
            return ({"ok": False, "server": self.name,
                     "error": f"manifest for plan {plan!r} but "
                              f"{mig.plan!r} is in flight"}, [])
        new_member = _partition_mod.PartitionMember(mig.new, mig.rank)
        for row in header.get("tables") or ():
            tid = int(row["table"])
            if mig.old is None:
                # new member: create the live table itself (idempotent
                # by name, force_tid keeps the id space aligned)
                self._op_create({"name": row["name"],
                                 "kind": row["kind"],
                                 "spec": row["spec"]},
                                force_tid=tid, staging_ok=True)
            else:
                self._table_specs.setdefault(
                    tid, (str(row["name"]), str(row["kind"]),
                          dict(row["spec"] or {})))
                if tid not in mig.staging:
                    mig.staging[tid] = self._mig_build_staging(
                        tid, new_member)
        return ({"ok": True, "plan": plan, "state": mig.state}, [])

    def _op_migrate_chunk(self, header: Dict[str, Any],
                          arrays: List[np.ndarray]) -> tuple:
        mig = self._mig_of(header)
        if int(header.get("crc", -1)) != wire.migrate_crc(arrays):
            # torn chunk: abort LOUDLY — the donor's drain raises, its
            # stream fails, and the admin's abort wave rolls back to v
            raise ValueError(
                f"reshard {mig.plan!r}: torn migrate chunk (crc "
                f"mismatch) for table {header.get('table')}")
        tid = int(header["table"])
        lo, hi = (int(x) for x in header["range"])
        target = self._mig_target(mig, tid)
        if str(header.get("kind")) == "dense":
            name, _kind, spec = self._table_specs[tid]
            nlo, nhi = self._mig_new_member(mig).dense_range(
                int(spec["size"]))
            if lo < nlo or hi > nhi:
                raise ValueError(
                    f"reshard {mig.plan!r}: chunk [{lo},{hi}) outside "
                    f"this rank's new range [{nlo},{nhi}) of "
                    f"table {name!r}")
            values = np.asarray(arrays[0])
            # set semantics, idempotent: a replayed chunk (donor link
            # redial) overwrites with the same bytes
            host = np.asarray(target.raw()).copy()
            host[lo - nlo: hi - nlo] = values.astype(host.dtype,
                                                     copy=False)
            target.put_raw(host)
        else:
            keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                          copy=False)
            self._mig_kv_inject(target, keys, np.asarray(arrays[1]))
        mig.chunks_in += 1
        return ({"ok": True, "seq": header.get("seq")}, [])

    def _op_migrate_fwd(self, header: Dict[str, Any],
                        arrays: List[np.ndarray]) -> tuple:
        mig = self._mig_of(header)
        orig, origins = wire.migrate_fwd_unwrap(header)
        op = str(orig.get("op"))
        tid = int(orig["table"])
        target = self._mig_target(mig, tid)
        option = self._option(orig)
        if op == "add":
            glo, ghi = (int(x) for x in orig["range"])
            _name, _kind, spec = self._table_specs[tid]
            nlo, nhi = self._mig_new_member(mig).dense_range(
                int(spec["size"]))
            delta = np.asarray(arrays[0])
            local = np.zeros(nhi - nlo, dtype=np.dtype(target.dtype))
            local[glo - nlo: ghi - nlo] = delta
            handle = target.add(local, option, sync=False)
        elif op == "kv_add":
            keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                          copy=False)
            handle = target.add(keys, np.asarray(arrays[1]), option,
                                sync=False)
        else:
            raise ValueError(f"unforwardable op {op!r}")
        reply = ({"ok": True, "gen": handle.generation,
                  "fwd": True}, [])
        # exactly-once note: the ORIGIN (client, rid) pairs in the
        # frame are trace breadcrumbs, NOT a dedup key here — rids are
        # per-connection, so a client resend always replays at the
        # DONOR (whose dedup caches the relay reply and never forwards
        # twice), and the donor's link resends replay from this
        # member's own wire dedup under the link's client id. Caching
        # origin rids here would poison the client's direct rid space
        # on this connection.
        mig.forwards_in += 1
        return reply

    def _op_migrate_state(self, header: Dict[str, Any]) -> tuple:
        mig = self._migration
        if mig is None:
            return ({"ok": True, "state": "idle"}, [])
        return ({"ok": True, **mig.status()}, [])

    def _op_migrate_commit(self, header: Dict[str, Any]) -> tuple:
        mig = self._mig_of(header)
        if mig.state == "committed":
            return ({"ok": True, "already": True,
                     "version": mig.new.version}, [])
        if mig.state in ("failed", "aborted", "begin", "streaming"):
            return ({"ok": False, "state": mig.state,
                     "server": self.name, "error": mig.error
                     or f"cannot commit from state {mig.state!r}"},
                    [])
        t0 = time.time()
        with mig.lock:
            # drain every outstanding chunk/forward ack first: an
            # unacked frame at the swap could be lost — a dead link
            # raises here, failing the commit (admin then aborts)
            for link in mig.links.values():
                link.drain()
            if mig.rank < mig.new.n:
                new_member = _partition_mod.PartitionMember(
                    mig.new, mig.rank)
                old_member = self._partition
                for tid in sorted(mig.staging):
                    self._mig_commit_table(mig, tid, mig.staging[tid],
                                           old_member, new_member)
                self._partition = new_member
                for tid, (name, kind, spec) in \
                        self._table_specs.items():
                    self._table_parts[tid] = self._part_info(
                        name, kind, spec)
                if self._tap is not None:
                    self._tap.update_claim(mig.new.to_wire())
            # an EVICTED rank (shrink) never flips: it keeps relaying
            # old-map frames by the new map until the admin shuts it
            # down after the linger window
            mig.staging.clear()
            mig.state = "committed"
        if mig.ctx is not None and _trace.active():
            with _trace.adopt_remote(mig.ctx):
                _trace.emit_span("server.migrate.commit", t0,
                                 time.time() - t0, server=self.name,
                                 plan=mig.plan,
                                 version=mig.new.version)
        log.info("server %r: reshard %r COMMITTED at v%d "
                 "(%d chunks in, %d forwards in)", self.name,
                 mig.plan, mig.new.version, mig.chunks_in,
                 mig.forwards_in)
        return ({"ok": True, "version": mig.new.version}, [])

    def _op_migrate_abort(self, header: Dict[str, Any]) -> tuple:
        mig = self._migration
        plan = str(header.get("plan", ""))
        if mig is None or mig.plan != plan:
            return ({"ok": True, "idle": True}, [])
        if mig.state == "committed":
            return ({"ok": False, "server": self.name,
                     "error": "cannot abort a committed reshard"}, [])
        with mig.lock:
            mig.state = "aborted"
            # live tables were never touched by the migration (donors
            # stream FROM them, recipients write STAGING) — dropping
            # staging leaves v serving bit-exactly
            mig.staging.clear()
            links = list(mig.links.values())
            mig.links.clear()
        for link in links:
            with contextlib.suppress(Exception):
                link.abort()
            with contextlib.suppress(Exception):
                link.close()
        self._c_mig_aborts.inc()
        self._migration = None
        log.warn("server %r: reshard %r ABORTED (%s)", self.name,
                 plan, header.get("reason") or mig.error or "admin")
        return ({"ok": True, "aborted": True}, [])

    def _op_migrate_fin(self, header: Dict[str, Any]) -> tuple:
        log.info("server %r: reshard %r stream from rank %s done "
                 "(%s chunks, %s bytes)", self.name,
                 header.get("plan"), header.get("from_rank"),
                 header.get("chunks"), header.get("bytes"))
        return ({"ok": True}, [])

    # -- resharding internals ----------------------------------------------

    def _mig_of(self, header: Dict[str, Any]) -> _Migration:
        mig = self._migration
        plan = str(header.get("plan", ""))
        if mig is None or mig.plan != plan:
            raise ValueError(
                f"no reshard plan {plan!r} on server {self.name!r}")
        return mig

    def _mig_new_member(self, mig: _Migration):
        if mig.rank >= mig.new.n:
            raise ValueError(
                f"rank {mig.rank} is evicted by v{mig.new.version} "
                "and owns nothing under the new map")
        return _partition_mod.PartitionMember(mig.new, mig.rank)

    def _mig_build_staging(self, tid: int, new_member):
        """A NEW-geometry shard for one table. The name gets a version
        suffix so a tiered staging table never shares the live one's
        disk spill path (the registry is a list — no name key to
        collide on)."""
        name, kind, spec = self._table_specs[tid]
        return self._build_table(f"{name}.v{new_member.map.version}",
                                 kind, dict(spec), member=new_member)

    def _mig_target(self, mig: _Migration, tid: int):
        """Where a chunk/forward lands: the staging shard, or (on a
        member born at v+1, whose live tables ARE the new geometry)
        the live table."""
        st = mig.staging.get(tid)
        if st is not None:
            return st
        table = self._tables.get(tid)
        if table is None:
            raise KeyError(
                f"no table {tid} for reshard {mig.plan!r}")
        return table

    def _mig_link(self, mig: _Migration, rcpt: int):
        """This donor's FIFO link to one recipient (caller holds
        ``mig.lock``): dialed once, manifest first — so every chunk
        and forward to that rank rides ONE ordered stream, which is
        what makes chunk-then-forward ordering free."""
        link = mig.links.get(int(rcpt))
        if link is not None:
            return link
        addr = mig.members.get(int(rcpt))
        if not addr:
            raise ValueError(
                f"reshard {mig.plan!r}: no address for rank {rcpt}")
        from multiverso_tpu.client import transport as _transport
        link = _transport.WireClient(
            addr, client=f"mig:{self.name}", quant=None,
            retry_policy=_replication.repl_retry_policy(
                f"mig-{self.name}"),
            deadline_s=None)
        mig.links[int(rcpt)] = link
        rows = [{"table": tid, "name": name, "kind": kind,
                 "spec": spec}
                for tid, (name, kind, spec)
                in sorted(self._table_specs.items())]
        link.submit({"op": wire.MIGRATE_MANIFEST, "plan": mig.plan,
                     "from_rank": mig.rank,
                     "map": mig.new.to_wire(), "tables": rows}, [])
        return link

    def _mig_rate_sleep(self, chunks: int = 1) -> None:
        rate = float(self._migrate_rate or 0.0)
        if rate > 0.0:
            time.sleep(chunks / rate)

    def _mig_forwarding(self) -> Optional[_Migration]:
        """The in-flight migration IF this member must forward writes
        alongside its applies (pre-commit donor primary)."""
        mig = self._migration
        if mig is not None and mig.donor \
                and mig.state in ("streaming", "shipped"):
            return mig
        return None

    def _relay_mode(self, header: Dict[str, Any]
                    ) -> Optional[_Migration]:
        """Post-commit old-map frame detection: clients stamp every
        frame with the map version it was built against (``pv``,
        frozen at build so reconnect replays stay identical); anything
        below the committed TARGET version addresses geometry this
        member no longer serves. Comparing against the target (not the
        live partition) covers the evicted rank too, whose partition
        never flips."""
        mig = self._migration
        if mig is None or mig.state != "committed" \
                or mig.old is None:
            return None
        pv = header.get("pv")
        if pv is None:
            return None
        return mig if int(pv) < mig.new.version else None

    def _mig_remap_refusal(self, mig: _Migration) -> Dict[str, Any]:
        return {"ok": False, "remap": True, "server": self.name,
                "partition": mig.new.to_wire(),
                "error": f"partition map advanced to "
                         f"v{mig.new.version}: re-read the fleet "
                         "file and re-split"}

    def _mig_forward_dense(self, mig: _Migration, tid: int,
                           delta: np.ndarray, option_raw,
                           origins: List[Tuple[str, Any]],
                           shipped_only: bool = True) -> None:
        """Forward the moved slices of one APPLIED dense delta (caller
        holds ``mig.lock``). Pre-commit: only already-shipped spans —
        the not-yet-extracted rest rides its chunk. Post-commit relay
        (``shipped_only=False``): every donated span."""
        segs = mig.dense_segs.get(tid)
        if not segs:
            return
        _name, _kind, spec = self._table_specs[tid]
        olo, _ohi = _partition_mod.PartitionMember(
            mig.old, mig.rank).dense_range(int(spec["size"]))
        for rcpt, slo, shi in segs:
            spans = [(slo, shi)] if not shipped_only \
                else mig.shipped_overlaps(tid, slo, shi)
            for lo, hi in spans:
                sl = np.ascontiguousarray(
                    np.asarray(delta)[lo - olo: hi - olo])
                if sl.size == 0:
                    continue
                orig = {"op": "add", "table": tid,
                        "range": [int(lo), int(hi)]}
                if option_raw:
                    orig["option"] = dict(option_raw)
                link = self._mig_link(mig, rcpt)
                link.submit(wire.migrate_fwd_wrap(
                    orig, plan=mig.plan, from_rank=mig.rank,
                    origins=origins), [sl])
                mig.forwards += 1
                self._c_mig_fwds.inc()
                try:
                    _chaos.chaos_point("reshard.handoff")
                except _chaos.ChaosError as exc:
                    # CONTAINED: the forward is already on the link;
                    # an error reply here would be dedup-cached and
                    # replayed to every client resend as a permanent
                    # failure
                    log.warn("reshard.handoff chaos (forward, "
                             "contained): %s", exc)

    def _mig_forward_kv(self, mig: _Migration, tid: int,
                        keys: np.ndarray, delta: np.ndarray,
                        option_raw, origins: List[Tuple[str, Any]],
                        shipped_only: bool = True) -> None:
        """KV counterpart of :meth:`_mig_forward_dense` (caller holds
        ``mig.lock``); keys filter by OLD-map logical bucket, which is
        version-invariant (the bucket space is pinned across a
        reshard)."""
        segs = mig.kv_segs.get(tid)
        if not segs:
            return
        keys = np.ascontiguousarray(keys).astype(np.uint64,
                                                 copy=False)
        if len(keys) == 0:
            return
        kb = mig.old.kv_bucket(keys)
        for rcpt, blo, bhi in segs:
            spans = [(blo, bhi)] if not shipped_only \
                else mig.shipped_overlaps(tid, blo, bhi)
            for lo, hi in spans:
                sel = (kb >= lo) & (kb < hi)
                if not sel.any():
                    continue
                ck = np.ascontiguousarray(keys[sel])
                cv = np.ascontiguousarray(np.asarray(delta)[sel])
                orig = {"op": "kv_add", "table": tid}
                if option_raw:
                    orig["option"] = dict(option_raw)
                link = self._mig_link(mig, rcpt)
                link.submit(wire.migrate_fwd_wrap(
                    orig, plan=mig.plan, from_rank=mig.rank,
                    origins=origins), [ck, cv])
                mig.forwards += 1
                self._c_mig_fwds.inc()
                try:
                    _chaos.chaos_point("reshard.handoff")
                except _chaos.ChaosError as exc:
                    log.warn("reshard.handoff chaos (forward, "
                             "contained): %s", exc)

    def _mig_relay_add(self, mig: _Migration, header: Dict[str, Any],
                       arrays: List[np.ndarray],
                       origin: Optional[str],
                       force_sync: bool) -> tuple:
        """A post-commit dense write built against the OLD map:
        dropping it loses an update the client already paid for, so
        apply the retained overlap locally and forward the donated
        slices — then tell the client to re-split (``remap``)."""
        tid = int(header.get("table", -1))
        if tid not in self._table_specs:
            raise KeyError(f"no table {tid} on this server")
        _name, _kind, spec = self._table_specs[tid]
        size = int(spec["size"])
        olo, ohi = _partition_mod.PartitionMember(
            mig.old, mig.rank).dense_range(size)
        delta = np.asarray(
            wire.decode_delta(header.get("quant"), arrays))
        if len(delta) != ohi - olo:
            raise ValueError(
                f"relayed add length {len(delta)} != old-map local "
                f"range {ohi - olo}")
        gen = 0
        if mig.rank < mig.new.n:
            nlo, nhi = _partition_mod.PartitionMember(
                mig.new, mig.rank).dense_range(size)
            table = self._tables[tid]
            local = np.zeros(nhi - nlo, dtype=np.dtype(table.dtype))
            x, y = max(olo, nlo), min(ohi, nhi)
            if x < y:
                local[x - nlo: y - nlo] = delta[x - olo: y - olo]
            handle = table.add(
                local, self._option(header),
                sync=bool(header.get("sync")) or force_sync)
            gen = handle.generation
        if not self._follower:
            with mig.lock:
                self._mig_forward_dense(
                    mig, tid, delta, header.get("option"),
                    [(origin or "?", header.get("rid"))],
                    shipped_only=False)
                for link in mig.links.values():
                    link.drain()
        return ({"ok": True, "gen": gen, "relay": True,
                 "remap": True,
                 "partition": mig.new.to_wire()}, [])

    def _mig_relay_kv_add(self, mig: _Migration,
                          header: Dict[str, Any],
                          arrays: List[np.ndarray],
                          origin: Optional[str],
                          force_sync: bool) -> tuple:
        """KV counterpart of :meth:`_mig_relay_add`: split by NEW-map
        ownership, apply mine, forward the rest."""
        tid = int(header.get("table", -1))
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        delta = np.asarray(
            wire.decode_delta(header.get("quant"), arrays[1:]))
        gen = 0
        mine = (mig.new.kv_owner(keys) == mig.rank) \
            if mig.rank < mig.new.n and len(keys) \
            else np.zeros(len(keys), bool)
        if mine.any():
            handle = self._tables[tid].add(
                keys[mine], delta[mine], self._option(header),
                sync=bool(header.get("sync")) or force_sync)
            gen = handle.generation
        if not self._follower and len(keys) and not mine.all():
            with mig.lock:
                self._mig_forward_kv(
                    mig, tid, keys[~mine], delta[~mine],
                    header.get("option"),
                    [(origin or "?", header.get("rid"))],
                    shipped_only=False)
                for link in mig.links.values():
                    link.drain()
        return ({"ok": True, "gen": gen, "relay": True,
                 "remap": True,
                 "partition": mig.new.to_wire()}, [])

    def _mig_stream(self, mig: _Migration) -> None:
        """Donor streaming thread: walk every donated range, ship it
        chunk by chunk (each chunk under ``mig.lock``, the rate sleep
        outside), then FIN + drain and flip to "shipped". Any error —
        dead recipient, chaos, torn-chunk reply — marks the migration
        failed; the admin's poll sees it and aborts fleet-wide."""
        t0 = time.time()
        ctx = _trace.adopt_remote(mig.ctx) \
            if mig.ctx is not None and _trace.active() \
            else contextlib.nullcontext()
        try:
            with ctx:
                self._mig_stream_ranges(mig)
                with mig.lock:
                    if mig.state != "streaming":
                        return
                    for link in mig.links.values():
                        link.submit({"op": wire.MIGRATE_FIN,
                                     "plan": mig.plan,
                                     "from_rank": mig.rank,
                                     "chunks": mig.chunks,
                                     "bytes": mig.moved_bytes}, [])
                    for link in mig.links.values():
                        link.drain()
                    mig.state = "shipped"
                if _trace.active():
                    _trace.emit_span(
                        "server.migrate.stream", t0,
                        time.time() - t0, server=self.name,
                        plan=mig.plan, chunks=mig.chunks,
                        bytes=mig.moved_bytes)
        except Exception as exc:    # noqa: BLE001 — any stream fault
            mig.error = f"{type(exc).__name__}: {exc}"  # fails the
            with mig.lock:                              # reshard, not
                if mig.state in ("begin", "streaming"):  # the server
                    mig.state = "failed"
            log.warn("server %r: reshard %r stream FAILED: %s",
                     self.name, mig.plan, mig.error)

    def _mig_stream_ranges(self, mig: _Migration) -> None:
        for tid in sorted(set(mig.dense_segs) | set(mig.kv_segs)):
            _name, _kind, spec = self._table_specs[tid]
            table = self._tables[tid]
            if tid in mig.dense_segs:
                olo, _ohi = _partition_mod.PartitionMember(
                    mig.old, mig.rank).dense_range(int(spec["size"]))
                for rcpt, seg_lo, seg_hi in mig.dense_segs[tid]:
                    pos = seg_lo
                    while pos < seg_hi:
                        hi = min(pos + _MIG_DENSE_CHUNK, seg_hi)
                        with mig.lock:
                            if mig.state != "streaming":
                                return
                            _chaos.chaos_point("reshard.handoff")
                            link = self._mig_link(mig, rcpt)
                            # re-read raw() EVERY chunk: add donates
                            # the buffer, so a cached reference goes
                            # stale under concurrent writes
                            vals = np.ascontiguousarray(
                                np.asarray(table.raw())
                                [pos - olo: hi - olo])
                            link.submit(wire.migrate_chunk_header(
                                mig.plan, table=tid, kind="dense",
                                lo=pos, hi=hi, seq=mig.next_seq(),
                                from_rank=mig.rank,
                                arrays=[vals]), [vals])
                            mig.mark_shipped(tid, pos, hi)
                            mig.chunks += 1
                            mig.moved_bytes += int(vals.nbytes)
                            self._c_mig_chunks.inc()
                            self._c_mig_bytes.inc(int(vals.nbytes))
                        self._mig_rate_sleep()
                        pos = hi
            for rcpt, blo, bhi in mig.kv_segs.get(tid, ()):
                sent = 0
                # one lock hold per donated bucket SEGMENT: the live
                # rows are enumerated and every chunk submitted before
                # any concurrent write can land between them, so
                # mark_shipped flips the whole segment atomically
                with mig.lock:
                    if mig.state != "streaming":
                        return
                    _chaos.chaos_point("reshard.handoff")
                    link = self._mig_link(mig, rcpt)
                    keys, rows = self._mig_kv_rows(table)
                    if len(keys):
                        kb = mig.old.kv_bucket(keys)
                        sel = (kb >= blo) & (kb < bhi)
                        mkeys = keys[sel]
                        mrows = rows[sel]
                        for s in range(0, len(mkeys), _MIG_KV_CHUNK):
                            ck = np.ascontiguousarray(
                                mkeys[s:s + _MIG_KV_CHUNK])
                            cv = np.ascontiguousarray(
                                mrows[s:s + _MIG_KV_CHUNK])
                            link.submit(wire.migrate_chunk_header(
                                mig.plan, table=tid, kind="kv",
                                lo=blo, hi=bhi, seq=mig.next_seq(),
                                from_rank=mig.rank,
                                arrays=[ck, cv]), [ck, cv])
                            nb = int(ck.nbytes + cv.nbytes)
                            mig.chunks += 1
                            mig.moved_bytes += nb
                            sent += 1
                            self._c_mig_chunks.inc()
                            self._c_mig_bytes.inc(nb)
                    mig.mark_shipped(tid, blo, bhi)
                self._mig_rate_sleep(max(sent, 1))

    def _mig_kv_rows(self, table) -> Tuple[np.ndarray, np.ndarray]:
        """Every live ``(key u64, value row)`` pair this shard holds.
        Tier-aware: device rows come off the live arrays; warm/cold
        rows come from the host/disk tiers' host-side records via
        ``peek`` (never faults in) — a tiered donor demotes-and-
        forwards with HBM flat."""
        from multiverso_tpu.tables import hashing as _hashing
        out_k: List[np.ndarray] = []
        out_v: List[np.ndarray] = []

        def collect(hk: np.ndarray, hv: np.ndarray) -> None:
            # hk: (..., S, 2) u32 planes; EMPTY = all-0xFFFFFFFF
            live = ~(hk == np.uint32(0xFFFFFFFF)).all(-1)
            if live.any():
                out_k.append(_hashing._join_keys(hk[live]))
                out_v.append(np.asarray(hv)[live])

        tiers = getattr(table, "tiers", None)
        if tiers is None:
            collect(np.asarray(table.keys), np.asarray(table.values))
        else:
            from multiverso_tpu.storage import manager as _tm
            slots = np.flatnonzero(np.asarray(tiers.bucket_at) >= 0)
            if len(slots):
                collect(np.asarray(table.keys)[slots],
                        np.asarray(table.values)[slots])
            for b in list(tiers.host.buckets()):
                if tiers.tier[int(b)] == _tm.TIER_HOST:
                    rec = tiers.host.peek(int(b))
                    collect(rec.keys[None], rec.values[None])
            for b in list(tiers.disk.buckets()):
                if tiers.tier[int(b)] == _tm.TIER_DISK:
                    rec = tiers.disk.peek(int(b))
                    collect(rec.keys[None], rec.values[None])
        if not out_k:
            vd = int(getattr(table, "value_dim", 0) or 0)
            return (np.zeros(0, np.uint64),
                    np.zeros((0, vd) if vd else (0,),
                             np.dtype(table.dtype)))
        return (np.concatenate(out_k),
                np.concatenate([np.asarray(v) for v in out_v],
                               axis=0))

    @staticmethod
    def _mig_set_row(bk: np.ndarray, bv: np.ndarray, k2: np.ndarray,
                     row, name: str, key: int) -> None:
        """Overwrite key ``k2``'s lane in one bucket's HOST copy
        (``bk``: (S, 2) u32, ``bv``: (S[, V])), claiming the first
        empty lane for a new key."""
        hit = np.flatnonzero((bk == k2).all(-1))
        if len(hit):
            bv[int(hit[0])] = row
            return
        empty = np.flatnonzero(
            (bk == np.uint32(0xFFFFFFFF)).all(-1))
        if not len(empty):
            raise ValueError(
                f"kv table {name!r}: migrated key {key} overflows "
                f"its bucket ({len(bk)} slots)")
        lane = int(empty[0])
        bk[lane] = k2
        bv[lane] = row

    def _mig_kv_install(self, table, hk: np.ndarray,
                        hv: np.ndarray) -> None:
        """ONE device reinstall of edited host copies (the
        kv_table.load idiom): placed to the table's shardings, with a
        generation bump so outstanding handles read superseded."""
        import jax
        table.keys = jax.device_put(hk, table._key_sharding)
        table.values = jax.device_put(
            hv.astype(table.dtype, copy=False), table._val_sharding)
        with table._option_lock:
            table.generation += 1
        table._notify_views()

    def _mig_kv_inject(self, table, keys: np.ndarray,
                       rows: np.ndarray) -> None:
        """Set-semantics install of migrated (key, value-row) pairs —
        idempotent, so a replayed chunk is harmless. Plain KV: edit
        host copies, ONE device reinstall. Tiered: each bucket is
        edited in its CURRENT tier (device slot / host arena / disk
        record / virgin→host-or-disk), so injection never inflates
        HBM either."""
        if len(keys) == 0:
            return
        from multiverso_tpu.tables import hashing as _hashing
        keys = np.ascontiguousarray(keys).astype(np.uint64,
                                                 copy=False)
        k2 = _hashing._split_keys(keys)
        tiers = getattr(table, "tiers", None)
        if tiers is None:
            hk = np.asarray(table.keys).copy()
            hv = np.asarray(table.values).copy()
            buckets = (_hashing._hash_u64(keys)
                       % np.uint64(table.num_buckets)).astype(
                           np.int64)
            for i in range(len(keys)):
                b = int(buckets[i])
                self._mig_set_row(hk[b], hv[b], k2[i], rows[i],
                                  table.name, int(keys[i]))
            self._mig_kv_install(table, hk, hv)
            return
        from multiverso_tpu.storage import manager as _tm
        logical = table._buckets_of(keys)
        order = np.argsort(logical, kind="stable")
        hk = hv = None      # device-tier host copies, installed once
        i = 0
        while i < len(order):
            b = int(logical[order[i]])
            j = i
            while j < len(order) and int(logical[order[j]]) == b:
                j += 1
            idxs = order[i:j]
            i = j
            code = int(tiers.tier[b])
            if code == _tm.TIER_DEVICE:
                if hk is None:
                    hk = np.asarray(table.keys).copy()
                    hv = np.asarray(table.values).copy()
                s = int(tiers.slot_of[b])
                for t in idxs:
                    self._mig_set_row(hk[s], hv[s], k2[t], rows[t],
                                      table.name, int(keys[t]))
                live = ~(hk[s] == np.uint32(0xFFFFFFFF)).all(-1)
                tiers._live[b] = int(live.sum())
                continue
            if code == _tm.TIER_HOST:
                rec = tiers.host.take(b)
            elif code == _tm.TIER_DISK:
                rec = tiers.disk.peek(b)
            else:   # TIER_VIRGIN
                rec = tiers.spec.empty()
            for t in idxs:
                self._mig_set_row(rec.keys, rec.values, k2[t],
                                  rows[t], table.name, int(keys[t]))
            if code == _tm.TIER_DISK:
                tiers.disk.spill(b, rec)    # re-spill overwrites the
            elif code == _tm.TIER_HOST \
                    or not tiers.host.full:  # slot in place
                tiers.host.put(b, rec)
                tiers.tier[b] = _tm.TIER_HOST
            else:
                tiers.disk.spill(b, rec)
                tiers.tier[b] = _tm.TIER_DISK
            tiers._live[b] = rec.live()
        if hk is not None:
            self._mig_kv_install(table, hk, hv)

    def _mig_commit_table(self, mig: _Migration, tid: int, st,
                          old_member, new_member) -> None:
        """Swap one table to its new-geometry staging shard: copy the
        RETAINED intersection from the live shard (the moved part
        arrived as chunks/forwards), then replace the live table and
        rebuild its read replica."""
        name, kind, spec = self._table_specs[tid]
        old_table = self._tables[tid]
        if kind == "array":
            size = int(spec["size"])
            olo, ohi = old_member.dense_range(size)
            nlo, nhi = new_member.dense_range(size)
            x, y = max(olo, nlo), min(ohi, nhi)
            if x < y:
                src = np.asarray(old_table.raw())[x - olo: y - olo]
                host = np.asarray(st.raw()).copy()
                host[x - nlo: y - nlo] = src
                st.put_raw(host)
        else:
            keys, rows = self._mig_kv_rows(old_table)
            if len(keys):
                blo, bhi = new_member.bucket_range()
                kb = mig.new.kv_bucket(keys)
                sel = (kb >= blo) & (kb < bhi)
                if sel.any():
                    self._mig_kv_inject(st, keys[sel], rows[sel])
        self._tables[tid] = st
        rep = self._replicas.pop(tid, None)
        if rep is not None:
            rep.stop()
        if kind in ("array", "kv"):
            self._replicas[tid] = TableReplica(
                st, kind, server=self.name, tid=tid,
                stream=self._fstate if self._follower else None)

    # -- table ops ---------------------------------------------------------

    def _table(self, header: Dict[str, Any]):
        tid = int(header.get("table", -1))
        table = self._tables.get(tid)
        if table is None:
            raise KeyError(f"no table {tid} on this server")
        return table

    def _table_name(self, header: Dict[str, Any]) -> str:
        try:
            tid = int(header.get("table", -1))
        except (TypeError, ValueError):
            return "?"
        t = self._tables.get(tid)
        name = getattr(t, "name", None) if t is not None else None
        return str(name) if name else (str(header.get("name"))
                                       if header.get("name") else "?")

    # -- range heat (attribution plane) -------------------------------------

    def _heat_touch_dense(self, header: Dict[str, Any], table,
                          weight: float = 1.0) -> None:
        """Attribute one dense whole-table op across the member's
        OWNED element range (the PartitionMap dense split): a
        whole-table add/get warms every owned element equally."""
        if self._attr is None:
            return
        tid = int(header.get("table", -1))
        part = self._table_parts.get(tid)
        if part is not None and "range" in part:
            lo, hi = part["range"]
        else:
            lo, hi = 0, int(getattr(table, "size", 1) or 1)
        name = self._table_name(header)
        self._attr.heat(name, "element", lo, hi) \
            .touch_span(lo, hi, weight)

    def _heat_touch_keys(self, header: Dict[str, Any],
                         keys: np.ndarray) -> None:
        """Attribute one KV op's keys into the member's owned
        splitmix64 bucket range — the SAME logical bucket space
        :class:`server.partition.PartitionMap` routes on, so fleet
        members' heat vectors concatenate into one aligned strip.
        Unpartitioned servers hash into their own heat-bucket space
        (lo=0, hi=heat_buckets) with the same splitmix64 finalizer."""
        if self._attr is None or len(keys) == 0:
            return
        name = self._table_name(header)
        if self._partition is not None:
            lo, hi = self._partition.bucket_range()
            pos = self._partition.map.kv_bucket(keys)
            heat = self._attr.heat(name, "bucket", lo, hi)
        else:
            from multiverso_tpu.tables import hashing as _hashing
            nb = self._attr.heat_buckets
            pos = _hashing._hash_u64(keys) % np.uint64(nb)
            heat = self._attr.heat(name, "bucket", 0, nb)
        span = heat.hi - heat.lo
        rel = pos.astype(np.int64) - heat.lo
        rel = rel[(rel >= 0) & (rel < span)]
        if len(rel) == 0:
            return
        idx = np.minimum(rel * heat.buckets // span, heat.buckets - 1)
        counts = np.bincount(idx, minlength=heat.buckets)
        for b in np.nonzero(counts)[0]:
            heat.counts[int(b)] += float(counts[b])

    def _op_create(self, header: Dict[str, Any],
                   force_tid: Optional[int] = None,
                   staging_ok: bool = False) -> tuple:
        name = str(header["name"])
        kind = str(header.get("kind", "array"))
        spec = dict(header.get("spec") or {})
        mig = self._migration
        if name not in self._by_name and not staging_ok \
                and mig is not None and mig.old is not None \
                and mig.state in ("begin", "streaming", "shipped"):
            # a brand-new table mid-reshard would miss the stream plan
            # (begin precomputed the donated segments from the tables
            # that existed then) — refuse, the client retries after
            # the commit. Idempotent attaches above are unaffected.
            return ({"ok": False, "retry": True, "server": self.name,
                     "error": f"reshard {mig.plan!r} in flight: "
                              "retry create after commit"}, [])
        if name in self._by_name:
            # idempotent by name: N workers all issue the same creates
            # at startup; first one builds, the rest attach
            tid = self._by_name[name]
            if force_tid is not None and force_tid != tid:
                raise ValueError(
                    f"replicated create {name!r}: primary id "
                    f"{force_tid} != local id {tid}")
            table = self._tables[tid]
        else:
            table = self._build_table(name, kind, spec)
            # a replicated create carries the PRIMARY's table id so the
            # follower's id space stays aligned (clients reuse their
            # primary handles against followers verbatim)
            tid = self._next_table if force_tid is None \
                else int(force_tid)
            if tid in self._tables:
                raise ValueError(f"table id {tid} already in use")
            self._next_table = max(self._next_table, tid + 1)
            self._tables[tid] = table
            self._by_name[name] = tid
            # the GLOBAL spec survives for migrate_begin: staging
            # shards and recipient manifests rebuild from it
            self._table_specs[tid] = (name, kind, dict(spec))
            if self._partition is not None:
                self._table_parts[tid] = self._part_info(name, kind,
                                                         spec)
            if kind in ("array", "kv"):
                # dormant until the first staleness-tolerant read;
                # tiered tables excluded (device arrays are one tier,
                # a snapshot of them would serve partial data). On a
                # follower the snapshot's staleness is measured
                # against the repl stream's noted primary generation,
                # not the local one.
                self._replicas[tid] = TableReplica(
                    table, kind, server=self.name, tid=tid,
                    stream=self._fstate if self._follower else None)
            log.info("server %r created table %d %r kind=%s", self.name,
                     tid, name, kind)
        meta = {"ok": True, "table": tid, "name": name, "kind": kind,
                "dtype": np.dtype(table.dtype).str}
        value_dim = getattr(table, "value_dim", None)
        if value_dim is not None:
            meta["value_dim"] = int(value_dim)
        size = getattr(table, "size", None)
        if size is not None:
            meta["size"] = int(size)
        return (meta, [])

    def _build_table(self, name: str, kind: str, spec: Dict[str, Any],
                     member: Any = _DEFAULT_MEMBER):
        """Instantiate a table from its GLOBAL create spec. A fleet
        member builds only its local shard: the contiguous element
        range of a dense table, or ceil(capacity/n) KV slots (the
        router never sends this rank a key it doesn't own, so local
        bucket identity is free to differ from the fleet's logical
        bucket space). ``member`` overrides the geometry — how a
        reshard builds its NEW-map staging shard while the live one
        keeps serving the old map."""
        common = {"name": name}
        for key in ("dtype", "updater"):
            if key in spec:
                common[key] = spec[key]
        if member is _DEFAULT_MEMBER:
            member = self._partition
        if kind == "array":
            from multiverso_tpu.tables.array_table import ArrayTable
            size = int(spec["size"])
            if member is not None:
                size = member.local_dense_size(size)
            return ArrayTable(size,
                              init_value=spec.get("init_value", 0),
                              **common)
        if kind == "kv":
            from multiverso_tpu.tables.kv_table import KVTable
            capacity = int(spec["capacity"])
            if member is not None:
                capacity = member.local_kv_capacity(capacity)
            return KVTable(capacity,
                           int(spec.get("value_dim", 0)), **common)
        if kind == "tiered_kv":
            from multiverso_tpu.storage.tiered_kv import TieredKVTable
            capacity = int(spec["capacity"])
            if member is not None:
                capacity = member.local_kv_capacity(capacity)
            return TieredKVTable(capacity,
                                 int(spec.get("value_dim", 0)),
                                 **common)
        raise ValueError(f"unknown table kind {kind!r} "
                         "(array | kv | tiered_kv)")

    def _part_info(self, name: str, kind: str,
                   spec: Dict[str, Any]) -> Dict[str, Any]:
        """Per-table ownership row for /statusz (what THIS rank holds
        of the global table)."""
        member = self._partition
        info: Dict[str, Any] = {"name": name, "kind": kind}
        if kind == "array":
            size = int(spec["size"])
            lo, hi = member.dense_range(size)
            info.update(size=size, range=[lo, hi], local=hi - lo)
        else:
            capacity = int(spec["capacity"])
            lo, hi = member.bucket_range()
            info.update(capacity=capacity, buckets=[lo, hi],
                        local=member.local_kv_capacity(capacity))
        return info

    @staticmethod
    def _option(header: Dict[str, Any]) -> Optional[AddOption]:
        raw = header.get("option")
        if not raw:
            return None
        fields = {k: float(raw[k]) for k in _OPTION_FIELDS if k in raw}
        return AddOption(**fields)

    def _maybe_arm_replica(self, header: Dict[str, Any]) -> None:
        """A staleness-tolerant read that reached the dispatch thread
        is a replica miss: arm the table's replica (first use) and
        kick a refresh so the NEXT one hits on the reader thread."""
        if header.get("staleness") is None:
            return
        rep = self._replicas.get(int(header.get("table", -1)))
        if rep is not None:
            rep.arm()
            rep.refresh()

    def _op_get(self, header: Dict[str, Any]) -> tuple:
        mig = self._relay_mode(header)
        if mig is not None:
            # post-commit, old-map frame: the live table is already
            # the NEW geometry — a slice would be the wrong length.
            # Structured refusal carrying the new map; the router
            # re-splits and retries (reads are idempotent).
            return (self._mig_remap_refusal(mig), [])
        table = self._table(header)
        self._maybe_arm_replica(header)
        self._heat_touch_dense(header, table)
        values = table.get()
        return ({"ok": True}, [np.ascontiguousarray(values)])

    def _op_kv_get(self, header: Dict[str, Any],
                   arrays: List[np.ndarray]) -> tuple:
        mig = self._relay_mode(header)
        if mig is not None:
            return (self._mig_remap_refusal(mig), [])
        table = self._table(header)
        self._maybe_arm_replica(header)
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        self._heat_touch_keys(header, keys)
        values, found = table.get(keys)
        return ({"ok": True}, [np.ascontiguousarray(values),
                               np.ascontiguousarray(found)])

    def _op_add(self, header: Dict[str, Any],
                arrays: List[np.ndarray],
                force_sync: bool = False,
                origin: Optional[str] = None) -> tuple:
        relay = self._relay_mode(header)
        if relay is not None:
            # post-commit, old-map WRITE: dropping it loses an update
            # the client already paid for — relay it by the new map
            # instead (apply the retained overlap, forward the moved
            # slices) and tell the client to re-split
            return self._mig_relay_add(relay, header, arrays, origin,
                                       force_sync)
        table = self._table(header)
        self._heat_touch_dense(header, table)
        # dequant-before-apply: the table layer only ever sees floats
        delta = wire.decode_delta(header.get("quant"), arrays)
        mig = self._mig_forwarding()
        if mig is None:
            handle = table.add(
                delta, self._option(header),
                sync=bool(header.get("sync")) or force_sync)
        else:
            # donor mid-reshard: apply + forward under the migration
            # lock (see _Migration) so this delta can never fall
            # between a shipped chunk and its forward
            with mig.lock:
                handle = table.add(
                    delta, self._option(header),
                    sync=bool(header.get("sync")) or force_sync)
                self._mig_forward_dense(
                    mig, int(header["table"]), np.asarray(delta),
                    header.get("option"),
                    [(origin or "?", header.get("rid"))])
        return ({"ok": True, "gen": handle.generation}, [])

    def _op_kv_add(self, header: Dict[str, Any],
                   arrays: List[np.ndarray],
                   force_sync: bool = False,
                   origin: Optional[str] = None) -> tuple:
        relay = self._relay_mode(header)
        if relay is not None:
            return self._mig_relay_kv_add(relay, header, arrays,
                                          origin, force_sync)
        table = self._table(header)
        keys = np.ascontiguousarray(arrays[0]).astype(np.uint64,
                                                      copy=False)
        self._heat_touch_keys(header, keys)
        delta = wire.decode_delta(header.get("quant"), arrays[1:])
        mig = self._mig_forwarding()
        if mig is None:
            handle = table.add(
                keys, delta, self._option(header),
                sync=bool(header.get("sync")) or force_sync)
        else:
            with mig.lock:
                handle = table.add(
                    keys, delta, self._option(header),
                    sync=bool(header.get("sync")) or force_sync)
                self._mig_forward_kv(
                    mig, int(header["table"]), keys, np.asarray(delta),
                    header.get("option"),
                    [(origin or "?", header.get("rid"))])
        return ({"ok": True, "gen": handle.generation}, [])
