"""Admission control for the wire server: who gets into the dispatch
queue, in what order, and what happens when it is full.

PR 11/12 funnel every client into ONE dispatch thread behind an
unbounded FIFO — the fast path, but also the collapse mode the
reference framework's server fleet is explicitly built to survive: one
flooding worker grows the queue without limit and every other client's
tail latency grows with it. This module is the policy half of that
story (the measurement half is the PR 7 SLO rules; the read-offload
half is the PR 12 replicas):

- **Classes** (``MVTPU_SERVER_QOS``): clients are classified by id
  into named QoS classes, each with a weighted-fair-queueing weight
  and an optional per-client token-bucket rate.
- **Weighted-fair queueing**: the dispatch queue becomes one FIFO per
  class drained by stride scheduling — each class is served in
  proportion to its weight, so a flooder saturating its own lane
  cannot starve another class's lane. Per-class order stays FIFO
  (per-connection reply order is what the client's in-order ack
  matching relies on; one client maps to one class, so its frames
  never reorder against each other).
- **Token buckets**: a class with ``rate=R`` gives every client in it
  its own bucket (``burst`` capacity, ``R`` tokens/sec refill). An
  empty bucket sheds the request with the exact time until the next
  token as the retry hint.
- **Bounded queue** (``MVTPU_SERVER_QUEUE``): with a bound of N,
  admitted-but-undispatched frames past N are shed instead of queued.
- **Shedding** is a structured reply, not a dropped connection::

      {ok: false, shed: true, retry_after_ms: <hint>, class: ..., reason: ...}

  The client transport honors it: sleep the hint, resend the IDENTICAL
  bytes (same rid — the server dedup cache still gives exactly-once
  effect), never burn reconnect-retry budget. A shed request is never
  executed and never enters the dedup cache, so shed-then-resend
  applies exactly once.
- **Degraded mode**: while mutations are being shed the server is
  *degraded* for a hold window; bounded-staleness reads arriving then
  are diverted to the replica path even when the snapshot exceeds the
  requested bound (the reply carries the real ``staleness`` and a
  ``degraded`` marker) — stale reads beat shed reads during overload.

Control ops (``hello``/``ping``/``stats``/``shutdown``) bypass buckets
and the bound and ride a priority lane: a flooded server must still
handshake, answer health probes, and shut down.

``MVTPU_SERVER_QOS`` grammar (semicolon-separated classes; the chaos
spec's shape — ``name:key=value[,key=value...]``)::

    MVTPU_SERVER_QOS = "class[;class...]"
    class            = <name>[:match=<glob>,weight=<float>,
                              rate=<float>,burst=<float>]

- ``match``  — ``fnmatch`` glob on the client id (default ``*``); the
  FIRST matching class in declaration order wins.
- ``weight`` — WFQ weight, > 0 (default 1).
- ``rate``   — per-client token refill, requests/sec (default 0 =
  unlimited, no bucket).
- ``burst``  — bucket capacity (default ``max(rate, 1)``).

Clients matching no class land in an implicit ``default`` class
(weight 1, unlimited). Example — flooders rate-limited and outweighed
8:1 by trainers::

    MVTPU_SERVER_QOS="trainers:match=w*,weight=8;bulk:weight=1,rate=200"
    MVTPU_SERVER_QUEUE=256

Malformed specs raise ``ValueError`` (a typo'd QoS spec silently
admitting everything would defeat the overload test that set it).
"""

from __future__ import annotations

import collections
import fnmatch
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.telemetry import metrics as telemetry

QOS_ENV = "MVTPU_SERVER_QOS"
QUEUE_ENV = "MVTPU_SERVER_QUEUE"

#: ops that bypass admission and ride the priority lane (a flooded
#: server must still handshake / health-check / shut down). The
#: replication plane rides here too: ``repl`` frames must keep their
#: stream order (a shed-then-resent repl create racing a later repl
#: add would misapply), and ``promote``/``adopt`` are the failover
#: path — exactly when the fleet is least healthy. The reshard plane
#: (``migrate_*``) joins for the same ordering reason: a donor's
#: chunk→forward sequence on one link must apply in link order at the
#: recipient — a shed-then-resent chunk overtaking a forward would
#: resurrect the pre-forward bytes (lost update).
CONTROL_OPS = ("hello", "ping", "stats", "shutdown",
               "repl", "promote", "adopt",
               "migrate_begin", "migrate_state", "migrate_commit",
               "migrate_abort", "migrate_manifest", "migrate_chunk",
               "migrate_fwd", "migrate_fin")

#: ops whose shed flips the server into degraded mode (reads are
#: diverted to replicas while WRITES are being shed)
MUTATING_OPS = ("add", "kv_add", "create")

#: seconds the degraded window stays open after the last write shed
DEGRADED_HOLD_S = 1.0

#: base retry hint for bound-of-queue sheds, scaled by overload factor
_QUEUE_RETRY_MS = 20.0

#: cap on distinct per-client token buckets (LRU) — same rationale as
#: the wire dedup client bound: a long-lived server must not grow
#: without limit as clients come and go
_MAX_BUCKETS = 4096


class QosClass:
    """One parsed QoS class (see module docstring for the grammar)."""

    __slots__ = ("name", "match", "weight", "_rate", "burst",
                 "_auto_burst", "__weakref__")

    def __init__(self, name: str, match: str = "*",
                 weight: float = 1.0, rate: float = 0.0,
                 burst: Optional[float] = None) -> None:
        if weight <= 0:
            raise ValueError(f"qos class {name!r}: weight must be > 0")
        if rate < 0:
            raise ValueError(f"qos class {name!r}: rate must be >= 0")
        self.name = name
        self.match = match
        self.weight = float(weight)
        self._auto_burst = burst is None
        self._rate = float(rate)
        self.burst = float(burst) if burst is not None \
            else max(self.rate, 1.0)
        if self.burst <= 0:
            raise ValueError(f"qos class {name!r}: burst must be > 0")

    @property
    def rate(self) -> float:
        return self._rate

    @rate.setter
    def rate(self, v: float) -> None:
        # runtime-mutable (control-plane binding). An auto-derived
        # burst (no explicit ``burst=`` in the spec) tracks the rate
        # BOTH ways: raising the rate must not stay starved by the old
        # capacity, and lowering it must not be masked for thousands
        # of requests by a bucket grown under the old rate. An
        # explicit burst is an operator pin: it only grows when the
        # rate is raised past it (a bucket smaller than one second of
        # refill makes no sense), never shrinks.
        self._rate = float(v)
        if getattr(self, "_auto_burst", False):
            self.burst = max(self._rate, 1.0)
            return
        burst = getattr(self, "burst", None)
        if burst is not None and self._rate > burst:
            self.burst = self._rate

    def matches(self, client_id: str) -> bool:
        return fnmatch.fnmatchcase(client_id, self.match)


def parse_qos(spec: str) -> List[QosClass]:
    """Parse a ``MVTPU_SERVER_QOS`` spec into an ordered class list
    (raises ``ValueError`` on malformed specs)."""
    classes: List[QosClass] = []
    seen = set()
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        name, _, params = raw.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"qos class {raw!r}: empty name")
        if name in seen:
            raise ValueError(f"qos class {name!r} declared twice")
        seen.add(name)
        kwargs: Dict[str, Any] = {}
        if params.strip():
            for kv in params.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise ValueError(
                        f"qos class {raw!r}: param {kv!r} is not k=v")
                k, v = kv.split("=", 1)
                k = k.strip()
                if k == "match":
                    kwargs["match"] = v.strip()
                elif k in ("weight", "rate", "burst"):
                    kwargs[k] = float(v)
                else:
                    raise ValueError(
                        f"qos class {raw!r}: unknown param {k!r} "
                        "(valid: match, weight, rate, burst)")
        classes.append(QosClass(name, **kwargs))
    return classes


def parse_queue_bound(spec: str) -> int:
    """``MVTPU_SERVER_QUEUE`` value → bound (0 = unbounded)."""
    spec = (spec or "").strip()
    if not spec:
        return 0
    bound = int(spec)
    if bound < 0:
        raise ValueError(f"{QUEUE_ENV} must be >= 0, got {bound}")
    return bound


class _Bucket:
    """One client's token bucket (lazy refill, monotonic clock)."""

    __slots__ = ("tokens", "ts")

    def __init__(self, burst: float, now: float) -> None:
        self.tokens = burst
        self.ts = now

    def take(self, rate: float, burst: float,
             now: float) -> Optional[float]:
        """Take one token. None = taken; else retry hint in ms (the
        exact time until the next token accrues)."""
        self.tokens = min(self.tokens + (now - self.ts) * rate, burst)
        self.ts = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        return max((1.0 - self.tokens) / rate * 1000.0, 1.0)


class _Lane:
    """One class's FIFO + stride-scheduling state."""

    __slots__ = ("klass", "fifo", "vpass", "admitted", "shed")

    def __init__(self, klass: QosClass) -> None:
        self.klass = klass
        self.fifo: "collections.deque" = collections.deque()
        self.vpass = 0.0        # virtual pass (stride scheduling)
        self.admitted = 0
        self.shed = 0


class AdmissionController:
    """The admission state machine + the weighted-fair dispatch queue.

    Queue-compatible surface for the dispatch thread (``get`` /
    ``get_nowait`` / ``qsize`` / ``put(None)`` sentinel), plus
    :meth:`offer` for reader threads: classify → token bucket → queue
    bound → enqueue-or-shed. One lock covers lanes, buckets, and the
    degraded clock — reader threads contend only on enqueue, which is
    deque appends and float math."""

    def __init__(self, *, qos: Optional[str] = None,
                 queue_bound: Optional[int] = None,
                 server: str = "tables") -> None:
        if qos is None:
            qos = os.environ.get(QOS_ENV, "")
        if queue_bound is None:
            queue_bound = _knobs.initial("server.queue_bound")
        self.server = server
        self.classes = parse_qos(qos)
        if not any(c.match == "*" for c in self.classes):
            # implicit catch-all so classify() is total
            self.classes.append(QosClass("default"))
        self.bound = max(int(queue_bound), 0)
        # control-plane bindings: offer() reads self.bound and the
        # class rate/weight per frame, so these are live immediately
        _knobs.bind("server.queue_bound", self, "bound", label=server)
        for c in self.classes:
            _knobs.bind("server.qos.rate", c, "rate",
                        label=f"{server}:{c.name}")
            _knobs.bind("server.qos.weight", c, "weight",
                        label=f"{server}:{c.name}")
        self._cond = threading.Condition()
        self._lanes: Dict[str, _Lane] = {
            c.name: _Lane(c) for c in self.classes}
        self._control: "collections.deque" = collections.deque()
        self._buckets: "collections.OrderedDict[str, _Bucket]" = \
            collections.OrderedDict()
        self._vtime = 0.0           # virtual clock (pass of last pop)
        self._size = 0              # data frames queued (not control)
        self._write_shed_ts = -1e18
        self._shed_total = 0
        self._expired_total = 0
        self._c_admitted = {
            c.name: telemetry.counter("server.admission.admitted",
                                      server=server, klass=c.name)
            for c in self.classes}
        self._c_shed_rate = {
            c.name: telemetry.counter("server.shed", server=server,
                                      klass=c.name, reason="rate")
            for c in self.classes}
        self._c_shed_queue = {
            c.name: telemetry.counter("server.shed", server=server,
                                      klass=c.name, reason="queue")
            for c in self.classes}
        self._c_expired = telemetry.counter("server.deadline.expired",
                                            server=server)
        self._g_degraded = telemetry.gauge("server.admission.degraded",
                                           server=server)
        telemetry.gauge("server.queue.bound",
                        server=server).set(float(self.bound))

    # -- classification / admission ----------------------------------------

    def classify(self, client_id: str) -> QosClass:
        for c in self.classes:
            if c.matches(client_id):
                return c
        return self.classes[-1]     # unreachable: catch-all exists

    def class_name(self, client_id: str,
                   header: Optional[Dict[str, Any]] = None) -> str:
        """QoS class label for one request — what the slow-request
        exemplar rows record (control ops ride the priority lane and
        report as ``"control"``)."""
        if header is not None \
                and str(header.get("op")) in CONTROL_OPS:
            return "control"
        return self.classify(client_id).name

    def offer(self, client_id: str, header: Dict[str, Any],
              item: tuple) -> Optional[Dict[str, Any]]:
        """Admit ``item`` into the fair queue (returns None) or shed it
        (returns the structured shed reply header — the caller sends it
        on the connection's writer queue; the frame never reaches the
        dispatch thread)."""
        op = str(header.get("op", "?"))
        now = time.monotonic()
        with self._cond:
            if op in CONTROL_OPS:
                self._control.append(item)
                self._cond.notify()
                return None
            lane = self._lanes[self.classify(client_id).name]
            klass = lane.klass
            retry_ms: Optional[float] = None
            reason = ""
            if klass.rate > 0:
                retry_ms = self._bucket(client_id, now).take(
                    klass.rate, klass.burst, now)
                if retry_ms is not None:
                    reason = "rate"
            if retry_ms is None and self.bound \
                    and self._size >= self.bound:
                factor = min(1.0 + self._size / self.bound, 5.0)
                retry_ms = _QUEUE_RETRY_MS * factor
                reason = "queue"
            if retry_ms is None:
                if not lane.fifo:
                    # (re)activation: no credit hoarding while idle
                    lane.vpass = max(lane.vpass, self._vtime)
                lane.fifo.append(item)
                lane.admitted += 1
                self._size += 1
                self._cond.notify()
                self._c_admitted[klass.name].inc()
                return None
            lane.shed += 1
            self._shed_total += 1
            if op in MUTATING_OPS:
                self._write_shed_ts = now
                self._g_degraded.set(1.0)
            (self._c_shed_rate if reason == "rate"
             else self._c_shed_queue)[klass.name].inc()
        return {"ok": False, "shed": True,
                "retry_after_ms": round(retry_ms, 3),
                "class": klass.name, "reason": reason,
                "error": f"shed ({reason}): class {klass.name!r} "
                         f"over capacity, retry in {retry_ms:.0f}ms"}

    def _bucket(self, client_id: str, now: float) -> _Bucket:
        b = self._buckets.get(client_id)
        if b is None:
            burst = self.classify(client_id).burst
            b = self._buckets[client_id] = _Bucket(burst, now)
            while len(self._buckets) > _MAX_BUCKETS:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(client_id)
        return b

    # -- degraded mode / bookkeeping ---------------------------------------

    def degraded(self, now: Optional[float] = None) -> bool:
        """True while the degraded window is open: a mutation was shed
        within the last :data:`DEGRADED_HOLD_S` seconds. Reader threads
        divert bounded-staleness reads to the replica path while it
        holds."""
        if now is None:
            now = time.monotonic()
        open_ = (now - self._write_shed_ts) < DEGRADED_HOLD_S
        if not open_:
            self._g_degraded.set(0.0)
        return open_

    def note_expired(self) -> None:
        """One deadline-expired frame dropped at dequeue."""
        self._expired_total += 1
        self._c_expired.inc()

    # -- queue surface (dispatch-thread side) ------------------------------

    def put(self, item) -> None:
        """Sentinel/compat enqueue (``stop()`` pushes None here). Items
        land on the priority lane unconditionally — real traffic goes
        through :meth:`offer`."""
        with self._cond:
            self._control.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            while True:
                item = self._pop_locked()
                if item is not _EMPTY:
                    return item
                if not self._cond.wait(timeout=timeout):
                    raise queue.Empty

    def get_nowait(self):
        with self._cond:
            item = self._pop_locked()
            if item is _EMPTY:
                raise queue.Empty
            return item

    def _pop_locked(self):
        if self._control:
            return self._control.popleft()
        best: Optional[_Lane] = None
        for lane in self._lanes.values():
            if lane.fifo and (best is None
                              or lane.vpass < best.vpass):
                best = lane
        if best is None:
            return _EMPTY
        self._vtime = best.vpass
        best.vpass += 1.0 / best.klass.weight
        self._size -= 1
        return best.fifo.popleft()

    def qsize(self) -> int:
        with self._cond:
            return self._size + len(self._control)

    def empty(self) -> bool:
        return self.qsize() == 0

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._cond:
            classes = [{"class": ln.klass.name,
                        "match": ln.klass.match,
                        "weight": ln.klass.weight,
                        "rate": ln.klass.rate or None,
                        "burst": ln.klass.burst
                        if ln.klass.rate else None,
                        "queued": len(ln.fifo),
                        "admitted": ln.admitted,
                        "shed": ln.shed}
                       for ln in self._lanes.values()]
            depth = self._size + len(self._control)
            shed = self._shed_total
            expired = self._expired_total
        return {"queue": {"bound": self.bound or None, "depth": depth},
                "classes": classes, "shed": shed, "expired": expired,
                "degraded": self.degraded()}


class _Empty:
    __slots__ = ()


#: internal "nothing to pop" marker (None is the shutdown sentinel)
_EMPTY = _Empty()
