"""Cross-process shard replication: delta-streamed followers.

PR 12's :class:`~multiverso_tpu.server.replica.TableReplica` broke the
read/write coupling *inside* one process; this module breaks it across
processes. Every shard in a fleet can run R replicas — one PRIMARY
that owns the dispatch queue for mutations, plus R-1 FOLLOWERS that
serve bounded-staleness ``get``/``kv_get``/range reads on their own
dispatch threads. Read throughput per shard then scales with the
number of follower processes instead of being capped by the primary's
single dispatch thread, and a primary death no longer loses the range:
the router promotes a follower (see ``client/router.py``).

The replication transport is the existing MVW1 wire, *reused end to
end* rather than reinvented:

- **The stream is the applied mutations themselves.** After the
  primary applies an ``add``/``kv_add``/``create``, the
  :class:`ReplicationTap` forwards the ORIGINAL frame — same header,
  same (already-quantized) arrays — wrapped as one ``op="repl"`` frame
  (:func:`~multiverso_tpu.server.wire.repl_wrap`). The follower runs
  the identical dequant-before-apply, so follower state is
  bit-identical to the primary's, and the bytes on the replication
  wire are the quantized delta stream (1-bit ≈ 32x smaller than a
  full-precision state sync — the ``replication_bytes_ratio`` the
  bench gates).
- **Fused groups forward as ONE pre-summed frame.** The primary's
  dispatch fusion applies K client adds as one table op; forwarding
  the K originals would triple-apply rounding and desync generation
  counts. Instead the tap ships the raw pre-summed payload with an
  ``origins`` list — 1 apply = 1 generation on both sides, bit parity
  preserved.
- **Exactly-once via the dedup cache, twice.** Each follower link is a
  real :class:`~multiverso_tpu.client.transport.WireClient`, so a
  dropped replication connection replays its unacked window and the
  follower's (client_id, rid) dedup absorbs the duplicates. The
  follower ALSO records every applied mutation under its ORIGINATING
  (client, rid) — that is the promotion replay window: after failover,
  clients resend their unacked mutations to the promoted follower, and
  anything it already applied via the stream dedups instead of
  double-applying. No acked write is lost, no replayed write applies
  twice.
- **Acks gate client acks.** The primary drains follower acks
  (:meth:`ReplicationTap.barrier`) before queueing its own client
  replies each dispatch cycle — an acked write is BY CONSTRUCTION on
  every live follower, which is what makes promotion lossless. A dead
  follower only stalls the primary for the tight replication retry
  deadline (``MVTPU_REPL_DEADLINE_S``), then its link is dropped and
  the primary moves on: replication degrades loudly
  (``replication.link_down``), it never wedges the shard.

Follower staleness is measured in generations against ``pgen`` — the
primary generation stamped on every repl frame, noted at the
follower's READER thread before the frame even queues
(:class:`FollowerState`). A follower serves a read iff
``latest_pgen - local_generation <= staleness + server.repl.slack``;
past the bound it replies ``{ok: false, stale: true}`` and the router
falls back to the primary.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiverso_tpu.server import partition as _partition
from multiverso_tpu.server import wire
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as _trace
from multiverso_tpu.utils import log


def repl_retry_policy(name: str = "repl"):
    """Link policy for primary→follower streams: far tighter than the
    client wire default — a dead follower must cost the primary a
    bounded stall (default 5s), not the 60s client deadline, because
    the barrier runs on the dispatch thread."""
    from multiverso_tpu.ft import retry as _retry
    env = os.environ.get
    return _retry.RetryPolicy(
        max_attempts=max(int(env("MVTPU_REPL_ATTEMPTS", "") or 4), 1),
        base_delay_s=0.01,
        max_delay_s=0.1,
        deadline_s=float(env("MVTPU_REPL_DEADLINE_S", "") or 5.0),
        name=name)


class ReplicationTap:
    """Primary-side delta tap: forwards applied mutations to follower
    links. Dispatch-thread-owned for all data-path methods (`forward*`
    / `barrier`); `status` may be read from the statusz thread."""

    def __init__(self, server_name: str, *,
                 member: Optional[Any] = None,
                 fleet_file: Optional[str] = None,
                 replicate_to: Optional[Sequence[str]] = None) -> None:
        self.server = server_name
        self._member = member
        self._fleet_file = fleet_file
        self._static = list(replicate_to) if replicate_to else None
        self._claim = member.map.to_wire() if member is not None \
            else None
        self._lock = threading.Lock()
        self._links: List[Any] = []
        self._pending = False
        self._dead = False          # no followers configured: stay off
        self._next_arm = 0.0
        # plain ints mirror the counters so status() needs no registry
        self.frames = 0
        self.bytes = 0              # encoded bytes on the repl wire
        self.full_bytes = 0         # what a full-precision sync costs
        self.drops = 0
        self._c_frames = telemetry.counter("replication.frames",
                                           server=server_name)
        self._c_bytes = telemetry.counter("replication.bytes",
                                          server=server_name)
        self._c_full = telemetry.counter("replication.full_bytes",
                                         server=server_name)
        self._c_drops = telemetry.counter("replication.link_down",
                                          server=server_name)
        self._g_links = telemetry.gauge("replication.links",
                                        server=server_name)

    # -- link management ----------------------------------------------------

    def _resolve_addresses(self) -> Optional[List[str]]:
        """Follower addresses: the explicit override, else this rank's
        ``replicas`` rows in the fleet file. ``None`` = can't tell yet
        (fleet file not written); ``[]`` = definitively no followers."""
        if self._static is not None:
            return list(self._static)
        if not self._fleet_file or self._member is None:
            return []
        doc = _partition.read_fleet_file(self._fleet_file)
        if not doc:
            return None
        for row in doc.get("members", ()):
            if int(row.get("rank", -1)) == self._member.rank:
                return [str(rep["addresses"][0])
                        for rep in row.get("replicas", ())
                        if rep.get("addresses")]
        return []

    def _live_links(self) -> List[Any]:
        """Arm lazily on the first forward (the fleet file — which
        names the followers — is only written once every member is up).
        Backed off so an unreachable follower doesn't turn every write
        into a dial attempt."""
        if self._links or self._dead:
            return self._links
        now = time.monotonic()
        if now < self._next_arm:
            return self._links
        self._next_arm = now + 0.5
        addrs = self._resolve_addresses()
        if addrs is None:
            return self._links
        if not addrs:
            self._dead = True
            return self._links
        links = []
        for addr in addrs:
            try:
                links.append(self._dial(addr))
            except Exception as exc:    # noqa: BLE001 — a follower
                self.drops += 1         # that never came up is a drop
                self._c_drops.inc()
                log.warn("replication %r: follower %s unreachable "
                         "at arm: %s", self.server, addr, exc)
        with self._lock:
            self._links = links
        self._g_links.set(float(len(links)))
        if links:
            log.info("replication %r: streaming to %d follower(s)",
                     self.server, len(links))
        return links

    def _dial(self, addr: str):
        from multiverso_tpu.client import transport as _transport
        return _transport.WireClient(
            addr, client=f"repl:{self.server}", quant=None,
            retry_policy=repl_retry_policy(), deadline_s=None,
            partition=dict(self._claim) if self._claim else None)

    def _drop(self, link: Any, exc: BaseException) -> None:
        self.drops += 1
        self._c_drops.inc()
        log.warn("replication %r: dropping follower link %s: %s",
                 self.server, link.address, exc)
        try:
            link.abort()
        except Exception:   # noqa: BLE001
            pass
        with self._lock:
            self._links = [x for x in self._links if x is not link]
        self._g_links.set(float(len(self._links)))

    def update_claim(self, wire_map: Dict[str, Any]) -> None:
        """Adopt a bumped partition map (post-promotion): future link
        reconnect hellos must claim the new version or the follower
        refuses them."""
        self._claim = dict(wire_map)
        for link in list(self._links):
            link.partition = dict(wire_map)

    # -- the tap ------------------------------------------------------------

    def forward(self, client_id: str, header: Dict[str, Any],
                arrays: Sequence[np.ndarray],
                reply_header: Dict[str, Any]) -> None:
        """Forward one UNFUSED applied mutation verbatim: the follower
        decodes the identical bytes (same quant meta, same EF'd
        payload), so its apply is bit-identical to the primary's."""
        links = self._live_links()
        if not links:
            return
        op = str(header.get("op", "?"))
        tid = reply_header.get("table") if op == "create" else None
        wrapped = wire.repl_wrap(header, origin=client_id,
                                 pgen=reply_header.get("gen"), tid=tid)
        if op == "kv_add" and arrays:
            full = int(np.asarray(arrays[0]).nbytes) \
                + wire.decoded_nbytes(header.get("quant"), arrays[1:])
        else:
            full = wire.decoded_nbytes(header.get("quant"), arrays)
        self._send(wrapped, list(arrays), full, header)

    def forward_fused(self, op: str, tid: int,
                      arrays: Sequence[np.ndarray], *,
                      origins: Sequence[Tuple[str, Any]],
                      pgen: Optional[int],
                      option: Optional[Dict[str, Any]] = None) -> None:
        """Forward a FUSED group as its single pre-summed apply (dense:
        the summed delta; kv: unique keys + summed rows) so follower
        generation count and float rounding match the primary exactly.
        ``origins`` carries every (client, rid) the group absorbed for
        the promotion replay window."""
        links = self._live_links()
        if not links:
            return
        orig: Dict[str, Any] = {"op": op, "table": int(tid)}
        if option is not None:
            orig["option"] = option
        wrapped = wire.repl_wrap(orig, origin=str(origins[0][0]),
                                 pgen=pgen, origins=origins)
        arrays = [np.ascontiguousarray(a) for a in arrays]
        full = sum(int(a.nbytes) for a in arrays)
        self._send(wrapped, arrays, full, orig)

    def _send(self, wrapped: Dict[str, Any],
              arrays: List[np.ndarray], full: int,
              orig_header: Dict[str, Any]) -> None:
        payload = sum(int(np.asarray(a).nbytes) for a in arrays)
        t0 = time.time()
        sent = False
        for link in list(self._links):
            try:
                link.submit(wrapped, arrays)
                sent = True
            except Exception as exc:    # noqa: BLE001
                self._drop(link, exc)
        if not sent:
            return
        self._pending = True
        self.frames += 1
        self.bytes += payload
        self.full_bytes += max(int(full), payload)
        self._c_frames.inc()
        self._c_bytes.inc(payload)
        self._c_full.inc(max(int(full), payload))
        ctx = wire.trace_ctx(orig_header)
        if ctx is not None and _trace.active():
            with _trace.adopt_remote(ctx):
                _trace.emit_span("server.repl.forward", t0,
                                 time.time() - t0, server=self.server,
                                 op=str(orig_header.get("op", "?")),
                                 followers=len(self._links),
                                 bytes=payload)

    def barrier(self) -> None:
        """Drain follower acks for everything forwarded this dispatch
        cycle — runs BEFORE the primary queues its client replies, so
        an acked write is on every live follower. No-op when nothing
        was forwarded (R=1 pays nothing)."""
        if not self._pending:
            return
        self._pending = False
        for link in list(self._links):
            try:
                link.drain()
            except Exception as exc:    # noqa: BLE001
                self._drop(link, exc)

    # -- lifecycle / observability -------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            links = list(self._links)
        return {"role": "primary",
                "links": [{"address": x.address,
                           "tx_bytes": x.tx_bytes,
                           "reconnects": x.reconnects}
                          for x in links],
                "frames": self.frames, "bytes": self.bytes,
                "full_bytes": self.full_bytes, "drops": self.drops,
                "bytes_ratio": round(self.full_bytes
                                     / self.bytes, 3)
                if self.bytes else None}

    def close(self) -> None:
        for link in list(self._links):
            try:
                link.abort()
            except Exception:   # noqa: BLE001
                pass
        with self._lock:
            self._links = []


class FollowerState:
    """Follower-side staleness ledger. ``note`` runs on READER threads
    (per repl frame, before it queues) so the staleness reference can
    never run behind what the stream has delivered; ``lag`` and
    ``applied`` run on the follower's dispatch thread."""

    def __init__(self, server_name: str) -> None:
        self.server = server_name
        self._lock = threading.Lock()
        self._latest: Dict[int, int] = {}   # tid -> newest pgen seen
        self.frames = 0
        self.applies = 0
        self._c_applies = telemetry.counter("replication.applies",
                                            server=server_name)
        self._g_lag = telemetry.gauge("replication.lag_gen",
                                      server=server_name)

    def note(self, header: Dict[str, Any]) -> None:
        """Record a repl frame's primary generation at intake."""
        try:
            orig, _, pgen, tid = wire.repl_unwrap(header)
        except Exception:   # noqa: BLE001 — malformed frames fail
            return          # loudly at dispatch, not here
        with self._lock:
            self.frames += 1
            if pgen is None:
                return
            t = tid if tid is not None else orig.get("table")
            if t is None:
                return
            t = int(t)
            if pgen > self._latest.get(t, 0):
                self._latest[t] = pgen

    def applied(self, tid: int, local_gen: int) -> None:
        self.applies += 1
        self._c_applies.inc()
        self._g_lag.set(float(self.lag(tid, local_gen)))

    def lag(self, tid: int, local_gen: int) -> int:
        """Generations this follower lags the newest pgen the stream
        has delivered for ``tid`` (0 for a table with no stream yet —
        nothing acked can be missing from it)."""
        with self._lock:
            return max(self._latest.get(int(tid), 0) - int(local_gen),
                       0)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            latest = dict(self._latest)
        return {"role": "follower", "frames": self.frames,
                "applies": self.applies,
                "latest_pgen": {str(k): v for k, v in latest.items()}}
