"""``python -m multiverso_tpu.server``: run one table-server process —
or launch a sharded fleet of N of them.

The process half of the reference's ``multiverso server`` role: init
the runtime (mesh, chaos-from-env, statusz), serve the wire address
until SIGTERM/SIGINT, then drain. With ``--fleet N`` this process
becomes a LAUNCHER instead: it spawns N member processes (rank r
listens on rank-derived addresses, owns partition r of every table per
``server/partition.py``), waits for every member's ready file, then
writes one fleet file naming the whole fleet — addresses, statusz
ports, pids, and the authoritative partition map — which
``client/router.py``'s ``connect_fleet_file`` and the
``/statusz?fleet=1`` aggregator both consume.

Flags:

``--address unix:/path | tcp:host:port | shm:///path [, ...]``
    wire address(es) to listen on, comma-separated (default
    ``unix:/tmp/mvtpu.sock``; ``tcp:host:0`` picks an ephemeral port —
    see ``--ready-file``; ``shm://`` serves the shared-memory ring
    transport, falling back to socket frames per connection for
    clients that dial it as plain unix).
``--name NAME``
    server name for logs/telemetry (default ``tables``).
``--fuse K``
    drain + fuse up to K queued frames per dispatch cycle (default:
    ``MVTPU_SERVER_FUSE`` env, else 1 = off).
``--qos SPEC``
    admission QoS classes (default: ``MVTPU_SERVER_QOS`` env, else
    none — every client in one unlimited class). See
    ``server/admission.py`` for the grammar.
``--queue N``
    bound on admitted-but-undispatched frames; excess load is shed
    with a retry-after reply (default: ``MVTPU_SERVER_QUEUE`` env,
    else 0 = unbounded).
``--ready-file PATH``
    after binding, atomically write the RESOLVED dialable address list
    here (comma-separated, same order as ``--address``). The launcher
    (``benchmarks/serving_mp.py``, ``make mp-smoke``) polls this file
    instead of racing the bind — and it is how an ephemeral tcp port
    gets back to the workers. Under ``--fleet`` the launcher's ready
    file is the fleet file itself (JSON, ``mvtpu.fleet.v1``).

Fleet flags:

``--fleet N``
    launcher mode: spawn N member processes. Rank r's addresses derive
    from ``--address`` (unix/shm paths gain a ``.r`` suffix; an
    explicit tcp port becomes port+r, an ephemeral ``:0`` stays
    ephemeral). Members get statusz armed (ephemeral) unless
    ``MVTPU_STATUSZ_PORT`` is already set, so ``?fleet=1`` aggregation
    works out of the box. SIGTERM/SIGINT forward to every member; one
    member dying does NOT take the rest down (a partition outage is
    partial by design — the launcher keeps the survivors).
``--fleet-file PATH``
    where the fleet file lands (default: ``--ready-file``, else
    ``<first unix/shm path>.fleet.json``).
``--fleet-version V``
    partition-map version claimed by every member (default 1).
``--kv-buckets B``
    logical KV bucket space (default 8192, rounded up to a multiple
    of N).
``--fleet-rank R`` / ``--fleet-n N``
    internal: member mode (set by the launcher).
``--replicas R``
    replication factor per rank (default 1 = no followers). R-1
    FOLLOWER processes spawn next to each rank's primary (unix/shm
    paths gain a ``fJ`` suffix; explicit tcp ports offset by ``n*J``),
    listed under the member's ``replicas`` row in the fleet file. The
    primary streams applied deltas to them (``server/replication.py``)
    and the router load-balances bounded-staleness reads across the
    replica set, promoting a follower if the primary dies.
``--replica-of RANK`` / ``--replica-idx J``
    internal: follower member mode (set by the launcher).
``--replicate-to ADDR[,ADDR...]``
    internal: static follower address override for this member's
    replication tap (set by ``--grow`` for the joining member, whose
    followers are not in the fleet file until the reshard commits).

Admin ops (run against a LIVE fleet, addressed by ``--fleet-file``):

``--grow``
    online reshard v→v+1 with N+1 members: spawn the joining member
    (rank N; addresses derive from ``--address`` exactly like the
    launcher, so pass the same base), drive ``migrate_begin`` on every
    existing member, poll until every donor has streamed its moved
    ranges, commit donors-first, rewrite the fleet file atomically,
    and print a one-line JSON summary. On any failure or timeout
    (``MVTPU_RESHARD_TIMEOUT_S``, default 120) the abort wave rolls
    every member back to v — the fleet keeps serving throughout.
``--shrink``
    the reverse: evict rank N-1 (its ranges stream to the survivors),
    commit, rewrite the fleet file with N-1 members, linger
    ``MVTPU_SHRINK_LINGER_S`` (default 2s) so stale clients get their
    writes relayed + a remap hint, then shut the evicted member down.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _rank_address(addr: str, rank: int) -> str:
    """Rank-derive one listen address (see module docstring)."""
    addr = addr.strip()
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        p = int(port or 0)
        return f"tcp:{host}:{p + rank if p else 0}"
    return f"{addr}.{rank}"


def _replica_address(addr: str, rank: int, n: int, idx: int) -> str:
    """Follower idx (1-based) of rank's listen address: path suffix
    ``.RfJ``; explicit tcp ports offset by ``n*J`` past the primary
    block so primaries and followers never collide."""
    addr = addr.strip()
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        p = int(port or 0)
        return f"tcp:{host}:{p + rank + n * idx if p else 0}"
    return f"{addr}.{rank}f{idx}"


def _write_ready(path: str, content: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(content)
    os.replace(tmp, path)


def _member_main(args, server_cls, partition) -> int:
    """One fleet member (or a plain standalone server when no
    partition flags are set)."""
    from multiverso_tpu import core

    member = None
    if args.fleet_n:
        pmap = partition.PartitionMap(args.fleet_n,
                                      version=args.fleet_version,
                                      kv_buckets=args.kv_buckets,
                                      replicas=args.replicas or 1)
        member = partition.PartitionMember(pmap, args.fleet_rank)
    core.init()
    follower = args.replica_idx is not None
    replicate_to = [a.strip() for a
                    in str(args.replicate_to or "").split(",")
                    if a.strip()] or None
    server = server_cls(args.address, name=args.name, fuse=args.fuse,
                        qos=args.qos, queue_bound=args.queue,
                        partition=member, fleet_file=args.fleet_file,
                        follower=follower,
                        replica_idx=args.replica_idx,
                        replicate_to=replicate_to)
    bound = server.start()

    if args.ready_file:
        ready = bound
        from multiverso_tpu.telemetry import statusz
        http = statusz.server()
        if http is not None:
            # the launcher lifts this into the fleet file; ?fleet=1
            # scrapes peers through it
            ready += f",statusz:{http.port}"
        _write_ready(args.ready_file, ready)

    def _stop(signum, frame):
        server.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        server.serve_forever()
    finally:
        server.stop()
        core.shutdown()
    return 0


def _fleet_main(args, partition) -> int:
    """Launcher: N member processes + one fleet file."""
    n = int(args.fleet)
    r = max(int(args.replicas or 1), 1)
    pmap = partition.PartitionMap(n, version=args.fleet_version,
                                  kv_buckets=args.kv_buckets,
                                  replicas=r)
    addresses = [a.strip() for a in str(args.address).split(",")
                 if a.strip()]
    fleet_file = args.fleet_file or args.ready_file
    if not fleet_file:
        stem = next((a.split(":", 1)[1].lstrip("/") for a in addresses
                     if a.startswith(("unix:", "shm:"))), None)
        fleet_file = ("/" + stem if stem else "/tmp/mvtpu") \
            + ".fleet.json"

    env = dict(os.environ)
    env.setdefault("MVTPU_STATUSZ_PORT", "0")
    # one spec per process: rank's primary (idx None) then its
    # followers (idx 1..R-1), all partition-member rank — a follower
    # sizes its shard exactly like its primary
    specs = []
    for rank in range(n):
        specs.append((rank, None,
                      [_rank_address(a, rank) for a in addresses]))
        for idx in range(1, r):
            specs.append((rank, idx,
                          [_replica_address(a, rank, n, idx)
                           for a in addresses]))
    procs, ready_files = [], []
    for rank, idx, addrs in specs:
        tag = f"r{rank}" if idx is None else f"r{rank}f{idx}"
        ready = f"{fleet_file}.{tag}.ready"
        try:
            os.unlink(ready)
        except OSError:
            pass
        ready_files.append(ready)
        name = f"{args.name}-{rank}" if idx is None \
            else f"{args.name}-{rank}f{idx}"
        cmd = [sys.executable, "-m", "multiverso_tpu.server",
               "--address", ",".join(addrs),
               "--name", name,
               "--ready-file", ready,
               "--fleet-rank", str(rank), "--fleet-n", str(n),
               "--fleet-version", str(args.fleet_version),
               "--fleet-file", fleet_file,
               "--replicas", str(r)]
        if idx is not None:
            cmd += ["--replica-of", str(rank),
                    "--replica-idx", str(idx)]
        if args.kv_buckets:
            cmd += ["--kv-buckets", str(args.kv_buckets)]
        if args.fuse is not None:
            cmd += ["--fuse", str(args.fuse)]
        if args.qos is not None:
            cmd += ["--qos", args.qos]
        if args.queue is not None:
            cmd += ["--queue", str(args.queue)]
        procs.append(subprocess.Popen(cmd, env=env))

    def _kill_all(sig=signal.SIGTERM):
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass

    # every process ready — primaries AND followers — before the
    # fleet file exists (clients and the primaries' replication taps
    # both gate on it, so nothing dials a follower that isn't up)
    members = {}
    deadline = time.monotonic() + float(
        os.environ.get("MVTPU_FLEET_STARTUP_S", "") or 60.0)
    for i, (rank, idx, _addrs) in enumerate(specs):
        ready = ready_files[i]
        tag = f"{rank}" if idx is None else f"{rank} follower {idx}"
        while not os.path.exists(ready):
            rc = procs[i].poll()
            if rc is not None:
                print(f"fleet member {tag} exited rc={rc} before "
                      "ready", file=sys.stderr)
                _kill_all()
                return 1
            if time.monotonic() > deadline:
                print(f"fleet member {tag} not ready in time",
                      file=sys.stderr)
                _kill_all()
                return 1
            time.sleep(0.02)
        with open(ready) as f:
            parts = [p for p in f.read().strip().split(",") if p]
        statusz_port = next(
            (int(p.split(":", 1)[1]) for p in parts
             if p.startswith("statusz:")), None)
        row = {"name": f"{args.name}-{rank}" if idx is None
               else f"{args.name}-{rank}f{idx}",
               "addresses": [p for p in parts
                             if not p.startswith("statusz:")],
               "statusz_port": statusz_port, "pid": procs[i].pid}
        if idx is None:
            row["rank"] = rank
            row["replicas"] = []
            members[rank] = row
        else:
            row["idx"] = idx
            members[rank]["replicas"].append(row)
    members = [members[rank] for rank in range(n)]

    partition.write_fleet_file(fleet_file, pmap, members)
    if args.ready_file and args.ready_file != fleet_file:
        with open(fleet_file) as f:
            _write_ready(args.ready_file, f.read())
    print(f"fleet of {n} x{r} up; fleet file {fleet_file}",
          flush=True)

    stopping = []

    def _stop(signum, frame):
        stopping.append(signum)
        _kill_all()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    # a member dying alone is a PARTIAL outage, not fleet shutdown:
    # keep waiting on the rest (the bench SIGKILLs rank 0 and asserts
    # rank 1 still serves through exactly this launcher)
    rcs = [p.wait() for p in procs]
    if stopping:
        return 0
    return 0 if all(rc == 0 for rc in rcs) else 1


def _reshard_summary(ok: bool, **fields) -> int:
    import json
    print(json.dumps({"ok": ok, **fields}), flush=True)
    return 0 if ok else 1


def _reshard_main(args, partition, grow: bool) -> int:
    """Admin driver for one online reshard (``--grow``/``--shrink``):
    begin on every existing member, poll donors to "shipped", commit
    donors-first, rewrite the fleet file. Any failure or timeout turns
    into an abort wave — v keeps serving, bit-exactly."""
    import json

    from multiverso_tpu.client import transport as _transport
    from multiverso_tpu.telemetry import trace as _trace

    mode = "grow" if grow else "shrink"
    fleet_file = args.fleet_file or args.ready_file
    if not fleet_file:
        print("--grow/--shrink need --fleet-file", file=sys.stderr)
        return 2
    doc = partition.read_fleet_file(fleet_file)
    if doc is None:
        print(f"no fleet file at {fleet_file}", file=sys.stderr)
        return 2
    old_map = partition.PartitionMap.from_wire(doc["map"])
    n, v = old_map.n, old_map.version
    new_n = n + 1 if grow else n - 1
    if new_n < 1:
        print(f"cannot shrink a fleet of {n}", file=sys.stderr)
        return 2
    r = max(int(old_map.replicas or 1), 1)
    new_map = partition.PartitionMap(
        new_n, version=v + 1, kv_buckets=old_map.kv_buckets,
        replicas=r)
    rows = sorted(doc.get("members", ()),
                  key=lambda m: int(m.get("rank", 0)))
    if len(rows) != n:
        print(f"fleet file lists {len(rows)} members for a map of "
              f"{n}", file=sys.stderr)
        return 2
    plan = f"{mode}-v{v}to{v + 1}-{os.getpid()}-{int(time.time())}"
    t0 = time.monotonic()
    timeout_s = float(
        os.environ.get("MVTPU_RESHARD_TIMEOUT_S", "") or 120.0)

    # -- grow: spawn the joining member (+ its followers) first, so
    # donors have somewhere to stream the moment begin lands
    procs, new_row = [], None
    addresses = [a.strip() for a in str(args.address).split(",")
                 if a.strip()]
    if grow:
        env = dict(os.environ)
        env.setdefault("MVTPU_STATUSZ_PORT", "0")
        fol_addrs = [[_replica_address(a, n, new_n, idx)
                      for a in addresses] for idx in range(1, r)]
        specs = [(None, [_rank_address(a, n) for a in addresses])] \
            + list(zip(range(1, r), fol_addrs))
        ready_files = []
        for idx, addrs in specs:
            tag = f"r{n}" if idx is None else f"r{n}f{idx}"
            ready = f"{fleet_file}.{tag}.ready"
            try:
                os.unlink(ready)
            except OSError:
                pass
            ready_files.append(ready)
            name = f"{args.name}-{n}" if idx is None \
                else f"{args.name}-{n}f{idx}"
            cmd = [sys.executable, "-m", "multiverso_tpu.server",
                   "--address", ",".join(addrs),
                   "--name", name, "--ready-file", ready,
                   "--fleet-rank", str(n), "--fleet-n", str(new_n),
                   "--fleet-version", str(v + 1),
                   "--fleet-file", fleet_file,
                   "--replicas", str(r),
                   "--kv-buckets", str(old_map.kv_buckets)]
            if idx is not None:
                cmd += ["--replica-of", str(n),
                        "--replica-idx", str(idx)]
            elif fol_addrs:
                # the fleet file is still at v (no rank-N row), so the
                # joining member's tap would latch "no followers" —
                # hand it its follower addresses explicitly
                cmd += ["--replicate-to",
                        ",".join(a[0] for a in fol_addrs)]
            # the member outlives this admin: detach it from our
            # stdio too, or a pipe-capturing caller of --grow waits
            # forever for EOF the daemon never sends
            mlog = open(f"{fleet_file}.{tag}.log", "ab")
            try:
                procs.append(subprocess.Popen(
                    cmd, env=env, start_new_session=True,
                    stdin=subprocess.DEVNULL, stdout=mlog,
                    stderr=mlog))
            finally:
                mlog.close()
        deadline = time.monotonic() + timeout_s
        ready_parts = []
        for i, ready in enumerate(ready_files):
            while not os.path.exists(ready):
                if procs[i].poll() is not None \
                        or time.monotonic() > deadline:
                    for p in procs:
                        if p.poll() is None:
                            p.terminate()
                    return _reshard_summary(
                        False, op=mode, plan=plan,
                        error="joining member failed to start",
                        elapsed_s=round(time.monotonic() - t0, 3))
                time.sleep(0.02)
            with open(ready) as f:
                ready_parts.append(
                    [p for p in f.read().strip().split(",") if p])

        def _row(i, idx):
            parts = ready_parts[i]
            port = next((int(p.split(":", 1)[1]) for p in parts
                         if p.startswith("statusz:")), None)
            return {"name": f"{args.name}-{n}" if idx is None
                    else f"{args.name}-{n}f{idx}",
                    "addresses": [p for p in parts
                                  if not p.startswith("statusz:")],
                    "statusz_port": port, "pid": procs[i].pid}
        new_row = _row(0, None)
        new_row.update(rank=n, replicas=[
            dict(_row(i, idx), idx=idx)
            for i, (idx, _a) in enumerate(specs) if idx is not None])

    # recipients every donor may dial: all ranks of the NEW map
    member_addrs = {int(m["rank"]): str(m["addresses"][0])
                    for m in rows if int(m["rank"]) < new_n}
    if new_row is not None:
        member_addrs[n] = str(new_row["addresses"][0])

    links = {}

    def _link(rank, addr):
        if rank not in links:
            links[rank] = _transport.WireClient(
                addr, client="reshard-admin", quant=None)
        return links[rank]

    def _close_all():
        for c in links.values():
            try:
                c.close()
            except Exception:   # noqa: BLE001
                pass

    def _abort(reason, states=None):
        for m in rows:
            try:
                _link(int(m["rank"]), str(m["addresses"][0])).call(
                    "migrate_abort", {"plan": plan, "reason": reason})
            except Exception:   # noqa: BLE001 — best-effort rollback
                pass
        for p in procs:
            if p.poll() is None:
                p.terminate()
        _close_all()
        return _reshard_summary(
            False, op=mode, plan=plan, error=reason,
            states=states or {},
            elapsed_s=round(time.monotonic() - t0, 3))

    with _trace.request(f"reshard.{mode}", plan=plan,
                        from_version=v, to_version=v + 1):
        # -- begin wave (existing members only: the joining member is
        # born at v+1 and learns its tables from donor manifests)
        donors = set()
        for m in rows:
            rank = int(m["rank"])
            try:
                reply, _ = _link(rank, str(m["addresses"][0])).call(
                    "migrate_begin",
                    {"plan": plan, "map": new_map.to_wire(),
                     "members": member_addrs})
            except Exception as exc:    # noqa: BLE001
                return _abort(f"begin at rank {rank} failed: {exc}")
            if reply.get("donor"):
                donors.add(rank)

        # -- poll donors until every moved range is streamed
        deadline = time.monotonic() + timeout_s
        while True:
            states = {}
            for m in rows:
                rank = int(m["rank"])
                try:
                    st, _ = _link(rank,
                                  str(m["addresses"][0])).call(
                        "migrate_state", {"plan": plan})
                except Exception as exc:    # noqa: BLE001
                    return _abort(
                        f"state poll at rank {rank} failed: {exc}")
                states[rank] = st
            if any(s.get("state") in ("failed", "aborted")
                   for s in states.values()):
                bad = {r_: s for r_, s in states.items()
                       if s.get("state") in ("failed", "aborted")}
                return _abort(
                    "stream failed: " + "; ".join(
                        f"rank {r_}: {s.get('error')}"
                        for r_, s in bad.items()),
                    {r_: s.get("state")
                     for r_, s in states.items()})
            if all(states[r_].get("state") == "shipped"
                   for r_ in states):
                break
            if time.monotonic() > deadline:
                return _abort(
                    f"reshard timed out after {timeout_s}s",
                    {r_: s.get("state") for r_, s in states.items()})
            time.sleep(0.05)
        moved_bytes = sum(int(s.get("moved_bytes") or 0)
                          for s in states.values())
        chunks = sum(int(s.get("chunks") or 0)
                     for s in states.values())
        forwards = sum(int(s.get("forwards") or 0)
                       for s in states.values())

        # -- commit wave: donors FIRST (sequential — each donor drains
        # its links under the migration lock before flipping), then
        # the rest, then the joining member if it staged anything
        order = [r_ for r_ in sorted(states) if r_ in donors] \
            + [r_ for r_ in sorted(states) if r_ not in donors]
        for rank in order:
            try:
                reply, _ = _link(
                    rank, member_addrs.get(
                        rank, str(rows[rank]["addresses"][0]))).call(
                    "migrate_commit", {"plan": plan})
            except Exception as exc:    # noqa: BLE001
                return _abort(f"commit at rank {rank} failed: {exc}")
            if not reply.get("ok"):
                return _abort(f"commit at rank {rank} refused: "
                              f"{reply.get('error')}")
        if grow:
            try:
                c = _link(n, member_addrs[n])
                st, _ = c.call("migrate_state", {"plan": plan})
                if st.get("state") not in ("idle",):
                    c.call("migrate_commit", {"plan": plan})
            except Exception as exc:    # noqa: BLE001
                return _abort(f"commit at joining rank failed: "
                              f"{exc}")

    # -- flip the fleet file atomically to v+1
    if grow:
        members = rows + [new_row]
    else:
        members = [m for m in rows if int(m["rank"]) < new_n]
    partition.write_fleet_file(fleet_file, new_map, members)

    evicted_pid = None
    if not grow:
        # linger so stale clients hit the relay path (their writes
        # forward to the survivors + they get the remap hint), then
        # retire the evicted member and its followers
        time.sleep(float(
            os.environ.get("MVTPU_SHRINK_LINGER_S", "") or 2.0))
        ev = rows[-1]
        evicted_pid = ev.get("pid")
        for addr in [str(ev["addresses"][0])] + [
                str(rep["addresses"][0])
                for rep in ev.get("replicas", ())
                if rep.get("addresses")]:
            try:
                _transport.WireClient(
                    addr, client="reshard-admin",
                    quant=None).call("shutdown", {})
            except Exception:   # noqa: BLE001 — already gone is fine
                pass
    _close_all()
    return _reshard_summary(
        True, op=mode, plan=plan, from_version=v, to_version=v + 1,
        n_from=n, n_to=new_n, moved_bytes=moved_bytes, chunks=chunks,
        forwards=forwards, evicted_pid=evicted_pid,
        joined_pid=procs[0].pid if procs else None,
        elapsed_s=round(time.monotonic() - t0, 3))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.server",
        description="multiverso_tpu table-server process / fleet "
                    "launcher")
    parser.add_argument("--address", default="unix:/tmp/mvtpu.sock")
    parser.add_argument("--name", default="tables")
    parser.add_argument("--fuse", type=int, default=None)
    parser.add_argument("--qos", default=None)
    parser.add_argument("--queue", type=int, default=None)
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--fleet", type=int, default=None)
    parser.add_argument("--fleet-file", default=None)
    parser.add_argument("--fleet-version", type=int, default=1)
    parser.add_argument("--kv-buckets", type=int, default=None)
    parser.add_argument("--fleet-rank", type=int, default=0)
    parser.add_argument("--fleet-n", type=int, default=0)
    parser.add_argument("--replicas", type=int, default=1)
    parser.add_argument("--replica-of", type=int, default=None)
    parser.add_argument("--replica-idx", type=int, default=None)
    parser.add_argument("--replicate-to", default=None)
    parser.add_argument("--grow", action="store_true")
    parser.add_argument("--shrink", action="store_true")
    args = parser.parse_args(argv)

    from multiverso_tpu.server import partition

    if args.grow or args.shrink:
        return _reshard_main(args, partition, grow=bool(args.grow))
    if args.fleet:
        return _fleet_main(args, partition)

    from multiverso_tpu.server.table_server import TableServer
    return _member_main(args, TableServer, partition)


if __name__ == "__main__":
    sys.exit(main())
