"""``python -m multiverso_tpu.server``: run one table-server process.

The process half of the reference's ``multiverso server`` role: init
the runtime (mesh, chaos-from-env, statusz), serve the wire address
until SIGTERM/SIGINT, then drain.

Flags:

``--address unix:/path | tcp:host:port | shm:///path [, ...]``
    wire address(es) to listen on, comma-separated (default
    ``unix:/tmp/mvtpu.sock``; ``tcp:host:0`` picks an ephemeral port —
    see ``--ready-file``; ``shm://`` serves the shared-memory ring
    transport, falling back to socket frames per connection for
    clients that dial it as plain unix).
``--name NAME``
    server name for logs/telemetry (default ``tables``).
``--fuse K``
    drain + fuse up to K queued frames per dispatch cycle (default:
    ``MVTPU_SERVER_FUSE`` env, else 1 = off).
``--qos SPEC``
    admission QoS classes (default: ``MVTPU_SERVER_QOS`` env, else
    none — every client in one unlimited class). See
    ``server/admission.py`` for the grammar.
``--queue N``
    bound on admitted-but-undispatched frames; excess load is shed
    with a retry-after reply (default: ``MVTPU_SERVER_QUEUE`` env,
    else 0 = unbounded).
``--ready-file PATH``
    after binding, atomically write the RESOLVED dialable address list
    here (comma-separated, same order as ``--address``). The launcher
    (``benchmarks/serving_mp.py``, ``make mp-smoke``) polls this file
    instead of racing the bind — and it is how an ephemeral tcp port
    gets back to the workers.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.server",
        description="multiverso_tpu table-server process")
    parser.add_argument("--address", default="unix:/tmp/mvtpu.sock")
    parser.add_argument("--name", default="tables")
    parser.add_argument("--fuse", type=int, default=None)
    parser.add_argument("--qos", default=None)
    parser.add_argument("--queue", type=int, default=None)
    parser.add_argument("--ready-file", default=None)
    args = parser.parse_args(argv)

    from multiverso_tpu import core
    from multiverso_tpu.server.table_server import TableServer

    core.init()
    server = TableServer(args.address, name=args.name, fuse=args.fuse,
                         qos=args.qos, queue_bound=args.queue)
    bound = server.start()

    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(bound)
        os.replace(tmp, args.ready_file)

    def _stop(signum, frame):
        server.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    try:
        server.serve_forever()
    finally:
        server.stop()
        core.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
