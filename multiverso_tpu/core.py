"""Core runtime: init / shutdown / barrier / topology / the device mesh.

TPU-native replacement for the reference's process runtime (upstream layout
`src/multiverso.cpp`, `src/zoo.cpp`, `src/communicator.cpp`,
`src/controller.cpp`, `src/net/{mpi,zmq}_net.h` — SURVEY.md §3.1/§3.2/§4.1):

- ``MV_Init`` (flag parsing + MPI/ZMQ bootstrap + actor threads + register
  handshake + barrier) becomes :func:`init`: parse ``-name=value`` flags,
  optionally ``jax.distributed.initialize`` over DCN, and build one global
  :class:`jax.sharding.Mesh` over all devices.
- The Worker/Server actor roles dissolve: every chip is simultaneously a
  worker (compute) and a server (holds its parameter shard) — the
  "no CPU PS in the loop" north star (BASELINE.json).
- ``MV_Barrier`` (Control_Barrier round trip through the rank-0 Controller)
  becomes a device-level sync: all hosts dispatch one tiny all-reduce over
  every device and block on the result.
- Topology queries (``MV_Rank/Size/NumWorkers/NumServers/WorkerId/ServerId``)
  map onto JAX process/device topology: a "node" is a host process, a
  "worker" and a "server" are both "a chip".

The mesh convention: axes ``("data", "model")``. Tables shard their leading
dimension over ``"model"`` (the analog of partitioning rows across server
shards) and gradients are reduced over ``"data"`` (the analog of the
Add/Aggregator path). ``model_parallel=1`` (default) gives pure DP with
fully replicated tables, matching the reference's default deployment shape.
"""

from __future__ import annotations

import atexit
import threading
import time
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.utils import configure, log

DATA_AXIS = "data"
MODEL_AXIS = "model"


class _Runtime:
    """Process-global runtime state (the Zoo singleton's successor)."""

    def __init__(self) -> None:
        self.initialized = False
        self.mesh: Optional[Mesh] = None
        self.lock = threading.Lock()
        self.barrier_count = 0


_RT = _Runtime()


def _build_mesh(devices: Sequence[jax.Device], data_parallel: int,
                model_parallel: int) -> Mesh:
    n = len(devices)
    if model_parallel <= 0:
        raise ValueError("model_parallel must be >= 1")
    if data_parallel <= 0:
        data_parallel = n // model_parallel
    if data_parallel * model_parallel != n:
        raise ValueError(
            f"mesh {data_parallel}x{model_parallel} != {n} devices")
    dev_array = np.asarray(devices).reshape(data_parallel, model_parallel)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def init(argv: Optional[Sequence[str]] = None, *,
         devices: Optional[Sequence[jax.Device]] = None,
         data_parallel: Optional[int] = None,
         model_parallel: Optional[int] = None) -> Mesh:
    """Initialise the runtime and build the global device mesh.

    ``argv`` may carry reference-style ``-name=value`` flags. ``devices``,
    ``data_parallel``, ``model_parallel`` override flags when given (used by
    tests to build virtual CPU meshes).

    Idempotent like ``MV_Init``: a second call with no arguments returns the
    existing mesh.
    """
    with _RT.lock:
        if argv:
            configure.parse_flags(argv)
        if _RT.initialized and not argv and devices is None \
                and data_parallel is None and model_parallel is None:
            assert _RT.mesh is not None
            return _RT.mesh

        log.set_level(configure.get_flag("log_level"))
        if configure.get_flag("log_file"):
            log.set_file(configure.get_flag("log_file"))

        coordinator = configure.get_flag("machine_file")
        if coordinator:
            # Multi-host bootstrap over DCN (the reference's MPI_Init /
            # ZMQ-machine_file moment). Must run before anything touches
            # the XLA backend; jax raises if the backend is already up,
            # and that is a real misconfiguration — fail fast, a silent
            # fallback to single-host topology would train wrong.
            # ``machine_file`` keeps the reference's flag shape: a FILE
            # listing one host per line (first = coordinator; the count
            # supplies -num_processes when unset). This host's rank comes
            # from -process_id (or the platform's auto-detection on cloud
            # TPU), NOT from the file — matching local addresses against
            # the list is unreliable in containers. A bare ``host`` /
            # ``host:port`` value is also accepted.
            import os
            if os.path.exists(coordinator):
                with open(coordinator) as f:
                    machines = [m for m in (ln.strip() for ln in f)
                                if m and not m.startswith("#")]
                if not machines:
                    raise ValueError(
                        f"machine_file {coordinator!r} lists no machines")
                coordinator = machines[0]
                if configure.get_flag("num_processes") == 0:
                    configure.set_flag("num_processes", len(machines))
            if ":" in coordinator:
                address = coordinator
            else:
                port = configure.get_flag("port") or 8476
                address = f"{coordinator}:{port}"
            nproc = configure.get_flag("num_processes")
            pid = configure.get_flag("process_id")
            kwargs = {}
            if nproc > 0:
                kwargs["num_processes"] = nproc
            if pid >= 0:
                kwargs["process_id"] = pid
            jax.distributed.initialize(coordinator_address=address,
                                       **kwargs)

        # fault injection rides runtime init: one env var turns any run
        # into a chaos run (tests / the chaos CI lane)
        from multiverso_tpu.ft.chaos import chaos_from_env
        chaos_from_env()

        # observability rides init the same way: MVTPU_STATUSZ_PORT
        # arms the live introspection server, MVTPU_SLO the tail-
        # latency monitor, MVTPU_HEALTH the training-health monitor
        # (all idempotent across re-inits)
        from multiverso_tpu.control.controller import maybe_controller
        from multiverso_tpu.telemetry.health import maybe_health_monitor
        from multiverso_tpu.telemetry.slo import maybe_slo_monitor
        from multiverso_tpu.telemetry.statusz import maybe_statusz
        maybe_statusz()
        maybe_slo_monitor()
        maybe_health_monitor()
        # MVTPU_AUTOTUNE closes the loop: the controller reads the
        # monitors' metrics and actuates the knob table
        maybe_controller()

        devs = list(devices) if devices is not None else jax.devices()
        dp = data_parallel if data_parallel is not None \
            else configure.get_flag("data_parallel")
        mp = model_parallel if model_parallel is not None \
            else configure.get_flag("model_parallel")
        _RT.mesh = _build_mesh(devs, dp, mp)
        _RT.initialized = True
        # topology on the record: one registry snapshot then identifies
        # the mesh shape a run's per-table byte counts came from
        telemetry.counter("core.init.ops").inc()
        telemetry.gauge("core.devices").set(len(devs))
        telemetry.gauge("core.data_parallel").set(
            _RT.mesh.shape[DATA_AXIS])
        telemetry.gauge("core.model_parallel").set(
            _RT.mesh.shape[MODEL_AXIS])
        telemetry.gauge("core.processes").set(jax.process_count())
        telemetry.gauge("core.process_index").set(jax.process_index())
        log.info("multiverso_tpu.init: %d devices, mesh data=%d model=%d, "
                 "process %d/%d", len(devs), _RT.mesh.shape[DATA_AXIS],
                 _RT.mesh.shape[MODEL_AXIS], jax.process_index(),
                 jax.process_count())
        return _RT.mesh


def is_initialized() -> bool:
    return _RT.initialized


def place(value, spec: P = P(), *, mesh: Optional[Mesh] = None) -> jax.Array:
    """Put a host value on the runtime mesh (replicated by default).

    Every device array an app creates MUST go through this (or an explicit
    ``NamedSharding`` ``device_put``): a bare ``jnp.asarray`` materialises
    on the process *default* device, which may be a different platform than
    the mesh — e.g. a TPU-default process building a CPU test mesh — and
    then either crashes the default backend or poisons a jit with
    mixed-platform operands.
    """
    m = mesh if mesh is not None else globals()["mesh"]()
    return jax.device_put(value, NamedSharding(m, spec))


def sharded_zeros(shape, dtype, sharding) -> jax.Array:
    """Zeros created DIRECTLY under a sharding — never on the default
    device and never materialised on host.

    A bare ``jnp.zeros(...)`` allocates on the process default backend
    before any ``device_put`` can move it (double allocation, and a crash
    when the default platform is broken — the same hazard ``place``
    documents); passing the sharding as ``device=`` makes jax allocate
    each shard on its target device only, with no per-call jit wrapper.
    """
    import jax.numpy as jnp
    return jnp.zeros(shape, dtype, device=sharding)


def prng_key(seed: int, *, mesh: Optional[Mesh] = None) -> jax.Array:
    """A PRNG key resident on the mesh, never on the default device.

    ``jax.random.PRNGKey(int)`` runs its seed-mixing ops eagerly on the
    default backend — which may be a different (even broken) platform than
    the mesh. Instead the key data is built on host and placed: for the
    default ``threefry2x32`` impl, ``PRNGKey(seed)`` is exactly the
    ``uint32[2]`` array ``[seed >> 32, seed & 0xffffffff]``, with negative
    seeds two's-complement wrapped — full 64-bit seed semantics preserved
    (verified against ``jax.random.PRNGKey`` in tests).
    """
    impl = jax.config.jax_default_prng_impl
    if impl != "threefry2x32":   # pragma: no cover - non-default impl
        return place(jax.random.PRNGKey(seed), mesh=mesh)
    # x64-off canonicalisation wraps the seed to int32 and the hi word of
    # threefry_seed's 32-by-32 logical shift is 0 — verified equal to
    # jax.random.PRNGKey for the int64 range in tests; beyond int64 raise
    # OverflowError exactly like jax's canonicalisation does (numpy 2.x
    # would silently give uint64/object dtype instead of raising)
    if not (-(2 ** 63) <= int(seed) < 2 ** 63):
        raise OverflowError(f"seed {seed} out of int64 range")
    wrapped = int(np.asarray(int(seed), dtype=np.int64).astype(np.int32))
    data = np.array([0, wrapped & 0xFFFFFFFF], dtype=np.uint32)
    return place(data, mesh=mesh)


def shutdown(finalize: bool = True) -> None:
    """``MV_ShutDown`` equivalent: drop the mesh; optionally report timing."""
    with _RT.lock:
        if not _RT.initialized:
            return
        _RT.initialized = False
        _RT.mesh = None
    from multiverso_tpu.control.controller import shutdown_controllers
    shutdown_controllers()
    if finalize:
        from multiverso_tpu.utils import dashboard
        log.debug("dashboard at shutdown:\n%s", dashboard.report())


def mesh() -> Mesh:
    if not _RT.initialized or _RT.mesh is None:
        init()
    assert _RT.mesh is not None
    return _RT.mesh


def set_mesh(m: Mesh) -> None:
    """Install an externally-built mesh (tests, embedding in a larger app)."""
    with _RT.lock:
        _RT.mesh = m
        _RT.initialized = True


@jax.jit
def _barrier_sum(x):
    return x.sum()


def barrier(name: Optional[str] = None) -> None:
    """Global synchronisation point (``MV_Barrier``).

    Dispatches a tiny all-reduce over every device of the mesh and blocks
    until it completes; across hosts this is a true barrier because the
    collective cannot complete until every host has dispatched it.
    """
    m = mesh()
    # fault point: a 'latency' rule here models a straggler host; an
    # 'error' rule a lost peer (the failure mode SURVEY §6.3 records
    # the reference hangs on)
    from multiverso_tpu.ft.chaos import chaos_point
    chaos_point("core.barrier")
    _RT.barrier_count += 1
    t0 = time.perf_counter()
    ones = jax.device_put(
        np.zeros((len(m.devices.flat),), np.int32),
        NamedSharding(m, P((DATA_AXIS, MODEL_AXIS))))
    _barrier_sum(ones).block_until_ready()
    # barrier latency IS the straggler signal on a multi-host mesh: the
    # collective completes only when the slowest host dispatches it
    telemetry.counter("core.barrier.ops").inc()
    telemetry.histogram("core.barrier.seconds").observe(
        time.perf_counter() - t0)


# -- Topology queries (reference MV_* names, SURVEY.md §3.5) ---------------

def rank() -> int:
    """Host-process rank (reference: node rank)."""
    return jax.process_index()


def size() -> int:
    """Number of host processes (reference: node count)."""
    return jax.process_count()


def num_workers() -> int:
    """Reference: count of worker roles. Here every chip computes."""
    return len(mesh().devices.flat)


def num_servers() -> int:
    """Reference: count of server roles. Here every chip holds a shard."""
    return len(mesh().devices.flat)


def worker_id() -> int:
    """First local device's position in the mesh (per-host worker id)."""
    me = jax.process_index()
    for i, d in enumerate(mesh().devices.flat):
        if d.process_index == me:
            return i
    return -1


def server_id() -> int:
    return worker_id()


def is_worker() -> bool:
    return True


def is_server() -> bool:
    return True


def data_axis_size() -> int:
    return mesh().shape[DATA_AXIS]


def model_axis_size() -> int:
    return mesh().shape[MODEL_AXIS]


atexit.register(shutdown)
