"""Updater implementations. See package docstring for semantics and the
reference mapping (SURVEY.md §3.4)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AddOption:
    """Per-Add hyperparameters, the reference's ``AddOption`` struct
    (upstream `include/multiverso/table_interface.h`; SURVEY.md §3.3).

    Registered as a pytree of scalar leaves so changing a value (lr decay
    schedules etc.) does NOT retrigger XLA compilation — the values are
    traced operands, not static attributes.
    """
    learning_rate: float = 0.1
    momentum: float = 0.9
    rho: float = 0.999          # second-moment decay (adam)
    lam: float = 1e-8           # epsilon / regularization knob
    step: int = 0               # global step counter (adam bias correction)

    @classmethod
    def for_ftrl(cls, learning_rate: float, l1: float = 0.0,
                 l2: float = 0.0, beta: float = 1.0) -> "AddOption":
        """The ftrl updater's field mapping in ONE place: ``lam`` = L1,
        ``rho`` = L2, ``momentum`` = beta (alpha = learning_rate)."""
        return cls(learning_rate=learning_rate, lam=l1, rho=l2,
                   momentum=beta)

    def as_jax(self, mesh=None) -> "AddOption":
        """Scalar leaves as device arrays. With ``mesh``, the scalars are
        placed replicated on that mesh — NOT on the process default device,
        which may be a different platform than the table's mesh."""
        if mesh is None:
            put = jnp.asarray
        else:
            from multiverso_tpu import core
            put = lambda x, dt: core.place(np.asarray(x, dt), mesh=mesh)
        return AddOption(
            learning_rate=put(self.learning_rate, jnp.float32),
            momentum=put(self.momentum, jnp.float32),
            rho=put(self.rho, jnp.float32),
            lam=put(self.lam, jnp.float32),
            step=put(self.step, jnp.int32),
        )


Param = Any    # jax array or pytree of arrays (one table shard)
State = Any    # pytree of arrays shaped/sharded like Param


@dataclasses.dataclass(frozen=True)
class Updater:
    """A named pair of pure functions: state init + apply."""
    name: str
    init_state: Callable[[Param], State]
    apply: Callable[[Param, State, Param, AddOption], Tuple[Param, State]]


def _no_state(param: Param) -> State:
    return ()


def _default_apply(param, state, delta, option):
    new = jax.tree.map(lambda p, d: p + d.astype(p.dtype), param, delta)
    return new, state


def _sgd_apply(param, state, delta, option):
    lr = option.learning_rate
    new = jax.tree.map(lambda p, d: p - (lr * d).astype(p.dtype),
                       param, delta)
    return new, state


def _adagrad_init(param: Param) -> State:
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), param)


def _adagrad_apply(param, state, delta, option):
    lr, eps = option.learning_rate, option.lam

    def upd(p, h, d):
        d32 = d.astype(jnp.float32)
        h = h + d32 * d32
        return (p - (lr * d32 / (jnp.sqrt(h) + eps)).astype(p.dtype), h)

    flat = jax.tree.map(upd, param, state, delta)
    new_param = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
    new_state = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
    return new_param, new_state


def _momentum_init(param: Param) -> State:
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), param)


def _momentum_apply(param, state, delta, option):
    lr, mu = option.learning_rate, option.momentum

    def upd(p, v, d):
        v = mu * v + d.astype(jnp.float32)
        return (p - (lr * v).astype(p.dtype), v)

    flat = jax.tree.map(upd, param, state, delta)
    new_param = jax.tree.map(lambda t: t[0], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
    new_state = jax.tree.map(lambda t: t[1], flat,
                             is_leaf=lambda t: isinstance(t, tuple))
    return new_param, new_state


def _adam_init(param: Param) -> State:
    zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
    return {"m": jax.tree.map(zeros, param), "v": jax.tree.map(zeros, param)}


def _adam_apply(param, state, delta, option):
    lr, b1, b2, eps = (option.learning_rate, option.momentum, option.rho,
                       option.lam)
    t = option.step.astype(jnp.float32) + 1.0

    def upd(p, m, v, d):
        d32 = d.astype(jnp.float32)
        m = b1 * m + (1.0 - b1) * d32
        v = b2 * v + (1.0 - b2) * d32 * d32
        mhat = m / (1.0 - b1 ** t)
        vhat = v / (1.0 - b2 ** t)
        return (p - (lr * mhat / (jnp.sqrt(vhat) + eps)).astype(p.dtype),
                m, v)

    flat = jax.tree.map(upd, param, state["m"], state["v"], delta)
    is_tup = lambda x: isinstance(x, tuple)
    return (jax.tree.map(lambda x: x[0], flat, is_leaf=is_tup),
            {"m": jax.tree.map(lambda x: x[1], flat, is_leaf=is_tup),
             "v": jax.tree.map(lambda x: x[2], flat, is_leaf=is_tup)})


def _ftrl_init(param: Param) -> State:
    zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
    return {"z": jax.tree.map(zeros, param), "n": jax.tree.map(zeros, param)}


def _ftrl_apply(param, state, delta, option):
    """FTRL-Proximal (per-coordinate), the reference LR app's FTRL-style
    objective (SURVEY.md §3.6 Apps/LogisticRegression).

    ``AddOption`` field mapping for this updater (the struct is the
    reference's generic hyperparameter carrier, SURVEY.md §3.3):
    ``learning_rate`` = alpha, ``momentum`` = beta, ``lam`` = L1,
    ``rho`` = L2. The closed-form proximal weight is recomputed from the
    (z, n) state, so L1 produces exact zeros — the reason the reference's
    sparse LR wanted FTRL at all.
    """
    alpha, beta = option.learning_rate, option.momentum
    l1, l2 = option.lam, option.rho

    def upd(p, z, n, d):
        g = d.astype(jnp.float32)
        n_new = n + g * g
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / alpha
        z_new = z + g - sigma * p.astype(jnp.float32)
        shrunk = jnp.sign(z_new) * jnp.maximum(jnp.abs(z_new) - l1, 0.0)
        # canonical guard: |z| <= l1 selects w = 0 OUTSIDE the division —
        # with beta = l2 = 0 a never-touched coordinate has n = z = 0 and
        # the quotient is 0/0 (NaN) without it
        w = jnp.where(jnp.abs(z_new) <= l1, 0.0,
                      -shrunk / ((beta + jnp.sqrt(n_new)) / alpha + l2))
        return (w.astype(p.dtype), z_new, n_new)

    flat = jax.tree.map(upd, param, state["z"], state["n"], delta)
    is_tup = lambda x: isinstance(x, tuple)
    return (jax.tree.map(lambda x: x[0], flat, is_leaf=is_tup),
            {"z": jax.tree.map(lambda x: x[1], flat, is_leaf=is_tup),
             "n": jax.tree.map(lambda x: x[2], flat, is_leaf=is_tup)})


def resolve_default_option(updater_name: str,
                           option: "AddOption | None") -> AddOption:
    """Table-constructor helper: the right default AddOption for an
    updater. The generic defaults are adam-oriented (momentum=0.9,
    rho=0.999) — under ``ftrl``'s field mapping those would silently
    become beta=0.9 and L2=0.999, so a missing option resolves to
    ``AddOption.for_ftrl()`` instead, and a generic-looking option gets
    a loud warning pointing at the mapping."""
    if updater_name != "ftrl":
        return option or AddOption()
    if option is None:
        return AddOption.for_ftrl(AddOption().learning_rate)
    if option.momentum == 0.9 and option.rho == 0.999:
        from multiverso_tpu.utils import log
        log.warn(
            "updater='ftrl' reads AddOption fields as (lam, rho, "
            "momentum) = (L1, L2, beta); this option carries the "
            "generic adam-oriented defaults (momentum=0.9, rho=0.999), "
            "which mean beta=0.9 and L2=0.999 under ftrl — build it "
            "with AddOption.for_ftrl(lr, l1, l2, beta) instead")
    return option


_REGISTRY: Dict[str, Updater] = {}


def register_updater(updater: Updater) -> None:
    _REGISTRY[updater.name] = updater


def get_updater(name: str) -> Updater:
    """Factory selected by the ``updater_type`` flag, the analog of
    ``Updater<T>::GetUpdater()`` (upstream `src/updater.cpp`)."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown updater_type {name!r}; "
                         f"valid: {sorted(_REGISTRY)}") from None


def updater_names():
    return sorted(_REGISTRY)


register_updater(Updater("default", _no_state, _default_apply))
register_updater(Updater("sgd", _no_state, _sgd_apply))
register_updater(Updater("adagrad", _adagrad_init, _adagrad_apply))
register_updater(Updater("momentum", _momentum_init, _momentum_apply))
register_updater(Updater("adam", _adam_init, _adam_apply))
register_updater(Updater("ftrl", _ftrl_init, _ftrl_apply))
