"""Server-side updater stack, compiled as on-device optimizer steps.

TPU-native equivalent of the reference updater layer (upstream layout
`include/multiverso/updater/{updater,sgd_updater,adagrad_updater,
momentum_updater}.h`, `src/updater.cpp` — SURVEY.md §3.4): the reference
selects an updater by the ``updater_type`` flag and calls
``Update(n, data, delta, AddOption*, offset)`` element-block-wise inside
``ServerTable::ProcessAdd``, with updater state living server-side, sized
like the table.

Here each updater is a pure function ``(param, state, delta, option) ->
(param, state)`` traced into the table's jitted ``add`` step; state is
created with ``init_state(param)`` via ``zeros_like`` so it inherits the
param's ``NamedSharding`` — optimizer state sharded like params, the
idiomatic TPU form of "state lives on the server shard".

Updater semantics (matching the reference's):

- ``default`` — plain additive merge: ``param += delta`` (the PS Add verb;
  delta is a value-difference, not a gradient).
- ``sgd``     — ``param -= lr * delta`` (delta is a gradient).
- ``adagrad`` — per-element squared-gradient accumulator ``h += delta**2``;
  ``param -= lr * delta / (sqrt(h) + eps)``.
- ``momentum``— velocity ``v = mu * v + delta``; ``param -= lr * v``.
- ``adam``    — extension beyond the reference set (not in upstream
  Multiverso; provided because modern workloads expect it).
- ``ftrl``    — FTRL-Proximal, the reference LR app's FTRL-style objective
  (SURVEY.md §3.6): per-coordinate (z, n) state, closed-form proximal
  weight with exact-zero L1 shrinkage. AddOption mapping: ``learning_rate``
  = alpha, ``momentum`` = beta, ``lam`` = L1, ``rho`` = L2.
"""

from multiverso_tpu.updaters.updaters import (AddOption, Updater,
                                              get_updater, register_updater,
                                              resolve_default_option,
                                              updater_names)

__all__ = ["AddOption", "Updater", "get_updater", "register_updater",
           "resolve_default_option", "updater_names"]
