"""Shared hashing / batch-shaping helpers for the table layer AND the
kernel engine.

Hoisted out of ``matrix_table.py`` / ``kv_table.py`` so that
``multiverso_tpu/ops/table_kernels.py`` (the Pallas kernel engine) can
use the same key→bucket mix and power-of-two batch bucketing WITHOUT
importing table classes (ops must stay importable with zero table-layer
dependencies — kernels are below tables in the layering). The old
locations re-export these names for back-compat.
"""

from __future__ import annotations

import numpy as np

#: reserved sentinel: a key value that can never be inserted (its split
#: uint32 planes equal the empty-slot marker).
EMPTY_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _bucket(n: int) -> int:
    """Round up to the next power of two (min 8) to bound recompiles."""
    b = 8
    while b < n:
        b <<= 1
    return b


def _hash_u64(keys: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stable key→bucket mix (host + device safe)."""
    x = keys.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _split_keys(keys: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 2) uint32 [hi, lo] for device storage."""
    return np.stack([(keys >> np.uint64(32)).astype(np.uint32),
                     (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
                    axis=1)


def _join_keys(split: np.ndarray) -> np.ndarray:
    """(..., 2) uint32 [hi, lo] → (...,) uint64."""
    return (split[..., 0].astype(np.uint64) << np.uint64(32)) \
        | split[..., 1].astype(np.uint64)
