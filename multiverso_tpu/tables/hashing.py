"""Shared hashing / batch-shaping helpers for the table layer AND the
kernel engine.

Hoisted out of ``matrix_table.py`` / ``kv_table.py`` so that
``multiverso_tpu/ops/table_kernels.py`` (the Pallas kernel engine) can
use the same key→bucket mix and power-of-two batch bucketing WITHOUT
importing table classes (ops must stay importable with zero table-layer
dependencies — kernels are below tables in the layering). The old
locations re-export these names for back-compat.
"""

from __future__ import annotations

import numpy as np

#: reserved sentinel: a key value that can never be inserted (its split
#: uint32 planes equal the empty-slot marker).
EMPTY_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _bucket(n: int) -> int:
    """Round up to the next power of two (min 8) to bound recompiles."""
    b = 8
    while b < n:
        b <<= 1
    return b


def _hash_u64(keys: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stable key→bucket mix (host + device safe)."""
    x = keys.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _split_keys(keys: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 2) uint32 [hi, lo] for device storage."""
    return np.stack([(keys >> np.uint64(32)).astype(np.uint32),
                     (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
                    axis=1)


def _join_keys(split: np.ndarray) -> np.ndarray:
    """(..., 2) uint32 [hi, lo] → (...,) uint64."""
    return (split[..., 0].astype(np.uint64) << np.uint64(32)) \
        | split[..., 1].astype(np.uint64)


def shard_lane_slices(shard_ids: np.ndarray, shards: int, arrays,
                      pads):
    """Slice one shard-sorted lane batch into per-shard lane rows.

    The substrate of the sharded kernel engine (and the layout ROADMAP
    item 4's resharding re-derives): each model-axis shard's Pallas grid
    runs over ONE dense, contiguous lane range — its row of the returned
    ``(shards, L, ...)`` arrays — with non-local lanes appearing only as
    masked padding at the row's tail. ``L`` is the power-of-two bucket
    (:func:`_bucket`) of the largest per-shard lane count, so the
    compiled-signature set stays bounded exactly like the flat path's
    batch bucketing.

    ``shard_ids`` must be sorted ascending (tables get this for free:
    bucket/row ownership is contiguous equal blocks, so the existing
    stable sort by bucket/row IS a sort by shard-then-bucket/row, and
    each shard's lanes keep their original relative order — the
    bit-parity argument for the per-bucket/per-row run scans).

    ``arrays`` is a sequence of ``(n, ...)`` lane arrays (local ids,
    queries, deltas, ...), ``pads`` the per-array scalar fill for the
    padding lanes. Returns ``(sliced, valid, pos)``: ``sliced[k]`` of
    shape ``(shards, L) + arrays[k].shape[1:]`` with
    ``sliced[k][shard_ids[i], pos[i]] == arrays[k][i]``; ``valid`` the
    ``(shards, L)`` real-lane mask; ``pos`` the per-lane position within
    its shard row (the inverse map callers build gather unpermutes
    from: flat index ``shard_ids[i] * L + pos[i]``).
    """
    shard_ids = np.asarray(shard_ids)
    n = len(shard_ids)
    if n and (np.diff(shard_ids) < 0).any():
        raise ValueError("shard_lane_slices needs shard-sorted lanes")
    counts = np.bincount(shard_ids, minlength=shards)
    L = _bucket(int(counts.max(initial=1)))
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(n) - starts[shard_ids]
    sliced = []
    for arr, pad in zip(arrays, pads):
        out = np.full((shards, L) + arr.shape[1:], pad, dtype=arr.dtype)
        out[shard_ids, pos] = arr
        sliced.append(out)
    valid = np.zeros((shards, L), bool)
    valid[shard_ids, pos] = True
    return sliced, valid, pos
