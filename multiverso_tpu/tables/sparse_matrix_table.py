"""SparseMatrixTable: matrix table with COO sparse Add and sparse-row Get.

Reference: `include/multiverso/table/sparse_matrix_table.h` (upstream
layout; SURVEY.md §3.3) — a matrix table variant where Add carries
(row, col, value) sparse deltas and Get returns only requested rows;
LightLDA's word-topic count store.

TPU design (SURVEY.md §3.9): storage stays DENSE and row-sharded (TPU HBM
is fine with dense counts; vocab×topics fits comfortably), and the sparse
COO Add becomes a jitted duplicate-safe ``.at[rows, cols].add(values)``
scatter — XLA lowers this to a sorted segment scatter on TPU. COO batch
lengths are bucketed to powers of two; padded lanes scatter zeros into a
reserved scratch row.

Sparse adds are supported for the stateless updaters (``default`` — the
LightLDA count case — and ``sgd``). Stateful updaters would need
per-element state touched only at COO positions; the reference never uses
them with sparse tables either.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from multiverso_tpu.tables.base import Handle
from multiverso_tpu.tables.matrix_table import MatrixTable, _bucket
from multiverso_tpu.updaters import AddOption


@dataclasses.dataclass
class SparseMatrixTableOption:
    num_rows: int
    num_cols: int
    dtype: Any = "float32"
    init_value: Any = 0
    updater: Optional[str] = None
    name: str = "sparse_matrix_table"


class SparseMatrixTable(MatrixTable):
    def __init__(self, num_rows: int, num_cols: int,
                 dtype: Any = "float32", *, init_value: Any = 0,
                 updater: Optional[str] = None, mesh=None,
                 name: str = "sparse_matrix_table",
                 default_option: Optional[AddOption] = None) -> None:
        super().__init__(num_rows, num_cols, dtype, init_value=init_value,
                         updater=updater, mesh=mesh, name=name,
                         default_option=default_option)
        if self.updater.name not in ("default", "sgd"):
            raise ValueError(
                f"SparseMatrixTable supports stateless updaters "
                f"(default, sgd), got {self.updater.name!r}")

        @partial(jax.jit, donate_argnums=(0,))
        def coo_scatter_add(param, rows, cols, vals):
            return param.at[rows, cols].add(vals.astype(param.dtype))

        self._coo_scatter_add = coo_scatter_add

    def add_sparse(self, rows, cols, values,
                   option: Optional[AddOption] = None,
                   sync: bool = False) -> Handle:
        """COO sparse Add: ``param[rows[i], cols[i]] += values[i]``.

        Duplicate (row, col) pairs accumulate. With the ``sgd`` updater the
        values are treated as gradients: ``param -= lr * values``.
        """
        rows = np.asarray(rows, dtype=np.int32)
        cols = np.asarray(cols, dtype=np.int32)
        values = np.asarray(values)
        if not (rows.shape == cols.shape == values.shape) or rows.ndim != 1:
            raise ValueError(
                f"COO arrays must be same-length 1-D, got rows={rows.shape} "
                f"cols={cols.shape} values={values.shape}")
        if len(rows) == 0:
            raise ValueError("empty COO add")
        self._check_ids(rows)
        if cols.min() < 0 or cols.max() >= self.num_cols:
            raise ValueError(f"col ids out of range [0, {self.num_cols})")

        n = len(rows)
        b = _bucket(n)
        prows = np.full(b, self._scratch_row, dtype=np.int32)
        pcols = np.zeros(b, dtype=np.int32)
        pvals = np.zeros(b, dtype=values.dtype)
        prows[:n], pcols[:n], pvals[:n] = rows, cols, values
        if self.updater.name == "sgd":
            lr = float(option.learning_rate if option is not None
                       else self.default_option.learning_rate)
            pvals = -lr * pvals
        self.param = self._coo_scatter_add(self.param, prows, pcols, pvals)
        self._bump_step()
        handle = Handle(table=self, generation=self.generation)
        if sync:
            handle.wait()
        return handle
