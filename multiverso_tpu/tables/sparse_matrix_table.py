"""SparseMatrixTable: matrix table with COO sparse Add and sparse-row Get.

Reference: `include/multiverso/table/sparse_matrix_table.h` (upstream
layout; SURVEY.md §3.3) — a matrix table variant where Add carries
(row, col, value) sparse deltas and Get returns only requested rows;
LightLDA's word-topic count store.

TPU design (SURVEY.md §3.9): storage stays DENSE and row-sharded (TPU HBM
is fine with dense counts; vocab×topics fits comfortably), and the sparse
COO Add becomes a jitted duplicate-safe ``.at[rows, cols].add(values)``
scatter — XLA lowers this to a sorted segment scatter on TPU. COO batch
lengths are bucketed to powers of two; padded lanes scatter zeros into a
reserved scratch row.

Tiled storage (``tiled=True``, requires ``num_cols % 128 == 0``): the
physical array is ``[rows, C, 128]`` with ``C = num_cols/128``, so ONE
LOGICAL ROW IS EXACTLY ONE (8,128) int32 TPU TILE — a random row gather
reads a 4 KB payload instead of the 32 KB tile-span the 2-D layout
incurs (8 consecutive rows share each tile). This is the layout the LDA
Gibbs superstep's gathers/scatters want (benchmarks/experiments/
lda_tile_probe.py); the PUBLIC API stays 2-D — row/COO/checkpoint
operations reshape at the jit boundary, and checkpoints serialize the
layout-agnostic padded 2-D shape either way.

Sparse adds are supported for the stateless updaters (``default`` — the
LightLDA count case — and ``sgd``). Stateful updaters would need
per-element state touched only at COO positions; the reference never uses
them with sparse tables either.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.ft.chaos import chaos_corrupt
from multiverso_tpu.ops import table_kernels as tk
from multiverso_tpu.tables.base import Handle
from multiverso_tpu.tables.hashing import _bucket, shard_lane_slices
from multiverso_tpu.tables.matrix_table import MatrixTable
from multiverso_tpu.telemetry import health as _health
from multiverso_tpu.telemetry.profiling import profiled_jit
from multiverso_tpu.updaters import AddOption

LANES = 128


@dataclasses.dataclass
class SparseMatrixTableOption:
    num_rows: int
    num_cols: int
    dtype: Any = "float32"
    init_value: Any = 0
    updater: Optional[str] = None
    name: str = "sparse_matrix_table"
    tiled: bool = False


class SparseMatrixTable(MatrixTable):
    def __init__(self, num_rows: int, num_cols: int,
                 dtype: Any = "float32", *, init_value: Any = 0,
                 updater: Optional[str] = None, mesh=None,
                 name: str = "sparse_matrix_table",
                 default_option: Optional[AddOption] = None,
                 tiled: bool = False) -> None:
        if tiled and num_cols % LANES:
            raise ValueError(f"tiled storage needs num_cols % {LANES} == 0,"
                             f" got {num_cols}")
        self.tiled = tiled
        self.tiles = num_cols // LANES if tiled else 0
        super().__init__(num_rows, num_cols, dtype, init_value=init_value,
                         updater=updater, mesh=mesh, name=name,
                         default_option=default_option)
        if self.updater.name not in ("default", "sgd"):
            raise ValueError(
                f"SparseMatrixTable supports stateless updaters "
                f"(default, sgd), got {self.updater.name!r}")
        if tiled:
            self._retile_storage()
        self._build_sparse_jits()

    # -- tiled layout ------------------------------------------------------

    def _retile_storage(self) -> None:
        """Swap the 2-D param for the [rows, C, 128] tile-aligned layout
        (state is the empty pytree — stateless updaters enforced)."""
        c = self.tiles
        self.storage_shape = (self.padded_shape[0], c, LANES)
        self.spec = P(core.MODEL_AXIS, None, None)
        self.sharding = NamedSharding(self.mesh, self.spec)
        host = np.asarray(self.param).reshape(self.storage_shape)
        self.param = jax.device_put(host, self.sharding)

        replicated = NamedSharding(self.mesh, P(None, None))
        n_rows, n_cols = self.logical_shape

        def snapshot(param):
            p2 = param.reshape(self.padded_shape)
            return jnp.copy(p2[:n_rows, :n_cols])

        # profiled like the base kernels (tiled layouts replace them)
        self._snapshot = profiled_jit(
            snapshot, name=f"table.snapshot.{self.name}",
            out_shardings=replicated)

        def gather_rows(param, ids):
            rows = jnp.take(param, ids, axis=0)      # [n, C, 128]
            return rows.reshape(ids.shape[0], n_cols)

        def scatter_add(param, ids, deltas):
            d3 = deltas.reshape(ids.shape[0], c, LANES)
            return param.at[ids].add(d3.astype(param.dtype))

        # sharded XLA adapters over the tiled layout (lane-sliced local
        # ids globalized; invalid lanes → global scratch row — see
        # matrix_table.py for the parity argument)
        rps = self._rows_per_shard
        offs = jnp.arange(self._shards, dtype=jnp.int32)[:, None] * rps

        def gather_sharded(param, ids, inv):
            rows = jnp.take(param, (ids + offs).reshape(-1), axis=0)
            return jnp.take(rows.reshape(-1, n_cols), inv, axis=0)

        def scatter_add_sharded(param, ids, deltas, valid):
            gids = jnp.where(valid, ids + offs,
                             self._scratch_row).reshape(-1)
            d3 = deltas.reshape(-1, c, LANES)
            return param.at[gids].add(d3.astype(param.dtype))

        # tiled layouts re-register behind the kernel engine with
        # tiles=c (one logical row = one (8,128) tile — the layout the
        # Pallas row kernels want)
        self._gather_rows = tk.select_kernel(
            f"table.gather.{self.name}",
            xla=profiled_jit(
                gather_rows, name=f"table.gather.{self.name}",
                out_shardings=replicated),
            pallas=lambda: profiled_jit(
                tk.build_row_gather(num_cols=n_cols, tiles=c,
                                    interpret=tk.interpret_mode()),
                name=f"table.gather.{self.name}.pallas",
                out_shardings=replicated),
            pallas_sharded=lambda: profiled_jit(
                tk.build_row_gather_sharded(
                    num_cols=n_cols, tiles=c,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS, lead=self.padded_shape[0]),
                name=f"table.gather.{self.name}.pallas",
                out_shardings=replicated),
            xla_sharded=lambda: profiled_jit(
                gather_sharded, name=f"table.gather.{self.name}",
                out_shardings=replicated),
            mesh=self.mesh)
        self._scatter_add = tk.select_kernel(
            f"table.scatter_add.{self.name}",
            xla=profiled_jit(
                scatter_add, name=f"table.scatter_add.{self.name}",
                donate_argnums=(0,)),
            pallas=lambda: profiled_jit(
                tk.build_row_scatter_add(num_cols=n_cols, tiles=c,
                                         interpret=tk.interpret_mode()),
                name=f"table.scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            pallas_sharded=lambda: profiled_jit(
                tk.build_row_scatter_add_sharded(
                    num_cols=n_cols, tiles=c,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS, lead=self.padded_shape[0]),
                name=f"table.scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            xla_sharded=lambda: profiled_jit(
                scatter_add_sharded,
                name=f"table.scatter_add.{self.name}",
                donate_argnums=(0,)),
            mesh=self.mesh)
        # _gather_apply_scatter is unreachable: stateless updaters only

    # -- jitted sparse kernels --------------------------------------------

    def _build_sparse_jits(self) -> None:
        if self.tiled:
            def coo_scatter_add(param, rows, cols, vals):
                return param.at[rows, cols // LANES, cols % LANES].add(
                    vals.astype(param.dtype))
        else:
            def coo_scatter_add(param, rows, cols, vals):
                return param.at[rows, cols].add(vals.astype(param.dtype))

        # sharded XLA adapter: lane-sliced (shards, L) COO triples with
        # local row ids; invalid lanes → global scratch row. Shard-major
        # flattening of the row-sorted batch stays globally sorted, so
        # duplicate (row, col) pairs accumulate in the same order as the
        # flat scatter — bit-parity with the Pallas run scans.
        rps = self._rows_per_shard
        offs = jnp.arange(self._shards, dtype=jnp.int32)[:, None] * rps

        if self.tiled:
            def coo_sharded(param, rows, cols, vals, valid):
                gr = jnp.where(valid, rows + offs,
                               self._scratch_row).reshape(-1)
                fc = cols.reshape(-1)
                return param.at[gr, fc // LANES, fc % LANES].add(
                    vals.reshape(-1).astype(param.dtype))
        else:
            def coo_sharded(param, rows, cols, vals, valid):
                gr = jnp.where(valid, rows + offs,
                               self._scratch_row).reshape(-1)
                return param.at[gr, cols.reshape(-1)].add(
                    vals.reshape(-1).astype(param.dtype))

        # profiled: the COO Add dispatch count (client coalescing of
        # sparse adds is asserted against profile.calls on this name).
        # Registered behind the kernel engine: the Pallas COO kernel
        # segment-sums each touched row's entries in VMEM and writes the
        # row back to HBM once (requires add_sparse's row sort).
        self._coo_scatter_add = tk.select_kernel(
            f"table.coo_scatter_add.{self.name}",
            xla=profiled_jit(
                coo_scatter_add,
                name=f"table.coo_scatter_add.{self.name}",
                donate_argnums=(0,)),
            pallas=lambda: profiled_jit(
                tk.build_coo_scatter_add(
                    num_cols=self.num_cols, tiles=self.tiles,
                    interpret=tk.interpret_mode()),
                name=f"table.coo_scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            pallas_sharded=lambda: profiled_jit(
                tk.build_coo_scatter_add_sharded(
                    num_cols=self.num_cols, tiles=self.tiles,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS, lead=self.padded_shape[0]),
                name=f"table.coo_scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            xla_sharded=lambda: profiled_jit(
                coo_sharded,
                name=f"table.coo_scatter_add.{self.name}",
                donate_argnums=(0,)),
            mesh=self.mesh)

        replicated = NamedSharding(self.mesh, P(None))
        n_cols = self.num_cols

        @partial(jax.jit, out_shardings=replicated)
        def row_nnz(param, ids):
            rows = jnp.take(param, ids, axis=0).reshape(ids.shape[0],
                                                        n_cols)
            return (rows != 0).sum(axis=1).astype(jnp.int32)

        self._row_nnz = row_nnz
        # per-k jitted top-k extractors (k is a trace constant; cache keeps
        # the jit-churn bounded the same way _bucket bounds id lengths)
        self._topk_jits: Dict[int, Any] = {}

    def _topk_fn(self, k: int):
        fn = self._topk_jits.get(k)
        if fn is None:
            replicated = NamedSharding(self.mesh, P(None, None))
            n_cols = self.num_cols

            @partial(jax.jit, out_shardings=(replicated, replicated))
            def topk(param, ids):
                rows = jnp.take(param, ids, axis=0).reshape(ids.shape[0],
                                                            n_cols)
                mag = jnp.abs(rows.astype(jnp.float32))
                _, cols = lax.top_k(mag, k)
                vals = jnp.take_along_axis(rows, cols, axis=1)
                return cols.astype(jnp.int32), vals

            fn = self._topk_jits[k] = topk
        return fn

    # (whole-table dense add comes from Table.add — the base class
    # reshapes normalized deltas to storage_shape for tiled layouts)

    # -- COO sparse Add ----------------------------------------------------

    def add_sparse(self, rows, cols, values,
                   option: Optional[AddOption] = None,
                   sync: bool = False) -> Handle:
        """COO sparse Add: ``param[rows[i], cols[i]] += values[i]``.

        Duplicate (row, col) pairs accumulate. With the ``sgd`` updater the
        values are treated as gradients: ``param -= lr * values``.
        """
        rows = np.asarray(rows, dtype=np.int32)
        cols = np.asarray(cols, dtype=np.int32)
        values = np.asarray(values)
        if not (rows.shape == cols.shape == values.shape) or rows.ndim != 1:
            raise ValueError(
                f"COO arrays must be same-length 1-D, got rows={rows.shape} "
                f"cols={cols.shape} values={values.shape}")
        if len(rows) == 0:
            raise ValueError("empty COO add")
        self._check_ids(rows)
        if cols.min() < 0 or cols.max() >= self.num_cols:
            raise ValueError(f"col ids out of range [0, {self.num_cols})")

        n = len(rows)
        values = chaos_corrupt("table.add", values)
        self._record_op("add", n, n * self.dtype.itemsize)
        _health.observe_update(self, values)
        # stable row sort: the Pallas COO engine segment-sums each row's
        # run in VMEM (requires sorted rows; same-(row,col) duplicates
        # keep their input order, so float accumulation order matches
        # the XLA scatter on the same sorted batch), and the scratch-row
        # padding (the max row id) keeps the array sorted
        order = np.argsort(rows, kind="stable")
        rows, cols, values = rows[order], cols[order], values[order]
        if self.updater.name == "sgd":
            lr = float(option.learning_rate if option is not None
                       else self.default_option.learning_rate)
            values = -lr * values
        if self._coo_scatter_add.layout == "sharded":
            # row ownership is contiguous equal blocks, so the row sort
            # above IS a shard sort; padding lanes take each shard's max
            # local row (keeps the in-shard run scan sorted) and are
            # masked out of the write-back
            rps = self._rows_per_shard
            shard_ids = rows // rps
            local = (rows - shard_ids * rps).astype(np.int32)
            (sl_rows, sl_cols, sl_vals), valid, _pos = shard_lane_slices(
                shard_ids, self._shards, [local, cols, values],
                [np.int32(rps - 1), np.int32(0), 0])
            self.param = self._coo_scatter_add(
                self.param, sl_rows, sl_cols, sl_vals, valid)
        else:
            b = _bucket(n)
            prows = np.full(b, self._scratch_row, dtype=np.int32)
            pcols = np.zeros(b, dtype=np.int32)
            pvals = np.zeros(b, dtype=values.dtype)
            prows[:n], pcols[:n], pvals[:n] = rows, cols, values
            self.param = self._coo_scatter_add(self.param, prows, pcols,
                                               pvals)
        handle = Handle(table=self, generation=self._bump_step())
        if sync:
            handle.wait()
        return handle

    # -- sparse Get --------------------------------------------------------

    def get_rows_sparse(self, row_ids) -> Tuple[np.ndarray, np.ndarray,
                                                np.ndarray]:
        """Sparse Get: only the NONZERO entries of the requested rows
        reach the host (the reference's SparseMatrixWorkerTable Get
        returns only nonzero/requested entries — SURVEY.md §3.3).

        Returns CSR-style ``(indptr [n+1], cols [nnz], vals [nnz])``:
        row ``i`` of the request holds entries
        ``cols[indptr[i]:indptr[i+1]]`` (ascending col order).

        Exact, not top-k-truncated: a device-side nnz reduction sizes the
        extraction, so the device→host transfer is O(max_nnz·n), not
        O(num_cols·n) — the TPU analog of the reference's sparse wire
        format (its point was not shipping the dense row).
        """
        ids = np.asarray(row_ids, dtype=np.int32)
        self._check_ids(ids)
        padded, _, n = self._pad_ids(ids)
        nnz = np.asarray(self._row_nnz(self.param, padded))[:n]
        k = min(_bucket(max(int(nnz.max(initial=0)), 1)), self.num_cols)
        cols, vals = self._topk_fn(k)(self.param, padded)
        cols = np.asarray(cols)[:n]
        vals = np.asarray(vals)[:n]
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(nnz, out=indptr[1:])
        # one vectorized pass over all requested rows (a per-row Python
        # loop crawls on full-model dumps): np.nonzero walks row-major,
        # then a single lexsort orders each row's entries by column
        ri, ci = np.nonzero(vals != 0)
        ecols = cols[ri, ci]
        order = np.lexsort((ecols, ri))
        self._record_op("get", len(ecols),
                        len(ecols) * self.dtype.itemsize)
        return indptr, ecols[order], vals[ri, ci][order]
