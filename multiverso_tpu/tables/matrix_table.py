"""MatrixTable: 2-D dense row-major table with row-subset Get/Add.

Reference: `include/multiverso/table/matrix_table.h` (upstream layout;
SURVEY.md §3.3) — row-sharded across servers; Get/Add of the whole matrix
or an arbitrary row-id list; word2vec's embedding store
(``MatrixWorkerTable<T>::Get(row_ids, ...)``, ``Add(row_ids, deltas)``).

TPU design:

- storage is one row-sharded array (``P("model", None)``); the reference's
  row→server partition map is the sharding.
- ``get_rows(ids)`` is a jitted gather (XLA inserts the collectives); the
  six-thread-hop request/reply path of the reference (SURVEY.md §4.2)
  becomes one compiled op.
- ``add_rows(ids, deltas)`` for the ``default`` updater is a jitted
  duplicate-safe scatter-add; for stateful updaters it is
  gather→updater→masked scatter, touching only the addressed rows (the
  reference applies the updater only to rows present in the Add).
- row-count-dependent shapes are bucketed to powers of two and padded, so
  the jit cache stays small; padded lanes scatter into a reserved scratch
  row that lives beyond the logical row range.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.ft.chaos import chaos_corrupt
from multiverso_tpu.ops import table_kernels as tk
from multiverso_tpu.tables.base import Handle, Table
# _bucket lives in tables/hashing.py now (shared with the kernel
# engine); re-imported here for historical import sites
from multiverso_tpu.tables.hashing import _bucket, shard_lane_slices
from multiverso_tpu.telemetry import health as _health
from multiverso_tpu.telemetry.profiling import profiled_jit
from multiverso_tpu.updaters import AddOption


@dataclasses.dataclass
class MatrixTableOption:
    num_rows: int
    num_cols: int
    dtype: Any = "float32"
    init_value: Any = 0
    updater: Optional[str] = None
    name: str = "matrix_table"
    shard_update: bool = False   # data-axis weight-update sharding


class MatrixTable(Table):
    def __init__(self, num_rows: int, num_cols: int, dtype: Any = "float32",
                 *, init_value: Any = 0, updater: Optional[str] = None,
                 mesh: Optional[Mesh] = None, name: str = "matrix_table",
                 default_option: Optional[AddOption] = None,
                 shard_update: bool = False) -> None:
        if num_rows <= 0 or num_cols <= 0:
            raise ValueError(f"MatrixTable dims must be positive, got "
                             f"{num_rows}x{num_cols}")
        super().__init__(name, (num_rows, num_cols), dtype, updater=updater,
                         mesh=mesh, init_value=init_value,
                         default_option=default_option,
                         shard_update=shard_update)
        # scratch row: guaranteed > logical rows (base padding reserves it)
        self._scratch_row = self.padded_shape[0] - 1
        assert self._scratch_row >= self.logical_shape[0], \
            "scratch row must live in the padded area"
        # row→shard ownership is contiguous equal blocks over the model
        # axis (base padding makes the lead divisible), so a sort by
        # row id IS a sort by shard-then-row — the sharded lane
        # slicer's precondition
        self._shards = self.mesh.shape[core.MODEL_AXIS]
        self._rows_per_shard = self.padded_shape[0] // self._shards
        self._build_jits()

    # base class hook: reserve at least one padding row for scatter scratch
    def _pad_lead(self, lead: int, shards: int) -> int:
        return -(-(lead + 1) // shards) * shards

    @property
    def num_rows(self) -> int:
        return self.logical_shape[0]

    @property
    def num_cols(self) -> int:
        return self.logical_shape[1]

    # -- jitted kernels ----------------------------------------------------

    def _build_jits(self) -> None:
        replicated = NamedSharding(self.mesh, P(None, None))

        def gather_rows(param, ids):
            return jnp.take(param, ids, axis=0)

        def scatter_add(param, ids, deltas):
            return param.at[ids].add(deltas.astype(param.dtype))

        state_sh = jax.tree.map(lambda _: self.state_sharding, self.state)

        def gather_apply_scatter(param, state, ids, deltas, mask, option):
            rows = jnp.take(param, ids, axis=0)
            st_rows = jax.tree.map(lambda s: jnp.take(s, ids, axis=0), state)
            new_rows, new_st = self.updater.apply(rows, st_rows, deltas,
                                                  option)
            m = mask[:, None]
            new_rows = jnp.where(m, new_rows, rows)
            param = param.at[ids].set(new_rows.astype(param.dtype))
            state = jax.tree.map(
                lambda s, ns, olds: s.at[ids].set(
                    jnp.where(m, ns, olds).astype(s.dtype)),
                state, new_st, st_rows)
            return param, state

        # sharded XLA adapters: lane-sliced (shards, L, ...) operands
        # with LOCAL row ids globalized (local + s*rps). Invalid lanes
        # redirect to the global scratch row — the masked Pallas
        # kernels gate those writes instead, so the logical rows stay
        # bit-identical across engines (the scratch row is garbage by
        # contract on every path). These serve as both the sharded
        # engine's runtime-fallback target and the MVTPU_KERNELS=xla
        # parity lane.
        rps = self._rows_per_shard
        offs = jnp.arange(self._shards, dtype=jnp.int32)[:, None] * rps

        def gather_sharded(param, ids, inv):
            rows = jnp.take(param, (ids + offs).reshape(-1), axis=0)
            return jnp.take(rows, inv, axis=0)

        def scatter_add_sharded(param, ids, deltas, valid):
            gids = jnp.where(valid, ids + offs,
                             self._scratch_row).reshape(-1)
            d = deltas.reshape(-1, self.num_cols)
            return param.at[gids].add(d.astype(param.dtype))

        # profiled: profile.calls{fn=table.{gather,scatter_add,
        # apply_rows}.<name>} count the row-path dispatches the client
        # pipeline's row coalescing / caching are measured against.
        # Gather and scatter-add register behind the kernel engine
        # (MVTPU_KERNELS) with the XLA closures above as fallback
        # (per-shard shard_map grids on multi-device meshes);
        # apply_rows (stateful row updates) stays XLA-only.
        self._gather_rows = tk.select_kernel(
            f"table.gather.{self.name}",
            xla=profiled_jit(
                gather_rows, name=f"table.gather.{self.name}",
                out_shardings=replicated),
            pallas=lambda: profiled_jit(
                tk.build_row_gather(num_cols=self.num_cols, tiles=0,
                                    interpret=tk.interpret_mode()),
                name=f"table.gather.{self.name}.pallas",
                out_shardings=replicated),
            pallas_sharded=lambda: profiled_jit(
                tk.build_row_gather_sharded(
                    num_cols=self.num_cols, tiles=0,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS, lead=self.padded_shape[0]),
                name=f"table.gather.{self.name}.pallas",
                out_shardings=replicated),
            xla_sharded=lambda: profiled_jit(
                gather_sharded, name=f"table.gather.{self.name}",
                out_shardings=replicated),
            mesh=self.mesh)
        self._scatter_add = tk.select_kernel(
            f"table.scatter_add.{self.name}",
            xla=profiled_jit(
                scatter_add, name=f"table.scatter_add.{self.name}",
                donate_argnums=(0,)),
            pallas=lambda: profiled_jit(
                tk.build_row_scatter_add(num_cols=self.num_cols, tiles=0,
                                         interpret=tk.interpret_mode()),
                name=f"table.scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            pallas_sharded=lambda: profiled_jit(
                tk.build_row_scatter_add_sharded(
                    num_cols=self.num_cols, tiles=0,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS, lead=self.padded_shape[0]),
                name=f"table.scatter_add.{self.name}.pallas",
                donate_argnums=(0,)),
            xla_sharded=lambda: profiled_jit(
                scatter_add_sharded,
                name=f"table.scatter_add.{self.name}",
                donate_argnums=(0,)),
            mesh=self.mesh)
        self._gather_apply_scatter = profiled_jit(
            gather_apply_scatter, name=f"table.apply_rows.{self.name}",
            donate_argnums=(0, 1),
            out_shardings=(self.sharding, state_sh))

    def _pad_ids(self, ids: np.ndarray,
                 deltas: Optional[np.ndarray] = None, *,
                 sort: bool = False):
        # scatter paths stable-sort by row id: the Pallas scatter engine
        # segment-sums each touched row's run in VMEM (requires sorted
        # ids), XLA's duplicate-combining scatter is order-insensitive,
        # and the scratch-row padding (the max row id) keeps the array
        # sorted. Gathers must NOT sort — output order is request order.
        if sort and len(ids) > 1:
            order = np.argsort(ids, kind="stable")
            ids = ids[order]
            if deltas is not None:
                deltas = deltas[order]
        n = len(ids)
        b = _bucket(n)
        out_ids = np.full(b, self._scratch_row, dtype=np.int32)
        out_ids[:n] = ids
        mask = np.zeros(b, dtype=bool)
        mask[:n] = True
        if deltas is None:
            return out_ids, mask, n
        out_d = np.zeros((b, self.num_cols), dtype=deltas.dtype)
        out_d[:n] = deltas
        return out_ids, mask, n, out_d

    def _pad_ids_sharded(self, ids: np.ndarray,
                         deltas: Optional[np.ndarray] = None, *,
                         sort: bool = False):
        """Lane-slice prep for the sharded engines: group lanes by
        owning shard (scatters sort by GLOBAL row id, which implies it
        and keeps each shard's lanes row-sorted for the run-scan
        kernels) and slice into per-shard rows of LOCAL ids via
        ``shard_lane_slices``. Padding lanes carry the shard's max
        local id (keeps in-shard sortedness; their writes are masked).
        Returns ``(local_ids, valid, inv, n[, deltas])`` with the
        lane-sliced (shards, L, ...) layout; ``inv`` is the pow2-padded
        flat ``shard*L + pos`` map gathers unpermute through."""
        rps = self._rows_per_shard
        if len(ids) > 1:
            key = ids if sort else ids // rps
            order = np.argsort(key, kind="stable")
            ids = ids[order]
            if deltas is not None:
                deltas = deltas[order]
        else:
            order = np.arange(len(ids))
        shard_ids = ids // rps
        local = (ids - shard_ids * rps).astype(np.int32)
        arrays, pads = [local], [np.int32(rps - 1)]
        if deltas is not None:
            arrays.append(deltas)
            pads.append(0)
        sliced, valid, pos = shard_lane_slices(shard_ids, self._shards,
                                               arrays, pads)
        n = len(ids)
        lanes = sliced[0].shape[1]
        inv = np.zeros(_bucket(n), np.int32)
        inv[order] = (shard_ids * lanes + pos).astype(np.int32)
        if deltas is None:
            return sliced[0], valid, inv, n
        return sliced[0], valid, inv, n, sliced[1]

    # -- row API -----------------------------------------------------------

    def _gather_dispatch(self, ids: np.ndarray):
        """One gather dispatch in whichever operand layout the selected
        engine wants; returns the device rows future (first n real)."""
        if self._gather_rows.layout == "sharded":
            sl_ids, _valid, inv, n = self._pad_ids_sharded(ids)
            return self._gather_rows(self.param, sl_ids, inv)[:n]
        padded, _, n = self._pad_ids(ids)
        return self._gather_rows(self.param, padded)[:n]

    def get_rows(self, row_ids) -> np.ndarray:
        """Fetch a list of rows (``MatrixWorkerTable::Get(row_ids, ...)``)."""
        ids = np.asarray(row_ids, dtype=np.int32)
        self._check_ids(ids)
        n = len(ids)
        self._record_op("get", n * self.num_cols,
                        n * self.num_cols * self.dtype.itemsize)
        return np.asarray(self._gather_dispatch(ids))

    def get_rows_async(self, row_ids) -> Handle:
        ids = np.asarray(row_ids, dtype=np.int32)
        self._check_ids(ids)
        n = len(ids)
        self._record_op("get", n * self.num_cols,
                        n * self.num_cols * self.dtype.itemsize)
        return Handle(self._gather_dispatch(ids))

    def add_rows(self, row_ids, deltas, option: Optional[AddOption] = None,
                 sync: bool = False) -> Handle:
        """Apply deltas to a row subset (``MatrixWorkerTable::Add(rows)``).

        With the ``default`` updater duplicate row ids accumulate (true
        scatter-add). Stateful updaters (adagrad/momentum/adam) require
        unique row ids per call — pre-aggregate duplicates first (the
        reference's client-side Aggregator role).
        """
        ids = np.asarray(row_ids, dtype=np.int32)
        self._check_ids(ids)
        deltas = np.asarray(deltas)
        if deltas.shape != (len(ids), self.num_cols):
            raise ValueError(f"deltas shape {deltas.shape} != "
                             f"({len(ids)}, {self.num_cols})")
        deltas = chaos_corrupt("table.add", deltas)
        self._record_op("add", deltas.size,
                        deltas.size * self.dtype.itemsize)
        _health.observe_update(self, deltas)
        if self.updater.name in ("default", "sgd"):
            if self.updater.name == "sgd":
                # stateless: scatter-add of -lr*delta, duplicate-safe
                lr = float(option.learning_rate if option is not None
                           else self.default_option.learning_rate)
                deltas = -lr * deltas
            if self._scatter_add.layout == "sharded":
                sl_ids, valid, _inv, _n, sl_d = self._pad_ids_sharded(
                    ids, deltas, sort=True)
                self.param = self._scatter_add(self.param, sl_ids, sl_d,
                                               valid)
            else:
                padded, _, _, pd = self._pad_ids(ids, deltas, sort=True)
                self.param = self._scatter_add(self.param, padded, pd)
        else:
            if len(np.unique(ids)) != len(ids):
                raise ValueError(
                    f"add_rows with stateful updater "
                    f"{self.updater.name!r} requires unique row ids; "
                    "pre-aggregate duplicates (Aggregator role)")
            opt = self._resolve_option(option)
            padded, mask, _, pd = self._pad_ids(ids, deltas)
            self.param, self.state = self._gather_apply_scatter(
                self.param, self.state, padded, pd, mask, opt)
        handle = Handle(table=self, generation=self._bump_step())
        if sync:
            handle.wait()
        return handle

    def _check_ids(self, ids: np.ndarray) -> None:
        if len(ids) == 0:
            raise ValueError("empty row id list")
        if ids.min() < 0 or ids.max() >= self.num_rows:
            raise ValueError(f"row ids out of range [0, {self.num_rows}): "
                             f"min={ids.min()} max={ids.max()}")
