"""KVTable: fixed-capacity hashed key→value table.

Reference: `include/multiverso/table/kv_table.h` (upstream layout;
SURVEY.md §3.3, confidence [M]) — a hash-map ``key→T`` table for
unbounded/sparse feature spaces (logistic regression with hashed
features), keys partitioned across servers by hash.

TPU design (SURVEY.md §3.9 / §8 hard-part #4): XLA wants static shapes,
so the open hash becomes a **bucketed cuckoo-free hash in fixed int32
arrays**: ``num_buckets × slots_per_bucket`` slots, each bucket probed
fully vectorized (no data-dependent while loops on the device). The
bucket axis is sharded over the mesh model axis — hash→bucket IS the
reference's hash→server partition.

- ``get(keys)``: one jitted gather+compare; missing keys return
  ``default_value`` and a found-mask.
- ``add(keys, deltas)``: slot assignment is a DEVICE-SIDE vectorized
  probe fused into the update program: a key takes its matching slot if
  present, else the first empty lane of its bucket — same-bucket new
  keys tie-break by batch order (a sort-free run-rank over the sorted
  bucket ids). Assignment is a pure function of (table state, batch), so
  under the SPMD collective contract (every process issues the same
  adds) multi-host processes stay in lockstep with NO host-side mirror.
  Bucket overflow drops the batch atomically on device and raises at
  the next table op (deferred — async adds stay fire-and-forget).

Values may be scalar (``value_dim=0``) or fixed-dim vectors.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.ft.chaos import chaos_corrupt
from multiverso_tpu.ops import table_kernels as tk
from multiverso_tpu.tables.base import (Handle, Table, _register,
                                        loadz_stream, pack_state,
                                        savez_stream, unpack_state)
# hashing helpers live in tables/hashing.py (shared with the kernel
# engine); re-imported here so historical `from kv_table import ...`
# call sites keep working
from multiverso_tpu.tables.hashing import (EMPTY_KEY, _bucket, _hash_u64,
                                           _join_keys, _split_keys,
                                           shard_lane_slices)
from multiverso_tpu.telemetry import health as _health
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.telemetry.profiling import profiled_jit
from multiverso_tpu.updaters import (AddOption, get_updater,
                                     resolve_default_option)
from multiverso_tpu.utils import configure, log


@dataclasses.dataclass
class KVTableOption:
    capacity: int
    value_dim: int = 0
    dtype: Any = "float32"
    slots_per_bucket: int = 8
    updater: Optional[str] = None
    name: str = "kv_table"
    shard_update: bool = False   # data-axis updater-state sharding


@dataclasses.dataclass
class PreparedKVAdd:
    """One Add batch with host prep done and operands staged on device
    (H2D already issued): the unit the async staging pipeline hands
    between its prepare thread and the dispatching thread."""
    buckets: Any        # device int32 [b]   (b = pow2 bucket of n);
    #                     sharded layout: int32 [shards, L] LOCAL ids
    query: Any          # device uint32 [b, 2]   (sharded: [shards, L, 2])
    deltas: Any         # device [b(, D)]        (sharded: [shards, L(, D)])
    valid: Any          # device bool [b]        (sharded: [shards, L])
    option: AddOption   # device-leaved (resolved at prepare time)
    elems: int
    nbytes: int
    #: operand layout this batch was prepped for — must match the
    #: engine's ``KernelEngine.layout`` ("flat" | "sharded")
    layout: str = "flat"
    #: host copy of the batch's GLOBAL bucket ids (sorted, no padding)
    #: — kept alongside the deferred overflow flag so a later raise can
    #: name the overflowing buckets, not just count keys
    host_buckets: Any = None


class KVTable:
    """Fixed-capacity hashed table. Not a dense-array Table subclass —
    storage is (keys, values, state) triple — but implements the same
    get/add/store/load contract and registers a table id."""

    #: subclasses that break the kernel engine's operand contract (the
    #: tiered store re-sorts lanes at dispatch) keep the plain XLA
    #: closures and skip the Pallas factories entirely
    ALLOW_PALLAS = True

    def __init__(self, capacity: int, value_dim: int = 0,
                 dtype: Any = "float32", *, slots_per_bucket: int = 8,
                 updater: Optional[str] = None,
                 mesh: Optional[Mesh] = None, name: str = "kv_table",
                 default_value: float = 0.0,
                 default_option: Optional[AddOption] = None,
                 shard_update: bool = False) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.name = name
        self.mesh = mesh if mesh is not None else core.mesh()
        self.value_dim = value_dim
        self.dtype = jnp.dtype(dtype)
        self.slots = slots_per_bucket
        self.default_value = default_value
        updater_name = updater if updater is not None \
            else configure.get_flag("updater_type")
        self.updater = get_updater(updater_name)
        self.default_option = resolve_default_option(updater_name,
                                                     default_option)
        self._option_lock = threading.Lock()
        self.generation = 0
        # client-pipeline hooks (see tables/base.py) — shared by
        # unbound-method assignment below, like _record_op
        self._view_refs: list = []
        self._coalescer_refs: list = []

        shards = self.mesh.shape[core.MODEL_AXIS]
        dp = dict(self.mesh.shape).get(core.DATA_AXIS, 1)
        # arXiv:2004.13336 for the KV updater state: the state leaves
        # (adagrad/adam accumulators) refine over the data axis too, so
        # optimizer memory per device shrinks by dp — same contract as
        # Table.shard_update for the dense tables (base.py)
        self.shard_update = bool(shard_update) and dp > 1
        bucket_mult = shards * dp if self.shard_update else shards
        buckets = -(-capacity // self.slots)
        self.num_buckets = -(-buckets // bucket_mult) * bucket_mult
        self.capacity = self.num_buckets * self.slots
        self._shards = shards
        # bucket→shard ownership is contiguous equal blocks (shard s
        # owns [s*bps, (s+1)*bps)), so a sort by bucket IS a sort by
        # shard-then-bucket — the invariant the sharded lane slicer and
        # the per-shard Pallas grids both stand on
        self._buckets_per_shard = self.num_buckets // shards

        kv_shape = (self.num_buckets, self.slots)
        val_shape = kv_shape + ((value_dim,) if value_dim else ())
        self._key_sharding = NamedSharding(
            self.mesh, P(core.MODEL_AXIS, None, None))
        self._val_sharding = NamedSharding(
            self.mesh, P(core.MODEL_AXIS, *([None] * (len(val_shape) - 1))))
        self._state_sharding = NamedSharding(
            self.mesh, P((core.MODEL_AXIS, core.DATA_AXIS),
                         *([None] * (len(val_shape) - 1)))) \
            if self.shard_update else self._val_sharding
        # 64-bit keys are stored as two uint32 planes (hi, lo): with
        # jax_enable_x64 off, uint64 device arrays silently canonicalize to
        # uint32, aliasing keys that share low 32 bits.
        self.keys = jax.device_put(
            np.full(kv_shape + (2,), 0xFFFFFFFF, dtype=np.uint32),
            self._key_sharding)
        self.values = jax.device_put(
            np.full(val_shape, default_value, dtype=self.dtype),
            self._val_sharding)
        self.state = jax.tree.map(
            lambda s: jax.device_put(s, self._state_sharding),
            self.updater.init_state(self.values))
        self._pending_over: list = []  # deferred overflow flags (device
        # scalars, one per in-flight add; drained non-blocking in add,
        # blocking at every other table op)
        self._build_jits()
        # checkpoint-export copier, built lazily on the first export
        self._export_copy = None
        # read-replica copier (keys+values only), lazy like _export_copy
        self._kv_snapshot_copy = None
        self.table_id = _register(self)  # type: ignore[arg-type]
        lbl = f"{self.table_id}:{self.name}"
        self._h_get = telemetry.histogram(
            "table.get.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        self._h_add = telemetry.histogram(
            "table.add.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        log.debug("kv table %r: %d buckets x %d slots (capacity %d)",
                  name, self.num_buckets, self.slots, self.capacity)

    def _build_jits(self) -> None:
        replicated = NamedSharding(self.mesh, P(None))

        def lookup(keys_arr, values_arr, query, buckets):
            # keys_arr: (B, S, 2) uint32; query: (n, 2) uint32
            slots = jnp.take(keys_arr, buckets, axis=0)        # (n, S, 2)
            vals = jnp.take(values_arr, buckets, axis=0)       # (n, S[, D])
            match = (slots == query[:, None, :]).all(axis=-1)  # (n, S)
            found = match.any(axis=1)
            m = match if vals.ndim == 2 else match[..., None]
            picked = jnp.sum(jnp.where(m, vals, 0), axis=1)
            fill = found if vals.ndim == 2 else found[:, None]
            picked = jnp.where(fill, picked,
                               jnp.asarray(self.default_value, vals.dtype))
            return picked, found

        n_slots = self.slots
        scalar_sh = NamedSharding(self.mesh, P())
        state_sh = jax.tree.map(lambda _: self._state_sharding, self.state)
        # the Pallas engines slice state like values (model axis only);
        # data-axis-refined state (shard_update) and subclasses that
        # re-sort lanes at dispatch (tiered) keep the XLA closures
        allow_pallas = self.ALLOW_PALLAS and not self.shard_update

        def probe_update(keys_arr, values_arr, state, buckets, query,
                         deltas, valid, option):
            """Fused slot probe + updater + scatter. The probe is the
            reference's hash-bucket insertion vectorized: match lane if
            the key is present, else the (rank+1)-th empty lane where
            rank = this key's position among the batch's NEW keys of the
            same bucket (deterministic batch-order tie-break, computed
            by a run-rank over the sorted bucket ids — no host state).
            Unplaced keys (bucket overflow) get an out-of-range slot and
            their scatters DROP; the count comes back for the host to
            raise on.

            ``valid`` masks PADDING lanes: batch lengths are bucketed to
            powers of two (prepare_add), so variable-size adds reuse a
            bounded set of compiled signatures instead of retracing per
            length. Padded lanes carry the EMPTY sentinel as query (can
            only ever match empty slots — a reserved key), are excluded
            from ranks and the overflow count, and are forced to the
            out-of-range slot so every one of their scatters drops."""
            rows = jnp.take(keys_arr, buckets, axis=0)       # (n, S, 2)
            match = (rows == query[:, None, :]).all(-1)      # (n, S)
            matched = match.any(axis=1)
            mlane = jnp.argmax(match, axis=1)
            empty = (rows == jnp.uint32(0xFFFFFFFF)).all(-1)
            new = ~matched & valid
            # rank among same-bucket new keys, in batch order
            perm = jnp.argsort(buckets, stable=True)
            b_s = jnp.take(buckets, perm)
            new_s = jnp.take(new, perm).astype(jnp.int32)
            csx = jnp.cumsum(new_s) - new_s                  # exclusive
            bound = jnp.concatenate(
                [jnp.ones(1, bool), b_s[1:] != b_s[:-1]])
            base = jax.lax.cummax(jnp.where(bound, csx, -1))
            rank_s = csx - base
            rank = jnp.zeros_like(rank_s).at[perm].set(rank_s)
            # (rank+1)-th empty lane of the bucket
            ecs = jnp.cumsum(empty.astype(jnp.int32), axis=1)
            hit = empty & (ecs == (rank + 1)[:, None])
            placed_new = hit.any(axis=1)
            elane = jnp.argmax(hit, axis=1)
            ok = matched | placed_new
            n_over = jnp.sum(~ok & valid)
            slot = jnp.where(matched, mlane, elane)
            # all-or-nothing: ANY overflow voids the whole batch (the
            # raise must leave the table untouched) — out-of-range slots
            # make every scatter drop; padding lanes always drop
            slot = jnp.where(ok & valid & (n_over == 0), slot, n_slots)
            keys_arr = keys_arr.at[buckets, slot].set(query)
            safe = jnp.minimum(slot, n_slots - 1)
            old = values_arr[buckets, safe]
            old_state = jax.tree.map(lambda s: s[buckets, safe], state)
            upd, new_state = self.updater.apply(old, old_state, deltas,
                                                option)
            values_arr = values_arr.at[buckets, slot].set(
                upd.astype(values_arr.dtype))
            state = jax.tree.map(
                lambda s, ns: s.at[buckets, slot].set(ns.astype(s.dtype)),
                state, new_state)
            return keys_arr, values_arr, state, n_over

        @partial(jax.jit, out_shardings=scalar_sh)
        def count_live(keys_arr):
            return jnp.sum(~(keys_arr == jnp.uint32(0xFFFFFFFF))
                           .all(-1))

        # the sharded XLA adapters: lane-sliced (shards, L, ...) operands
        # flattened shard-major with bucket ids globalized (local +
        # s*bps). Shard-major flattening of the per-shard bucket-sorted
        # slices stays GLOBALLY bucket-sorted (each shard's padding
        # parks on its local max bucket bps-1 → global (s+1)*bps-1,
        # still below the next shard's first bucket), so the XLA
        # argsort-rank tie-break sees the same lane order as the flat
        # path and the results are bit-identical. These are both the
        # runtime-fallback target of the sharded Pallas engine and the
        # MVTPU_KERNELS=xla comparison lane the parity tests drive.
        bps = self._buckets_per_shard
        offs = jnp.arange(self._shards, dtype=jnp.int32)[:, None] * bps

        def lookup_sharded(keys_arr, values_arr, query, buckets, inv):
            gb = (buckets + offs).reshape(-1)
            picked, found = lookup(keys_arr, values_arr,
                                   query.reshape(-1, 2), gb)
            return (jnp.take(picked, inv, axis=0),
                    jnp.take(found, inv, axis=0))

        def probe_update_sharded(keys_arr, values_arr, state, buckets,
                                 query, deltas, valid, option):
            shards, lanes = buckets.shape
            gb = (buckets + offs).reshape(-1)
            d = deltas.reshape((shards * lanes,) + deltas.shape[2:])
            return probe_update(keys_arr, values_arr, state, gb,
                                query.reshape(-1, 2), d,
                                valid.reshape(-1), option)

        # profiled: profile.calls{fn=kv.lookup/kv.apply.<name>} are the
        # Get/Add dispatch counts the client pipeline's coalescing and
        # caching claims are asserted against. All paths register
        # behind the kernel engine (MVTPU_KERNELS): the XLA closures
        # above stay the fallback, the Pallas engine (same signatures,
        # bit-equal results — tests/test_table_kernels.py) keeps each
        # bucket's slot rows in VMEM and replaces the batch-wide argsort
        # with the in-kernel per-bucket scan; on a multi-device mesh the
        # sharded forms run the same per-shard grids under shard_map.
        # The Pallas engine's dispatches land on
        # profile.calls{fn=....pallas}.
        self._lookup = tk.select_kernel(
            f"kv.lookup.{self.name}",
            xla=profiled_jit(
                lookup, name=f"kv.lookup.{self.name}",
                out_shardings=(replicated, replicated)),
            pallas=None if not allow_pallas else lambda: profiled_jit(
                tk.build_kv_lookup(
                    slots=self.slots, value_dim=self.value_dim,
                    default_value=self.default_value,
                    interpret=tk.interpret_mode()),
                name=f"kv.lookup.{self.name}.pallas",
                out_shardings=(replicated, replicated)),
            pallas_sharded=None if not allow_pallas else lambda: profiled_jit(
                tk.build_kv_lookup_sharded(
                    slots=self.slots, value_dim=self.value_dim,
                    default_value=self.default_value,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS,
                    num_buckets=self.num_buckets),
                name=f"kv.lookup.{self.name}.pallas",
                out_shardings=(replicated, replicated)),
            xla_sharded=lambda: profiled_jit(
                lookup_sharded, name=f"kv.lookup.{self.name}",
                out_shardings=(replicated, replicated)),
            mesh=self.mesh)
        self._probe_update = tk.select_kernel(
            f"kv.apply.{self.name}",
            xla=profiled_jit(
                probe_update, name=f"kv.apply.{self.name}",
                donate_argnums=(0, 1, 2),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh, scalar_sh)),
            pallas=None if not allow_pallas else lambda: profiled_jit(
                tk.build_kv_probe_update(
                    slots=self.slots, value_dim=self.value_dim,
                    updater=self.updater, state_template=self.state,
                    interpret=tk.interpret_mode()),
                name=f"kv.apply.{self.name}.pallas",
                donate_argnums=(0, 1, 2),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh, scalar_sh)),
            pallas_sharded=None if not allow_pallas else lambda: profiled_jit(
                tk.build_kv_probe_update_sharded(
                    slots=self.slots, value_dim=self.value_dim,
                    updater=self.updater, state_template=self.state,
                    interpret=tk.interpret_mode(), mesh=self.mesh,
                    axis=core.MODEL_AXIS,
                    num_buckets=self.num_buckets),
                name=f"kv.apply.{self.name}.pallas",
                donate_argnums=(0, 1, 2),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh, scalar_sh)),
            xla_sharded=lambda: profiled_jit(
                probe_update_sharded, name=f"kv.apply.{self.name}",
                donate_argnums=(0, 1, 2),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh, scalar_sh)),
            mesh=self.mesh)
        self._count_live = count_live

    def _buckets_of(self, keys: np.ndarray) -> np.ndarray:
        return (_hash_u64(keys) % np.uint64(self.num_buckets)).astype(
            np.int32)

    def _check_keys(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim != 1 or len(keys) == 0:
            raise ValueError("keys must be a non-empty 1-D array")
        if (keys == EMPTY_KEY).any():
            raise ValueError(f"key {EMPTY_KEY} is the reserved empty "
                             "sentinel")
        return keys

    def _raise_overflow(self, n_over: int, bucket_ids=None) -> None:
        where = ""
        if bucket_ids:
            shown = ", ".join(str(b) for b in bucket_ids[:16])
            more = "" if len(bucket_ids) <= 16 \
                else f" (+{len(bucket_ids) - 16} more)"
            where = f"; bucket id(s) at capacity for the batch: " \
                    f"[{shown}]{more}"
        raise RuntimeError(
            f"kv table {self.name!r}: {n_over} keys overflowed their "
            f"buckets in a previous add (configured capacity "
            f"{self.capacity} keys = {self.capacity // self.slots} "
            f"buckets x {self.slots} slots{where}; the batch was "
            "dropped "
            "atomically); raise capacity or slots_per_bucket. NOTE: "
            "the dropped add still advanced the table generation and "
            "option step (its buffers were swapped; overflow is only "
            "known after device execution) — re-issue the dropped "
            "batch after resizing")

    def _overflowing_buckets(self, host_buckets) -> list:
        """Cold path behind an overflow raise: name the buckets that
        could not take the dropped batch. A bucket is flagged when the
        batch's key demand plus its CURRENT fill exceeds ``slots`` —
        an upper bound (keys already present match in place and need
        no new slot), but the dropped batch left fill untouched, so
        the true overflowing bucket is always in the list."""
        if host_buckets is None or len(host_buckets) == 0:
            return []
        ub, cnt = np.unique(np.asarray(host_buckets, np.int64),
                            return_counts=True)
        rows = np.asarray(jnp.take(
            self.keys, jnp.asarray(ub, jnp.int32), axis=0))
        fill = (~(rows == np.uint32(0xFFFFFFFF)).all(-1)).sum(-1)
        return [int(b) for b in ub[(fill + cnt) > self.slots]]

    @staticmethod
    def _over_entry(entry):
        """``_pending_over`` entries are ``(flag, host_buckets)`` pairs;
        a bare flag (the pre-tiering contract, still poked in by tests
        and tools) reads as a pair with no bucket context."""
        return entry if isinstance(entry, tuple) else (entry, None)

    def _drain_overflow(self, entries) -> None:
        n_over = 0
        bucket_ids: set = set()
        for entry in entries:
            flag, host_buckets = self._over_entry(entry)
            n = int(np.asarray(flag))
            if n:
                n_over += n
                bucket_ids.update(self._overflowing_buckets(host_buckets))
        if n_over:
            self._raise_overflow(n_over, sorted(bucket_ids))

    def _check_overflow(self) -> None:
        """Raise any pending overflow from previous async adds —
        BLOCKING (drains every in-flight flag). Called by every table
        op except ``add``: their own D2H results already serialize
        behind the in-flight updates, so the extra readback costs
        nothing; the overflowed batches were dropped atomically on
        device, so the table is consistent."""
        pending, self._pending_over = self._pending_over, []
        self._drain_overflow(pending)

    def _poll_overflow(self) -> None:
        """Non-blocking drain for the ``add`` hot path: only flags whose
        device scalar is already computed are inspected, so back-to-back
        ``add(sync=False)`` calls keep pipelining (a blocking readback
        here would cap the async queue at depth 1 — the exact
        serialization the deferral exists to avoid). A flag with no
        ``is_ready`` attribute stays DEFERRED (treated as still in
        flight): readiness is unknowable without a blocking
        ``np.asarray`` readback, and every non-add table op drains it
        through :meth:`_check_overflow` anyway."""
        still, ready = [], []
        for entry in self._pending_over:
            is_ready = getattr(self._over_entry(entry)[0], "is_ready",
                               None)
            (ready if is_ready is not None and is_ready()
             else still).append(entry)
        self._pending_over = still
        self._drain_overflow(ready)

    # -- API ---------------------------------------------------------------

    # per-table op accounting + client-pipeline hooks, shared with the
    # dense Table hierarchy (KVTable is contract-compatible, not a
    # subclass)
    _record_op = Table._record_op
    _attach_view = Table._attach_view
    _attach_coalescer = Table._attach_coalescer
    _notify_views = Table._notify_views
    flush_coalesced = Table.flush_coalesced

    def get_jax(self, keys) -> Tuple[jax.Array, jax.Array]:
        """Device-resident batched lookup → (values, found_mask) as
        device arrays (futures — dispatch is async; nothing blocks until
        the caller reads them back).

        Query lengths are bucketed to powers of two like adds (padded
        lanes carry the EMPTY sentinel and are sliced off), so variable
        query sizes share compiled signatures."""
        self._check_overflow()
        keys = self._check_keys(keys)
        return self._get_with_buckets(keys, self._buckets_of(keys))

    def _get_with_buckets(self, keys: np.ndarray,
                          lane_buckets: np.ndarray):
        """Dispatch half of a Get for pre-hashed per-lane bucket ids in
        DEVICE geometry — the seam the tiered store drives after
        translating logical buckets to resident device slots
        (``storage/tiered_kv.py``); :meth:`get_jax` is the identity
        translation."""
        n = len(keys)
        t0 = time.monotonic()
        with tracing.span("table.get",
                          table=f"{self.table_id}:{self.name}", n=n,
                          engine=self._lookup.engine):
            elems = n * max(self.value_dim, 1)
            self._record_op("get", elems, elems * self.dtype.itemsize)
            if self._lookup.layout == "sharded":
                out = self._get_jax_sharded(keys, lane_buckets, n)
                self._h_get.observe(time.monotonic() - t0)
                return out
            b = _bucket(n)
            query = np.full((b, 2), 0xFFFFFFFF, np.uint32)
            query[:n] = _split_keys(keys)
            buckets = np.zeros(b, np.int32)
            buckets[:n] = lane_buckets
            vals, found = self._lookup(
                self.keys, self.values,
                core.place(query, mesh=self.mesh),
                core.place(buckets, mesh=self.mesh))
            if b != n:  # padding lanes (sentinel query) sliced away
                vals, found = vals[:n], found[:n]
        self._h_get.observe(time.monotonic() - t0)
        return vals, found

    def _get_jax_sharded(self, keys: np.ndarray,
                         lane_buckets: np.ndarray, n: int):
        """Lane-sliced Get prep for the sharded engine: sort lanes by
        owning shard, hand each shard its dense row of local bucket ids
        + queries, and an ``inv`` map (flat ``shard*L + pos`` indices,
        pow2-padded) that unpermutes the per-shard results back to
        caller order."""
        bps = self._buckets_per_shard
        shard_ids = lane_buckets // bps
        order = np.argsort(shard_ids, kind="stable")
        sshard = shard_ids[order]
        local = (lane_buckets[order] - sshard * bps).astype(np.int32)
        (sl_local, sl_query), _valid, pos = shard_lane_slices(
            sshard, self._shards, [local, _split_keys(keys[order])],
            [np.int32(bps - 1), np.uint32(0xFFFFFFFF)])
        lanes = sl_local.shape[1]
        inv = np.zeros(_bucket(n), np.int32)
        inv[order] = (sshard * lanes + pos).astype(np.int32)
        mput = lambda a: core.place(
            a, P(core.MODEL_AXIS, *([None] * (a.ndim - 1))),
            mesh=self.mesh)
        vals, found = self._lookup(
            self.keys, self.values, mput(sl_query), mput(sl_local),
            core.place(inv, mesh=self.mesh))
        if len(inv) != n:
            vals, found = vals[:n], found[:n]
        return vals, found

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        """Batched lookup → (values, found_mask). Missing keys yield
        ``default_value`` (the reference's KV semantics: absent = initial
        value). Blocks on the device→host readback; use
        :meth:`get_async` / :meth:`get_jax` to keep the hot loop
        non-blocking."""
        vals, found = self.get_jax(keys)
        return np.asarray(vals), np.asarray(found)

    def get_async(self, keys) -> Handle:
        """Non-blocking Get: a handle wrapping the DEVICE (values,
        found) pair; ``wait()`` returns the device arrays once computed
        (the true-async variant of the reference's ``GetAsync``)."""
        return Handle(self.get_jax(keys))

    def prepare_add(self, keys, deltas,
                    option: Optional[AddOption] = None) -> "PreparedKVAdd":
        """Host-side half of an Add: validate, hash, split, and STAGE the
        batch onto the device (H2D), without touching table state.

        Safe to run on a worker thread while the device applies a
        previous batch — the double-buffered upload seam
        (:class:`multiverso_tpu.client.KVStagingWriter` drives it). The
        AddOption (lr/step) is resolved HERE, at prepare time.

        The batch is PADDED to a power-of-two length (masked lanes carry
        the EMPTY sentinel and drop on device), so variable-size adds
        share a bounded set of compiled signatures — without it every
        distinct length recompiles the fused probe program.

        Lanes are stable-SORTED by bucket: the Pallas probe engine needs
        same-bucket lanes on consecutive grid steps (its per-bucket scan
        replaces the XLA path's global argsort), and the XLA path is
        lane-order-insensitive (its rank tie-break is batch order, which
        a stable sort preserves within each bucket) — so the final table
        state is identical either way."""
        keys, deltas, lane_buckets, opt = self._prep_host_add(
            keys, deltas, option)
        return self._pack_prepared(keys, deltas, lane_buckets, opt)

    def _prep_host_add(self, keys, deltas,
                       option: Optional[AddOption] = None):
        """Placement-independent host half of :meth:`prepare_add`:
        validate, hash, stable-sort by bucket, resolve the AddOption.
        Returns host arrays sorted by THIS table's bucket ids — device
        geometry here; LOGICAL geometry in the tiered subclass, which
        defers packing until its dispatch half has faulted the buckets
        in and can translate them to device slots."""
        keys = self._check_keys(keys)
        uniq = np.unique(keys)
        if len(uniq) != len(keys):
            raise ValueError("duplicate keys in one add; pre-aggregate")
        deltas = np.asarray(deltas)
        n = len(keys)
        want = (n, self.value_dim) if self.value_dim else (n,)
        if deltas.shape != want:
            raise ValueError(f"deltas shape {deltas.shape} != {want}")
        deltas = chaos_corrupt("table.add", deltas)
        lane_buckets = self._buckets_of(keys)
        order = np.argsort(lane_buckets, kind="stable")
        opt = (option or self.default_option).as_jax(self.mesh)
        return keys[order], deltas[order], lane_buckets[order], opt

    def _pack_prepared(self, keys: np.ndarray, deltas: np.ndarray,
                       lane_buckets: np.ndarray,
                       opt: AddOption) -> "PreparedKVAdd":
        """Pack bucket-sorted host lanes into the selected engine's
        operand layout and STAGE them on device (H2D). ``lane_buckets``
        must be DEVICE-geometry bucket ids, sorted ascending with
        per-bucket batch order preserved (what :meth:`_prep_host_add`
        returns for a non-tiered table)."""
        n = len(keys)
        if self._probe_update.layout == "sharded":
            # bucket ownership is contiguous equal blocks, so the sort
            # above already grouped lanes by owning shard (in shard
            # order) with each shard's lanes bucket-sorted — exactly
            # what shard_lane_slices and the per-shard grids need
            bps = self._buckets_per_shard
            shard_ids = lane_buckets // bps
            local = (lane_buckets - shard_ids * bps).astype(np.int32)
            (sl_local, sl_query, sl_deltas), valid, _pos = \
                shard_lane_slices(
                    shard_ids, self._shards,
                    [local, _split_keys(keys), deltas],
                    [np.int32(bps - 1), np.uint32(0xFFFFFFFF), 0])
            mput = lambda a: core.place(
                a, P(core.MODEL_AXIS, *([None] * (a.ndim - 1))),
                mesh=self.mesh)
            return PreparedKVAdd(
                buckets=mput(sl_local), query=mput(sl_query),
                deltas=mput(sl_deltas), valid=mput(valid), option=opt,
                elems=int(deltas.size),
                nbytes=int(deltas.size) * self.dtype.itemsize,
                layout="sharded", host_buckets=lane_buckets)
        b = _bucket(n)
        query = np.full((b, 2), 0xFFFFFFFF, np.uint32)
        query[:n] = _split_keys(keys)
        # padding lanes park on the LAST bucket so the sorted-by-bucket
        # invariant holds across them (they never write — valid=False)
        buckets = np.full(b, self.num_buckets - 1, np.int32)
        buckets[:n] = lane_buckets
        pdeltas = np.zeros((b,) + deltas.shape[1:], deltas.dtype)
        pdeltas[:n] = deltas
        valid = np.zeros(b, bool)
        valid[:n] = True
        put = lambda a: core.place(a, mesh=self.mesh)
        return PreparedKVAdd(buckets=put(buckets), query=put(query),
                             deltas=put(pdeltas), valid=put(valid),
                             option=opt, elems=int(deltas.size),
                             nbytes=int(deltas.size) * self.dtype.itemsize,
                             host_buckets=lane_buckets)

    def add_prepared(self, prepared: "PreparedKVAdd",
                     sync: bool = False) -> Handle:
        """Device half of an Add: dispatch one staged batch through the
        fused probe+updater program. Must run on the thread that owns
        the table (it swaps the live buffers)."""
        self._poll_overflow()
        t0 = time.monotonic()
        with tracing.span("table.add",
                          table=f"{self.table_id}:{self.name}",
                          engine=self._probe_update.engine, sync=sync):
            self._record_op("add", prepared.elems, prepared.nbytes)
            _health.observe_update(self, prepared.deltas)
            self.keys, self.values, self.state, n_over = \
                self._probe_update(
                    self.keys, self.values, self.state,
                    prepared.buckets, prepared.query, prepared.deltas,
                    prepared.valid, prepared.option)
            self._pending_over.append((n_over, prepared.host_buckets))
            _health.observe_param(self, self.values)
            with self._option_lock:
                self.default_option.step += 1
                self.generation += 1
                gen = self.generation
            self._notify_views()
            handle = Handle(table=self, generation=gen)
            if sync:
                handle.wait()
                self._check_overflow()
        self._h_add.observe(time.monotonic() - t0)
        return handle

    def add(self, keys, deltas, option: Optional[AddOption] = None,
            sync: bool = False) -> Handle:
        """Batched upsert-through-updater.

        Duplicate keys within one batch must be pre-aggregated (the
        client-side Aggregator role) — they raise otherwise.
        :class:`multiverso_tpu.client.CoalescingBuffer` does that
        pre-aggregation (and batches K adds into one dispatch).

        On bucket overflow the batch is dropped atomically ON DEVICE and
        the error surfaces at a later table op; the returned Handle and
        the option step still advance (overflow is unknowable at
        dispatch time without serializing the async queue).
        """
        self._poll_overflow()
        return self.add_prepared(self.prepare_add(keys, deltas, option),
                                 sync=sync)

    def wait(self) -> None:
        jax.block_until_ready(self._live_buffers())
        self._check_overflow()

    def _live_buffers(self):
        return (self.keys, self.values, self.state)

    def _live_value(self):
        return self.values

    def __len__(self) -> int:
        """Number of live keys (device count — there is no host mirror)."""
        self._check_overflow()
        return int(np.asarray(self._count_live(self.keys)))

    def snapshot_kv_async(self):
        """Light async copy of (keys, values) for read replicas: jitted
        device copies that survive the next add's donation, returned as
        futures for an off-thread ``np.asarray``. Unlike
        :meth:`export_checkpoint_async` this does NOT flush coalescers
        or drain overflow flags — it is a dispatch-thread hot-path call
        and must never block or raise for unrelated pending adds."""
        if self._kv_snapshot_copy is None:
            self._kv_snapshot_copy = jax.jit(
                lambda k, v: (jnp.copy(k), jnp.copy(v)),
                out_shardings=(self._key_sharding, self._val_sharding))
        return self._kv_snapshot_copy(self.keys, self.values)

    # -- checkpoint --------------------------------------------------------

    KV_MAGIC = "multiverso_tpu.kvtable.v1"

    def export_checkpoint_async(self):
        """Checkpoint export split like ``Table.export_checkpoint_async``:
        dispatch half here (flush, overflow check, jitted copies of the
        keys/values/state triple — the copies survive the next add's
        donation), blocking half in the returned ``finish()``."""
        # checkpoint contract: every issued delta lands, including ones
        # parked in attached coalescing buffers
        self.flush_coalesced()
        self._check_overflow()
        if self._export_copy is None:
            state_sh = jax.tree.map(lambda _: self._state_sharding,
                                    self.state)
            self._export_copy = jax.jit(
                lambda k, v, s: (jnp.copy(k), jnp.copy(v),
                                 jax.tree.map(jnp.copy, s)),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh))
        keys_fut, vals_fut, state_fut = self._export_copy(
            self.keys, self.values, self.state)
        manifest = {"magic": self.KV_MAGIC, "name": self.name,
                    "capacity": self.capacity, "value_dim": self.value_dim,
                    "slots": self.slots, "num_buckets": self.num_buckets,
                    "dtype": self.dtype.name, "updater": self.updater.name,
                    "step": self.default_option.step}

        def finish():
            host_keys = np.asarray(keys_fut)
            # lanes fill contiguously (no deletion), so fill = live count
            fill = (~(host_keys == 0xFFFFFFFF).all(-1)).sum(-1)
            payload = {"keys": host_keys,
                       "values": np.asarray(vals_fut),
                       "bucket_fill": fill.astype(np.int32)}
            manifest["n_state_leaves"] = pack_state(state_fut, payload)
            self._record_op("store", payload["values"].size,
                            sum(a.nbytes for a in payload.values()))
            return manifest, payload
        return finish

    def store(self, uri: str) -> None:
        # every rank writes (per-process targets need their own copy);
        # shared-path safety comes from the stream layer's atomic rename
        # — same rationale as tables/base.py store
        manifest, payload = self.export_checkpoint_async()()
        savez_stream(uri, manifest, payload)

    def load(self, uri: str) -> None:
        # buffered deltas refer to the PRE-load state — flush them into
        # it before the restore replaces the triple
        self.flush_coalesced()
        # load is a table op: a pending overflow surfaces HERE, before
        # the restore replaces the state it refers to (a post-load raise
        # about pre-load state would be spurious)
        self._check_overflow()
        manifest, data = loadz_stream(uri, self.KV_MAGIC)
        for field in ("value_dim", "dtype"):
            mine = getattr(self, field) if field != "dtype" \
                else self.dtype.name
            theirs = manifest[field]
            if theirs != mine:
                raise ValueError(
                    f"kv table {field} mismatch: checkpoint {theirs!r} != "
                    f"table {mine!r}")
        if manifest["updater"] != self.updater.name:
            raise ValueError(
                f"checkpoint updater {manifest['updater']!r} != "
                f"{self.updater.name!r}")
        new_buckets = self.num_buckets
        if manifest["num_buckets"] != self.num_buckets \
                or manifest["slots"] != self.slots:
            # mesh-portable restore: num_buckets is padded to the mesh
            # model-axis size at construction, so a checkpoint written on
            # mp=2 has a different geometry than an mp=1/4 table.  Dense
            # tables repad (base.py); here the live triples are rehashed
            # into the current geometry instead.
            new_buckets, host_keys, host_vals, host_state = \
                self._rehash_checkpoint(manifest, data)
            state_src = {f"state_{i}": leaf
                         for i, leaf in enumerate(host_state)}
        else:
            host_keys = data["keys"]
            host_vals = data["values"]
            state_src = data
        keys_dev = jax.device_put(host_keys, self._key_sharding)
        vals_dev = jax.device_put(host_vals.astype(self.dtype),
                                  self._val_sharding)
        state_dev = unpack_state(
            state_src, manifest["n_state_leaves"], self.state,
            lambda leaf, tmpl: jax.device_put(leaf.astype(tmpl.dtype),
                                              self._state_sharding))
        # commit only after every new array placed: an exception above
        # (missing state leaf, placement failure) must leave the live
        # table consistent — geometry fields changing ahead of the
        # arrays would make get()/add() silently address wrong slots
        self._record_op("load", data["values"].size,
                        data["keys"].nbytes + data["values"].nbytes)
        self.keys, self.values, self.state = keys_dev, vals_dev, state_dev
        if new_buckets != self.num_buckets:
            log.warn(
                "kv table %r: rehash from %dx%d into %dx%d overflowed a "
                "bucket; geometry auto-grown to %dx%d (capacity %d -> "
                "%d) so the restore succeeds",
                self.name, manifest["num_buckets"], manifest["slots"],
                self.num_buckets, self.slots, new_buckets, self.slots,
                self.capacity, new_buckets * self.slots)
            self.num_buckets = new_buckets
            self.capacity = new_buckets * self.slots
        # slot assignment is device-derived: nothing host-side to rebuild
        self.default_option.step = int(manifest.get("step", 0))
        # load replaces live state: outstanding add-handles read superseded
        with self._option_lock:
            self.generation += 1
        self._notify_views()

    def _rehash_checkpoint(self, manifest, data):
        """Re-insert a checkpoint's live (key, value, state) triples into
        THIS table's (num_buckets, slots) geometry.

        Host-side: a checkpoint restore is not a hot path, and the insert
        needs data-dependent bucket occupancy that a fixed-shape device
        program handles worse than numpy.  Lane order within a bucket is
        the checkpoint's bucket-major traversal order — deterministic,
        and lookup/probe semantics don't depend on lane order.

        If a bucket of the requested geometry would overflow (restores
        into a smaller mesh/geometry concentrate keys), the bucket count
        DOUBLES until every key fits — restores succeed with a larger
        table instead of failing (runtime probes stay one-bucket; a
        spill-to-second-choice design would tax every get/add instead of
        this cold path).  Doubling preserves the model-axis shard
        divisibility established at construction.  Returns the chosen
        bucket count WITHOUT mutating the table — load() commits the
        geometry only after the new arrays are safely placed on device,
        so a failure mid-restore can't leave geometry fields ahead of
        the arrays."""
        ck_keys = data["keys"]                        # [B0, S0, 2] u32
        live = ~(ck_keys == np.uint32(0xFFFFFFFF)).all(-1)
        bb, ss = np.nonzero(live)
        k2 = ck_keys[bb, ss]                          # [n, 2]
        hashes = _hash_u64(_join_keys(k2))
        n = len(hashes)
        nb = self.num_buckets
        # occupancy-only check per doubling — via unique, O(n) memory
        # regardless of nb (a bincount(minlength=nb) would allocate
        # gigabytes before the pathological-collision guard could
        # fire); the full lane assignment runs once, for the geometry
        # that fits
        while n and np.unique(hashes % np.uint64(nb),
                              return_counts=True)[1].max() > self.slots:
            if nb >= 2 ** 30:
                raise ValueError(
                    f"kv table {self.name!r}: rehash from "
                    f"{manifest['num_buckets']}x{manifest['slots']} "
                    f"cannot fit every bucket even at {nb} buckets of "
                    f"{self.slots} slot(s). At small slots_per_bucket "
                    "the bucket count needed for n keys grows like the "
                    "birthday bound (~n^2 at 1 slot) — construct the "
                    "restoring table with slots_per_bucket >= 4 "
                    "instead of relying on geometry growth")
            nb *= 2
        buckets = (hashes % np.uint64(nb)).astype(np.int32)
        order = np.argsort(buckets, kind="stable")
        sb = buckets[order]
        # lane = rank within each bucket run of the sorted order
        pos = np.arange(n)
        run_start = np.concatenate([[True], sb[1:] != sb[:-1]]) \
            if n else np.zeros(0, bool)
        lane = pos - np.maximum.accumulate(np.where(run_start, pos, 0))
        kv_shape = (nb, self.slots)
        new_keys = np.full(kv_shape + (2,), 0xFFFFFFFF, np.uint32)
        new_keys[sb, lane] = k2[order]

        def remap(arr, fill):
            out_shape = kv_shape + arr.shape[2:]
            out = np.full(out_shape, fill, arr.dtype)
            out[sb, lane] = arr[bb, ss][order]
            return out

        new_vals = remap(data["values"], self.default_value)
        new_state = [remap(data[f"state_{i}"], 0)
                     for i in range(manifest["n_state_leaves"])]
        return nb, new_keys, new_vals, new_state
