"""KVTable: fixed-capacity hashed key→value table.

Reference: `include/multiverso/table/kv_table.h` (upstream layout;
SURVEY.md §3.3, confidence [M]) — a hash-map ``key→T`` table for
unbounded/sparse feature spaces (logistic regression with hashed
features), keys partitioned across servers by hash.

TPU design (SURVEY.md §3.9 / §8 hard-part #4): XLA wants static shapes,
so the open hash becomes a **bucketed cuckoo-free hash in fixed int32
arrays**: ``num_buckets × slots_per_bucket`` slots, each bucket probed
fully vectorized (no data-dependent while loops on the device). The
bucket axis is sharded over the mesh model axis — hash→bucket IS the
reference's hash→server partition.

- ``get(keys)``: one jitted gather+compare; missing keys return
  ``default_value`` and a found-mask.
- ``add(keys, deltas)``: slot assignment (existing slot, else first free
  slot) is resolved host-side per batch — insertion-order races between
  duplicate new keys are a host concern, not a device loop — then one
  jitted scatter applies all updates. Bucket overflow raises.

Values may be scalar (``value_dim=0``) or fixed-dim vectors.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.tables.base import (Handle, Table, _register,
                                        loadz_stream, pack_state,
                                        savez_stream, unpack_state)
from multiverso_tpu.updaters import AddOption, get_updater
from multiverso_tpu.utils import configure, log

EMPTY_KEY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _split_keys(keys: np.ndarray) -> np.ndarray:
    """(n,) uint64 → (n, 2) uint32 [hi, lo] for device storage."""
    return np.stack([(keys >> np.uint64(32)).astype(np.uint32),
                     (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
                    axis=1)


def _join_keys(split: np.ndarray) -> np.ndarray:
    """(..., 2) uint32 [hi, lo] → (...,) uint64."""
    return (split[..., 0].astype(np.uint64) << np.uint64(32)) \
        | split[..., 1].astype(np.uint64)


def _hash_u64(keys: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stable key→bucket mix (host + device safe)."""
    x = keys.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


@dataclasses.dataclass
class KVTableOption:
    capacity: int
    value_dim: int = 0
    dtype: Any = "float32"
    slots_per_bucket: int = 8
    updater: Optional[str] = None
    name: str = "kv_table"


class KVTable:
    """Fixed-capacity hashed table. Not a dense-array Table subclass —
    storage is (keys, values, state) triple — but implements the same
    get/add/store/load contract and registers a table id."""

    def __init__(self, capacity: int, value_dim: int = 0,
                 dtype: Any = "float32", *, slots_per_bucket: int = 8,
                 updater: Optional[str] = None,
                 mesh: Optional[Mesh] = None, name: str = "kv_table",
                 default_value: float = 0.0,
                 default_option: Optional[AddOption] = None) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.name = name
        self.mesh = mesh if mesh is not None else core.mesh()
        self.value_dim = value_dim
        self.dtype = jnp.dtype(dtype)
        self.slots = slots_per_bucket
        self.default_value = default_value
        updater_name = updater if updater is not None \
            else configure.get_flag("updater_type")
        self.updater = get_updater(updater_name)
        self.default_option = default_option or AddOption()
        self._option_lock = threading.Lock()
        self.generation = 0

        shards = self.mesh.shape[core.MODEL_AXIS]
        buckets = -(-capacity // self.slots)
        self.num_buckets = -(-buckets // shards) * shards
        self.capacity = self.num_buckets * self.slots

        kv_shape = (self.num_buckets, self.slots)
        val_shape = kv_shape + ((value_dim,) if value_dim else ())
        self._key_sharding = NamedSharding(
            self.mesh, P(core.MODEL_AXIS, None, None))
        self._val_sharding = NamedSharding(
            self.mesh, P(core.MODEL_AXIS, *([None] * (len(val_shape) - 1))))
        # 64-bit keys are stored as two uint32 planes (hi, lo): with
        # jax_enable_x64 off, uint64 device arrays silently canonicalize to
        # uint32, aliasing keys that share low 32 bits.
        self.keys = jax.device_put(
            np.full(kv_shape + (2,), 0xFFFFFFFF, dtype=np.uint32),
            self._key_sharding)
        self.values = jax.device_put(
            np.full(val_shape, default_value, dtype=self.dtype),
            self._val_sharding)
        self.state = jax.tree.map(
            lambda s: jax.device_put(s, self._val_sharding),
            self.updater.init_state(self.values))
        # host-side mirror of key→(bucket, slot): authoritative slot
        # assignment (insertion decisions are host-side; device arrays are
        # the data plane). That mirror is PER-PROCESS: two hosts inserting
        # different keys would silently assign conflicting slots — fence
        # it off until insertion is deterministic from the key alone.
        if jax.process_count() > 1:
            raise NotImplementedError(
                "KVTable slot assignment is host-side and per-process; "
                "multi-host runs would silently desync. Use ArrayTable/"
                "MatrixTable for multi-host, or shard keys per host.")
        self._slot_map: Dict[int, Tuple[int, int]] = {}
        self._bucket_fill = np.zeros(self.num_buckets, dtype=np.int32)
        self._build_jits()
        self.table_id = _register(self)  # type: ignore[arg-type]
        log.debug("kv table %r: %d buckets x %d slots (capacity %d)",
                  name, self.num_buckets, self.slots, self.capacity)

    def _build_jits(self) -> None:
        replicated = NamedSharding(self.mesh, P(None))

        @partial(jax.jit, out_shardings=(replicated, replicated))
        def lookup(keys_arr, values_arr, query, buckets):
            # keys_arr: (B, S, 2) uint32; query: (n, 2) uint32
            slots = jnp.take(keys_arr, buckets, axis=0)        # (n, S, 2)
            vals = jnp.take(values_arr, buckets, axis=0)       # (n, S[, D])
            match = (slots == query[:, None, :]).all(axis=-1)  # (n, S)
            found = match.any(axis=1)
            m = match if vals.ndim == 2 else match[..., None]
            picked = jnp.sum(jnp.where(m, vals, 0), axis=1)
            fill = found if vals.ndim == 2 else found[:, None]
            picked = jnp.where(fill, picked,
                               jnp.asarray(self.default_value, vals.dtype))
            return picked, found

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def scatter_update(keys_arr, values_arr, state, buckets, slot_ids,
                           query, deltas, option):
            keys_arr = keys_arr.at[buckets, slot_ids].set(query)
            old = values_arr[buckets, slot_ids]
            old_state = jax.tree.map(lambda s: s[buckets, slot_ids], state)
            new, new_state = self.updater.apply(old, old_state, deltas,
                                                option)
            values_arr = values_arr.at[buckets, slot_ids].set(
                new.astype(values_arr.dtype))
            state = jax.tree.map(
                lambda s, ns: s.at[buckets, slot_ids].set(ns.astype(s.dtype)),
                state, new_state)
            return keys_arr, values_arr, state

        self._lookup = lookup
        self._scatter_update = scatter_update

    def _buckets_of(self, keys: np.ndarray) -> np.ndarray:
        return (_hash_u64(keys) % np.uint64(self.num_buckets)).astype(
            np.int32)

    def _check_keys(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim != 1 or len(keys) == 0:
            raise ValueError("keys must be a non-empty 1-D array")
        if (keys == EMPTY_KEY).any():
            raise ValueError(f"key {EMPTY_KEY} is the reserved empty "
                             "sentinel")
        return keys

    # -- API ---------------------------------------------------------------

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        """Batched lookup → (values, found_mask). Missing keys yield
        ``default_value`` (the reference's KV semantics: absent = initial
        value)."""
        keys = self._check_keys(keys)
        buckets = self._buckets_of(keys)
        vals, found = self._lookup(
            self.keys, self.values,
            core.place(_split_keys(keys), mesh=self.mesh),
            core.place(buckets, mesh=self.mesh))
        return np.asarray(vals), np.asarray(found)

    def add(self, keys, deltas, option: Optional[AddOption] = None,
            sync: bool = False) -> Handle:
        """Batched upsert-through-updater.

        Duplicate keys within one batch must be pre-aggregated (the
        client-side Aggregator role) — they raise otherwise.
        """
        keys = self._check_keys(keys)
        uniq = np.unique(keys)
        if len(uniq) != len(keys):
            raise ValueError("duplicate keys in one add; pre-aggregate")
        deltas = np.asarray(deltas)
        want = (len(keys), self.value_dim) if self.value_dim else (len(keys),)
        if deltas.shape != want:
            raise ValueError(f"deltas shape {deltas.shape} != {want}")

        # Two-pass slot assignment: plan first (no mutation), commit only
        # once the whole batch is known to fit — an overflow raise must not
        # leak slots or desynchronize the host mirror from device state.
        buckets = self._buckets_of(keys)
        slot_ids = np.empty(len(keys), dtype=np.int32)
        planned_fill: Dict[int, int] = {}
        new_assignments: Dict[int, Tuple[int, int]] = {}
        for i, (k, b) in enumerate(zip(keys.tolist(), buckets.tolist())):
            assigned = self._slot_map.get(k)
            if assigned is not None:
                slot_ids[i] = assigned[1]
                continue
            fill = planned_fill.get(b, int(self._bucket_fill[b]))
            if fill >= self.slots:
                raise RuntimeError(
                    f"kv table {self.name!r}: bucket {b} overflow "
                    f"({self.slots} slots); raise capacity or "
                    "slots_per_bucket")
            new_assignments[k] = (b, fill)
            planned_fill[b] = fill + 1
            slot_ids[i] = fill
        self._slot_map.update(new_assignments)
        for b, fill in planned_fill.items():
            self._bucket_fill[b] = fill

        opt = (option or self.default_option).as_jax(self.mesh)
        put = lambda a: core.place(a, mesh=self.mesh)
        self.keys, self.values, self.state = self._scatter_update(
            self.keys, self.values, self.state, put(buckets),
            put(slot_ids), put(_split_keys(keys)), put(deltas), opt)
        with self._option_lock:
            self.default_option.step += 1
            self.generation += 1
            gen = self.generation
        handle = Handle(table=self, generation=gen)
        if sync:
            handle.wait()
        return handle

    def wait(self) -> None:
        jax.block_until_ready(self._live_buffers())

    def _live_buffers(self):
        return (self.keys, self.values, self.state)

    def _live_value(self):
        return self.values

    def __len__(self) -> int:
        return len(self._slot_map)

    # -- checkpoint --------------------------------------------------------

    KV_MAGIC = "multiverso_tpu.kvtable.v1"

    def store(self, uri: str) -> None:
        payload = {"keys": np.asarray(self.keys),
                   "values": np.asarray(self.values),
                   "bucket_fill": self._bucket_fill}
        manifest = {"magic": self.KV_MAGIC, "name": self.name,
                    "capacity": self.capacity, "value_dim": self.value_dim,
                    "slots": self.slots, "num_buckets": self.num_buckets,
                    "dtype": self.dtype.name, "updater": self.updater.name,
                    "n_state_leaves": pack_state(self.state, payload),
                    "step": self.default_option.step}
        savez_stream(uri, manifest, payload)

    def load(self, uri: str) -> None:
        manifest, data = loadz_stream(uri, self.KV_MAGIC)
        for field in ("num_buckets", "slots", "value_dim", "dtype"):
            mine = getattr(self, field) if field != "dtype" \
                else self.dtype.name
            theirs = manifest[field]
            if theirs != mine:
                raise ValueError(
                    f"kv table {field} mismatch: checkpoint {theirs!r} != "
                    f"table {mine!r}")
        if manifest["updater"] != self.updater.name:
            raise ValueError(
                f"checkpoint updater {manifest['updater']!r} != "
                f"{self.updater.name!r}")
        host_keys = data["keys"]
        self.keys = jax.device_put(host_keys, self._key_sharding)
        self.values = jax.device_put(data["values"].astype(self.dtype),
                                     self._val_sharding)
        self.state = unpack_state(
            data, manifest["n_state_leaves"], self.state,
            lambda leaf, tmpl: jax.device_put(leaf.astype(tmpl.dtype),
                                              self._val_sharding))
        self._bucket_fill = data["bucket_fill"].copy()
        self._slot_map = {}
        joined = _join_keys(host_keys)
        for b in range(self.num_buckets):
            for s in range(int(self._bucket_fill[b])):
                self._slot_map[int(joined[b, s])] = (b, s)
        self.default_option.step = int(manifest.get("step", 0))
        # load replaces live state: outstanding add-handles read superseded
        with self._option_lock:
            self.generation += 1
