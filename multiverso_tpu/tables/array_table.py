"""ArrayTable: 1-D dense table.

Reference: `include/multiverso/table/array_table.h` (upstream layout;
SURVEY.md §3.3) — a 1-D dense ``T[]`` sharded in contiguous blocks across
servers, with whole-array Get/Add (``ArrayWorker<T>::Get(T*, size)``,
``Add(T*, size, AddOption*)``).

Here the contiguous-block-per-server sharding IS the array's
``NamedSharding`` over the mesh model axis; Get is a device→host copy (or
a zero-copy device view), Add is the jitted updater step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from jax.sharding import Mesh

from multiverso_tpu.tables.base import Table
from multiverso_tpu.updaters import AddOption


@dataclasses.dataclass
class ArrayTableOption:
    """``ArrayTableOption<T>`` analog for the create_table factory."""
    size: int
    dtype: Any = "float32"
    init_value: Any = 0
    updater: Optional[str] = None
    name: str = "array_table"
    shard_update: bool = False   # data-axis weight-update sharding


class ArrayTable(Table):
    def __init__(self, size: int, dtype: Any = "float32", *,
                 init_value: Any = 0, updater: Optional[str] = None,
                 mesh: Optional[Mesh] = None, name: str = "array_table",
                 default_option: Optional[AddOption] = None,
                 shard_update: bool = False) -> None:
        if size <= 0:
            raise ValueError(f"ArrayTable size must be positive, got {size}")
        super().__init__(name, (size,), dtype, updater=updater, mesh=mesh,
                         init_value=init_value, default_option=default_option,
                         shard_update=shard_update)

    @property
    def size(self) -> int:
        return self.logical_shape[0]
