"""Table layer: sharded parameter tables (SURVEY.md §3.3).

``create_table(option)`` is the TableFactory / ``MV_CreateTable<Option>``
analog (upstream `src/table_factory.cpp`): paired worker+server creation
collapses to constructing one sharded-array table; the option dataclass
type selects the table kind.
"""

from typing import Union

from multiverso_tpu.tables.base import (Handle, Table, get_table,
                                        num_tables, reset_tables)
from multiverso_tpu.tables.array_table import ArrayTable, ArrayTableOption
from multiverso_tpu.tables.matrix_table import MatrixTable, MatrixTableOption
from multiverso_tpu.tables.sparse_matrix_table import (SparseMatrixTable,
                                                       SparseMatrixTableOption)
from multiverso_tpu.tables.kv_table import KVTable, KVTableOption
from multiverso_tpu.tables.superstep import FusedSuperstep, make_superstep

TableOption = Union[ArrayTableOption, MatrixTableOption,
                    SparseMatrixTableOption, KVTableOption]


def create_table(option: TableOption):
    """``MV_CreateTable(option)``: construct the table kind selected by the
    option dataclass."""
    if isinstance(option, ArrayTableOption):
        return ArrayTable(option.size, option.dtype,
                          init_value=option.init_value,
                          updater=option.updater, name=option.name,
                          shard_update=option.shard_update)
    if isinstance(option, SparseMatrixTableOption):
        return SparseMatrixTable(option.num_rows, option.num_cols,
                                 option.dtype, init_value=option.init_value,
                                 updater=option.updater, name=option.name,
                                 tiled=option.tiled)
    if isinstance(option, MatrixTableOption):
        return MatrixTable(option.num_rows, option.num_cols, option.dtype,
                           init_value=option.init_value,
                           updater=option.updater, name=option.name,
                           shard_update=option.shard_update)
    if isinstance(option, KVTableOption):
        return KVTable(option.capacity, option.value_dim, option.dtype,
                       slots_per_bucket=option.slots_per_bucket,
                       updater=option.updater, name=option.name,
                       shard_update=option.shard_update)
    raise TypeError(f"unknown table option type {type(option).__name__}")


__all__ = [
    "ArrayTable", "ArrayTableOption", "FusedSuperstep", "Handle", "KVTable",
    "KVTableOption", "MatrixTable", "MatrixTableOption", "SparseMatrixTable",
    "SparseMatrixTableOption", "Table", "TableOption", "create_table",
    "get_table", "make_superstep", "num_tables", "reset_tables",
]
