"""Fused superstep: the SUPPORTED way for an app to run a custom jitted
update over table storage in one compiled program.

Why this exists (SURVEY.md §3.3/§3.9 and the round-1 review): on TPU the
Get → local-train → Add round-trip of the reference (SURVEY.md §4.2/§4.3)
wants to be ONE fused XLA program per dispatch — gathers, model math, and
scatter-updates compiled together so nothing round-trips through HBM
staging or host. The first-round apps each hand-rolled that pattern
(private ``jax.jit`` + direct ``table.param`` assignment), which bypassed
the table contract: step counters did not advance and donation/sharding
handling was copy-pasted. :class:`FusedSuperstep` moves that machinery
into the table layer:

- reads each table's live ``param`` (and updater ``state``) as donated
  carry inputs,
- pins output shardings to each table's ``NamedSharding`` (and optional
  shardings for app-local carries),
- resolves each table's :class:`AddOption` (traced pytree — no retrace on
  lr/step changes) and passes it to the body,
- writes results back and advances each table's step/generation counters,
  so :class:`multiverso_tpu.tables.base.Handle` semantics hold for fused
  updates exactly as for plain ``add``.

Body contract::

    body(params, states, locals_, options, *inputs)
        -> (new_params, new_states, new_locals, aux)

where ``params``/``states``/``options`` are tuples aligned with the
``tables`` argument, ``locals_`` is the app-local carry tuple (e.g. LDA's
doc-topic counts and z-assignments), ``inputs`` are per-call operands
(minibatches, RNG keys, lr arrays), and ``aux`` is any non-donated output
pytree (losses/metrics) or ``None``. The body runs under ``jax.jit`` —
use ``lax.scan`` for multi-minibatch supersteps.

Tables with stateless updaters thread ``states`` through unchanged (their
state is the empty pytree). Bodies that apply updater math should call
``table.updater.apply(param, state, delta, option)`` — the same pure
function ``add`` uses, so the fused path and the plain path share
semantics.

Kernel engine: bodies that gather/scatter table rows should use the
re-exported :func:`gather_rows` / :func:`row_scatter_add` /
:func:`coo_scatter_add` (from ``ops/table_kernels.py``) instead of raw
``jnp.take`` / ``.at[].add`` — they are traceable inside the fused jit
and route through the same ``MVTPU_KERNELS``-selected Pallas/XLA engine
as the plain table Get/Add paths, so a fused superstep picks up the
kernel engine with no other change. On sharded meshes the dispatch runs
under :func:`kernel_mesh_scope`, so those functional kernels shard_map
their Pallas grids over the model axis (masked-lane form — lane counts
are dynamic inside a trace, so no host-side lane slicing here).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax

from multiverso_tpu import core
from multiverso_tpu.ops import table_kernels as tk

# re-exported for superstep bodies (see module docstring): the
# engine-selected, trace-safe gather/scatter kernels
from multiverso_tpu.ops.table_kernels import (coo_scatter_add,
                                              gather_rows,
                                              row_scatter_add)
from multiverso_tpu.tables.base import Handle, Table
from multiverso_tpu.telemetry import health as _health
from multiverso_tpu.telemetry.profiling import profiled_jit
from multiverso_tpu.updaters import AddOption

__all__ = ["FusedSuperstep", "coo_scatter_add", "gather_rows",
           "make_superstep", "row_scatter_add"]


class FusedSuperstep:
    """A compiled fused update bound to one or more tables."""

    def __init__(self, tables: Sequence[Table],
                 body: Callable[..., Tuple[Any, Any, Any, Any]], *,
                 local_shardings: Any = None,
                 name: str = "superstep") -> None:
        if not tables:
            raise ValueError("FusedSuperstep needs at least one table")
        self.tables = tuple(tables)
        self.name = name
        self._last_generation: Optional[int] = None
        mesh0 = self.tables[0].mesh
        for t in self.tables[1:]:
            if t.mesh is not mesh0:
                raise ValueError(
                    f"superstep {name!r}: tables {self.tables[0].name!r} "
                    f"and {t.name!r} live on different meshes")

        param_sh = tuple(t.sharding for t in self.tables)
        state_sh = tuple(
            jax.tree.map(lambda _, t=t: t.state_sharding, t.state)
            for t in self.tables)

        # profiled_jit, not bare jax.jit: every app trains through a
        # superstep, so this is THE place the flight recorder learns
        # each program's lowering/compile wall time and HLO cost
        # (profile.* metrics keyed fn=superstep.<name>)
        def run(params, states, locals_, options, *inputs):
            return body(params, states, locals_, options, *inputs)

        self._run = profiled_jit(
            run, name=f"superstep.{name}", donate_argnums=(0, 1, 2),
            out_shardings=(param_sh, state_sh, local_shardings, None))

    def __call__(self, locals_: Any = (), *inputs: Any,
                 options: Optional[Sequence[Optional[AddOption]]] = None
                 ) -> Tuple[Any, Any]:
        """Dispatch one fused update.

        Returns ``(new_locals, aux)``; table params/states are written
        back in place and each table's step/generation advances. Dispatch
        is async (XLA) — use ``table.wait()`` or a returned value to
        fence.
        """
        if options is None:
            options = (None,) * len(self.tables)
        # client pipeline: buffered coalesced deltas must land BEFORE
        # the fused program reads (and donates) each table's storage —
        # applying them after would reorder updates across the superstep
        for t in self.tables:
            t.flush_coalesced()
        opts = tuple(t._resolve_option(o)
                     for t, o in zip(self.tables, options))
        params = tuple(t.param for t in self.tables)
        states = tuple(t.state for t in self.tables)
        # sharded meshes: the scope tells the in-trace functional kernels
        # which mesh/axis to shard_map their Pallas grids over (tracing
        # sees only abstract values — the mesh can't be inferred there)
        with tk.kernel_mesh_scope(self.tables[0].mesh, core.MODEL_AXIS):
            new_params, new_states, new_locals, aux = self._run(
                params, states, locals_, opts, *inputs)
        for t, p, s in zip(self.tables, new_params, new_states):
            t.param = p
            t.state = s
            # a fused dispatch IS one Get -> train -> Add round-trip per
            # table (SURVEY §4.2/§4.3), so it lands in the same per-table
            # accounting the plain get()/add() paths record — apps that
            # only ever train through supersteps (all of them) still show
            # table.get/add bytes on every registry snapshot
            elems = 1
            for d in t.logical_shape:
                elems *= int(d)
            nbytes = elems * t.dtype.itemsize
            t._record_op("get", elems, nbytes)
            t._record_op("add", elems, nbytes)
            # fused updates never pass through add(), so the numerics
            # audit samples the written-back storage here (stride-gated
            # inside observe_param; a no-op when health is off)
            _health.observe_param(t, p)
            gen = t._bump_step()
            if t is self.tables[0]:
                # mint from the returned generation (racing with
                # concurrent adds through self.tables[0].generation could
                # hand this superstep a LATER update's generation)
                self._last_generation = gen
        return new_locals, aux

    def handle(self) -> Handle:
        """An add-handle for this superstep's latest dispatch on the
        first table (all tables in one superstep advance together)."""
        if self._last_generation is None:
            raise RuntimeError(f"superstep {self.name!r} has not been "
                               "dispatched yet")
        return Handle(table=self.tables[0],
                      generation=self._last_generation)


def make_superstep(tables: Sequence[Table], body: Callable, *,
                   local_shardings: Any = None,
                   name: str = "superstep") -> FusedSuperstep:
    """Build a :class:`FusedSuperstep` over ``tables`` (see module doc)."""
    return FusedSuperstep(tables, body, local_shardings=local_shardings,
                          name=name)
