"""Table base: the Worker/Server table contract collapsed onto sharded
``jax.Array`` storage.

Reference mapping (upstream layout `include/multiverso/table_interface.h`,
`src/table.cpp`, `src/table_factory.cpp` — SURVEY.md §3.3/§3.9):

- ``WorkerTable::Get/Add/GetAsync/AddAsync/Wait`` → :meth:`Table.get`,
  :meth:`Table.add`, ``*_async`` variants returning :class:`Handle`,
  :meth:`Table.wait`. There is no Partition/ProcessReply machinery: the
  "partition across servers" is the array's ``NamedSharding``, and the
  request/reply round-trip is an XLA gather/scatter inside one compiled
  program.
- ``ServerTable::ProcessAdd`` (through the Updater) → a jitted
  ``(param, state, delta, option) -> (param, state)`` step with donated
  buffers, state sharded like params.
- ``ServerTable::Store/Load(Stream*)`` → :meth:`Table.store` /
  :meth:`Table.load` through the URI stream layer.
- ``TableFactory`` / ``MV_CreateTable(option)`` → :func:`create_table`
  dispatching on the option dataclass; tables registered process-wide
  with integer ids like the reference's table ids.

Sharding convention: tables shard their leading dimension over the mesh
``"model"`` axis (the analog of row-blocks across server shards). Sizes
that don't divide the shard count are zero-padded internally; the logical
size is preserved at the API boundary.
"""

from __future__ import annotations

import io
import json
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.ft.chaos import chaos_corrupt, chaos_point
from multiverso_tpu.io import open_stream
from multiverso_tpu.telemetry import health as _health
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry import trace as tracing
from multiverso_tpu.telemetry.profiling import profiled_jit
from multiverso_tpu.updaters import (AddOption, Updater, get_updater,
                                     resolve_default_option)
from multiverso_tpu.utils import configure, log

CHECKPOINT_MAGIC = "multiverso_tpu.table.v1"


def _payload_crc32(arr: np.ndarray) -> int:
    """CRC32 over an array's raw bytes (C order) — the per-array
    checksum ``savez_stream`` stamps and ``loadz_stream`` verifies."""
    import zlib
    return int(zlib.crc32(np.ascontiguousarray(arr).tobytes()))


def savez_stream(uri: str, manifest: Dict[str, Any],
                 payload: Dict[str, np.ndarray]) -> None:
    """Write an npz (manifest json + arrays) through the stream layer.

    The manifest is stamped with a per-array CRC32 (verified at load:
    a torn or bit-rotted checkpoint fails LOUDLY instead of silently
    corrupting a resumed run), and the stream write is guarded by the
    env-configured IO :class:`~multiverso_tpu.ft.retry.RetryPolicy`
    (transient faults — including chaos-injected ones — are retried
    with jittered backoff and ``retry.*`` telemetry)."""
    from multiverso_tpu.ft.retry import io_retry_policy
    manifest = dict(manifest)
    manifest["crc32"] = {k: _payload_crc32(v) for k, v in payload.items()}
    buf = io.BytesIO()
    np.savez(buf, manifest=json.dumps(manifest), **payload)
    data = buf.getvalue()

    def write() -> None:
        with open_stream(uri, "wb") as stream:
            stream.write(data)
    io_retry_policy("io.store").call(write)


def loadz_stream(uri: str, magic: str):
    """Read an npz through the stream layer; validate its manifest magic
    and (when present) the per-array CRC32 checksums.
    Returns (manifest dict, npz data)."""
    from multiverso_tpu.ft.retry import io_retry_policy

    def read() -> bytes:
        with open_stream(uri, "rb") as stream:
            return stream.read()
    data = np.load(io.BytesIO(io_retry_policy("io.load").call(read)),
                   allow_pickle=False)
    try:
        manifest = json.loads(str(data["manifest"]))
    except Exception:
        raise ValueError(f"{uri!r} is not a multiverso_tpu checkpoint "
                         "(no manifest)") from None
    if manifest.get("magic") != magic:
        raise ValueError(f"{uri!r}: checkpoint magic "
                         f"{manifest.get('magic')!r} != expected {magic!r}")
    # checksum verification: pre-CRC checkpoints (no "crc32" key) load
    # unverified for back-compat; anything stamped must match
    for key, want in (manifest.get("crc32") or {}).items():
        if key not in data:
            raise ValueError(
                f"{uri!r}: checkpoint is torn — manifest lists payload "
                f"{key!r} but the archive lacks it")
        got = _payload_crc32(data[key])
        if got != int(want):
            raise ValueError(
                f"{uri!r}: payload {key!r} checksum mismatch "
                f"(crc32 {got:#010x} != manifest {int(want):#010x}) — "
                "the checkpoint is torn or bit-rotted; use an older "
                "complete generation")
    return manifest, data


def pack_state(state: Any, payload: Dict[str, np.ndarray]) -> int:
    """Add updater-state leaves to a checkpoint payload as state_{i}.
    Returns the leaf count (for the manifest)."""
    leaves = jax.tree.leaves(state)
    for i, leaf in enumerate(leaves):
        payload[f"state_{i}"] = np.asarray(leaf)
    return len(leaves)


def unpack_state(data, n_leaves: int, template_state: Any, convert) -> Any:
    """Rebuild an updater-state pytree from checkpoint leaves.
    ``convert(leaf_np, template_leaf)`` places one leaf on device."""
    leaves = [data[f"state_{i}"] for i in range(n_leaves)]
    _, treedef = jax.tree.flatten(template_state)
    tmpl = jax.tree.leaves(template_state)
    return jax.tree.unflatten(
        treedef, [convert(l, t) for l, t in zip(leaves, tmpl)])


class Handle:
    """Async completion handle (the reference's Waiter, SURVEY.md §3.7):
    wraps dispatched device values; ``wait()`` blocks until they land.

    Contract (explicit, generation-based — no exception sniffing):

    - A **get-handle** wraps a stable snapshot buffer (never donated);
      ``wait()`` blocks on it and returns exactly that snapshot.
    - An **add-handle** records the table and the *generation* its update
      produced. Updates apply in program order, so by the time the
      table's current buffers are ready, every generation ≤ the current
      one has been applied. ``wait()`` on an add-handle therefore blocks
      on the table's live buffers and returns the CURRENT param value —
      which is the handle's own result only while the handle is the
      latest update; a superseded handle returns the newer state (use
      :meth:`superseded` to distinguish). The original buffer is never
      touched after donation.
    """

    def __init__(self, values: Any = None, *, table: "Table" = None,
                 generation: Optional[int] = None) -> None:
        if (values is None) == (table is None):
            raise ValueError("Handle wraps either snapshot values or a "
                             "(table, generation) pair")
        self._values = values
        self._table = table
        self._generation = generation

    @property
    def generation(self) -> Optional[int]:
        """The table generation this add-handle's update produced
        (None for get-handles)."""
        return self._generation

    def superseded(self) -> bool:
        """True when a later update has been applied to the table since
        this handle was issued: ``wait()`` will return the newer state."""
        return (self._table is not None
                and self._table.generation > self._generation)

    def done(self) -> bool:
        """Non-blocking completion check.

        WARNING (add-handles): reports readiness of the table's CURRENT
        buffers, consistent with :meth:`wait`'s generation contract — so
        ``done()`` is NOT monotonic: it can flip back to False when a
        LATER add is dispatched after this handle's update already
        landed. Poll ``done() or superseded()`` to ask "has *my* update
        been applied"."""
        values = self._values if self._table is None \
            else self._table._live_buffers()
        return all(getattr(v, "is_ready", lambda: True)()
                   for v in jax.tree.leaves(values))

    def wait(self) -> Any:
        if self._table is None:
            jax.block_until_ready(self._values)
            return self._values
        # program order: the current buffers being ready implies this
        # handle's generation has been applied
        jax.block_until_ready(self._table._live_buffers())
        return self._table._live_value()

    # the reference's GetAsync returns data through the waiting buffer;
    # here the handle carries the result.
    def result(self) -> Any:
        return self.wait()


class Table:
    """Base class owning one sharded param array (+ updater state)."""

    def __init__(self, name: str, shape: Tuple[int, ...], dtype: Any,
                 *, updater: Optional[str] = None,
                 mesh: Optional[Mesh] = None,
                 init_value: Any = 0,
                 default_option: Optional[AddOption] = None,
                 shard_update: bool = False) -> None:
        self.name = name
        self.mesh = mesh if mesh is not None else core.mesh()
        self.logical_shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        updater_name = updater if updater is not None \
            else configure.get_flag("updater_type")
        self.updater: Updater = get_updater(updater_name)
        self.default_option = resolve_default_option(updater_name,
                                                     default_option)
        self._option_lock = threading.Lock()
        # monotonically increasing update counter backing the Handle
        # generation contract (bumped on every applied update/load)
        self.generation = 0
        # client-pipeline hooks (weakrefs — a dropped CachedView or
        # CoalescingBuffer must not be pinned by its table):
        # views are woken on every generation bump so their background
        # refresh starts at the update, not at the next read; coalescers
        # are flushed by ops that must observe every buffered delta
        # (supersteps, store/load)
        self._view_refs: List[weakref.ref] = []
        self._coalescer_refs: List[weakref.ref] = []

        # weight-update sharding (cross-replica sharding of the weight
        # update, arXiv:2004.13336 — the ZeRO-2-on-TPU classic): shard
        # updater STATE (and so the state-update compute) over the data
        # axis too, instead of every data replica holding and updating
        # identical state. Costs ~one data-axis all-gather per add when
        # the param update needs the state; buys state memory and
        # update FLOPs divided by dp. Opt-in: best for whole-table adds
        # (the DP gradient push); row-streamed adds pay the gather per
        # call.
        dp = dict(self.mesh.shape).get(core.DATA_AXIS, 1)
        self.shard_update = bool(shard_update) and dp > 1

        # pad leading dim to a multiple of the model-axis size — and of
        # the model*data product under shard_update (subclasses override
        # _pad_lead to reserve scratch rows); dense checkpoints repad
        # across differing padded shapes, so the flag stays portable
        shards = self.mesh.shape[core.MODEL_AXIS]
        lead = self.logical_shape[0] if self.logical_shape else 1
        lead_mult = shards * dp if self.shard_update else shards
        padded_lead = self._pad_lead(lead, lead_mult)
        self.padded_shape = (padded_lead,) + self.logical_shape[1:]
        # physical layout of the param array; subclasses may re-tile it
        # (storage_shape != padded_shape) while keeping the 2-D logical
        # contract — checkpoints always serialize the PADDED shape
        self.storage_shape = self.padded_shape
        self.spec = P(core.MODEL_AXIS, *([None] * (len(shape) - 1)))
        self.sharding = NamedSharding(self.mesh, self.spec)
        state_spec = P((core.MODEL_AXIS, core.DATA_AXIS),
                       *([None] * (len(shape) - 1))) \
            if self.shard_update else self.spec
        self.state_sharding = NamedSharding(self.mesh, state_spec)

        init = np.full(self.padded_shape, init_value, dtype=self.dtype) \
            if np.isscalar(init_value) else self._pad(np.asarray(init_value))
        self.param = jax.device_put(init, self.sharding)
        # state leaves are zeros_like(param) shaped -> param sharding,
        # refined over the data axis under shard_update
        self.state = jax.tree.map(
            lambda s: jax.device_put(s, self.state_sharding),
            self.updater.init_state(self.param))
        state_sh = jax.tree.map(lambda _: self.state_sharding, self.state)
        # profiled_jit, not bare jax.jit: profile.calls{fn=table.apply.*}
        # is THE dispatch count of the Add path — the client pipeline's
        # coalescing contract ("K buffered adds -> 1 apply dispatch") is
        # asserted against it in tests and the micro-bench
        self._apply = profiled_jit(
            self.updater.apply, name=f"table.apply.{name}",
            donate_argnums=(0, 1),
            out_shardings=(self.sharding, state_sh))

        # whole-table snapshot: logical region, REPLICATED output (the
        # all-gather is the reference's whole-table Get; a replicated
        # result is also host-readable on every process of a multi-host
        # run, where a model-sharded array is not fully addressable)
        replicated = NamedSharding(
            self.mesh, P(*([None] * len(self.padded_shape))))
        slices = tuple(slice(0, l) for l in self.logical_shape)

        def snapshot(param):
            # jnp.copy guarantees a fresh buffer even when the slice is
            # the whole array and shardings coincide — the snapshot must
            # survive the next add's donation of the live buffer
            return jnp.copy(param[slices])

        # profiled: profile.calls{fn=table.snapshot.*} counts whole-table
        # Get dispatches — the number a CachedView exists to shrink
        self._snapshot = profiled_jit(snapshot,
                                      name=f"table.snapshot.{name}",
                                      out_shardings=replicated)
        # checkpoint-export copier, built lazily on the first export
        # (tables that never checkpoint pay nothing)
        self._export_copy = None
        self.table_id = _register(self)
        lbl = f"{self.table_id}:{self.name}"
        # tail-latency histograms over the dispatch paths (the SLO
        # monitor's table.{get,add}.p99 targets)
        self._h_get = telemetry.histogram(
            "table.get.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        self._h_add = telemetry.histogram(
            "table.add.seconds", telemetry.LATENCY_BUCKETS, table=lbl)
        log.debug("table %r id=%d shape=%s padded=%s updater=%s", name,
                  self.table_id, self.logical_shape, self.padded_shape,
                  self.updater.name)

    # -- helpers -----------------------------------------------------------

    def _record_op(self, op: str, elems: int, nbytes: int) -> None:
        """Per-table op accounting: ``table.<op>.{ops,elems,bytes}``
        keyed by table id (the telemetry spine's hot-path
        instrumentation — counts what the Get/Add/Store/Load contract
        actually moved). Shared by KVTable (not a subclass) via
        unbound-method assignment — only needs table_id + name."""
        lbl = f"{self.table_id}:{self.name}"
        telemetry.counter(f"table.{op}.ops", table=lbl).inc()
        telemetry.counter(f"table.{op}.elems", table=lbl).inc(int(elems))
        telemetry.counter(f"table.{op}.bytes", table=lbl).inc(int(nbytes))

    def _pad_lead(self, lead: int, shards: int) -> int:
        return -(-lead // shards) * shards

    def _pad(self, arr: np.ndarray) -> np.ndarray:
        if arr.shape == self.padded_shape:
            return arr.astype(self.dtype, copy=False)
        if arr.shape != self.logical_shape:
            raise ValueError(f"table {self.name!r}: value shape {arr.shape} "
                             f"!= table shape {self.logical_shape}")
        pad = [(0, p - l) for p, l in zip(self.padded_shape, arr.shape)]
        return np.pad(arr.astype(self.dtype, copy=False), pad)

    def _resolve_option(self, option: Optional[AddOption]) -> AddOption:
        opt = option if option is not None else self.default_option
        return opt.as_jax(self.mesh)

    def _bump_step(self) -> int:
        """Advance step + generation; returns the new generation. Handles
        must be minted from the RETURNED value — reading self.generation
        afterwards races with concurrent adds (a handle could carry a
        later add's generation and never read as superseded)."""
        with self._option_lock:
            self.default_option.step += 1
            self.generation += 1
            gen = self.generation
        self._notify_views()
        return gen

    # -- client-pipeline hooks (multiverso_tpu.client) ---------------------

    def _attach_view(self, view: Any) -> None:
        """Register a CachedView for update notification (weakref)."""
        self._view_refs.append(weakref.ref(view))

    def _attach_coalescer(self, buf: Any) -> None:
        """Register a CoalescingBuffer so flush-demanding table ops
        (supersteps, store/load) can force its buffered deltas out."""
        self._coalescer_refs.append(weakref.ref(buf))

    def _notify_views(self) -> None:
        """Wake attached CachedViews: the generation advanced, so their
        background refresh should start NOW rather than at the next
        read. Must stay cheap — it runs on every applied update."""
        refs = self._view_refs
        if not refs:
            return
        live = []
        for r in refs:
            v = r()
            if v is not None:
                v._on_table_update()
                live.append(r)
        self._view_refs[:] = live

    def flush_coalesced(self) -> None:
        """Flush every attached CoalescingBuffer's pending deltas into
        the table. Called by ops whose contract requires observing all
        prior adds (fused supersteps before they read/donate ``param``,
        store/load around checkpoints); plain ``get`` does NOT call this
        — a buffered delta is invisible until its flush, the bounded-
        staleness semantics coalescing opts into."""
        refs = self._coalescer_refs
        if not refs:
            return
        live = []
        for r in refs:
            b = r()
            if b is not None:
                b.flush()
                live.append(r)
        self._coalescer_refs[:] = live

    # -- the Get/Add contract ---------------------------------------------

    def raw(self) -> jax.Array:
        """The padded device array — a LIVE view of table storage: the next
        ``add`` donates this buffer to XLA, invalidating the reference.
        Use :meth:`get_jax` for a stable snapshot."""
        return self.param

    def put_raw(self, padded: jax.Array) -> None:
        """Replace table storage with a device value of the STORAGE shape
        (placed to the table's sharding). The supported way for apps to
        install computed initial state (e.g. LDA's count build); advances
        the generation so outstanding add-handles read as superseded.
        Updater state is untouched."""
        if tuple(padded.shape) != self.storage_shape:
            raise ValueError(
                f"table {self.name!r}: put_raw shape {tuple(padded.shape)} "
                f"!= storage shape {self.storage_shape}")
        if padded.dtype != self.dtype:
            raise ValueError(
                f"table {self.name!r}: put_raw dtype {padded.dtype} != "
                f"table dtype {self.dtype}")
        self.param = jax.device_put(padded, self.sharding)
        with self._option_lock:
            self.generation += 1
        self._notify_views()

    def get_jax(self) -> jax.Array:
        """Device-resident logical value (slices off padding), replicated.

        Returns a fresh buffer: ``add`` donates the param buffer, so a
        zero-copy view would be invalidated by the next update.
        """
        chaos_point("table.get")
        t0 = time.monotonic()
        with tracing.span("table.get",
                          table=f"{self.table_id}:{self.name}"):
            elems = int(np.prod(self.logical_shape)) \
                if self.logical_shape else 1
            self._record_op("get", elems, elems * self.dtype.itemsize)
            _health.observe_param(self)
            out = self._snapshot(self.param)
        self._h_get.observe(time.monotonic() - t0)
        return out

    def get(self) -> np.ndarray:
        """Whole-table fetch to host (``WorkerTable::Get``)."""
        return np.asarray(self.get_jax())

    def get_async(self) -> Handle:
        """Non-blocking whole-table Get: the returned handle wraps the
        DEVICE snapshot (a future — dispatch is async), so nothing
        round-trips to host unless the caller converts the waited value
        (``np.asarray(h.wait())``)."""
        return Handle(self.get_jax())

    def add(self, delta: Any, option: Optional[AddOption] = None,
            sync: bool = False) -> Handle:
        """``WorkerTable::Add``: fold a delta through the updater.

        Dispatch is asynchronous (XLA async dispatch); ``sync=True`` blocks
        until the update has been applied, matching the reference's
        blocking Add.
        """
        chaos_point("table.add")
        delta = chaos_corrupt("table.add", delta)
        t0 = time.monotonic()
        with tracing.span("table.add",
                          table=f"{self.table_id}:{self.name}",
                          sync=sync):
            if isinstance(delta, jax.Array):
                if delta.shape == self.logical_shape \
                        and self.logical_shape != self.padded_shape:
                    pad = [(0, p - l) for p, l in zip(self.padded_shape,
                                                      delta.shape)]
                    delta = jnp.pad(delta, pad)
                elif delta.shape != self.padded_shape:
                    if delta.shape != self.logical_shape:
                        raise ValueError(
                            f"table {self.name!r}: delta shape "
                            f"{delta.shape} != table shape "
                            f"{self.logical_shape}")
            else:
                delta = self._pad(np.asarray(delta))
            if self.storage_shape != self.padded_shape:
                # re-tiled storage layouts (SparseMatrixTable
                # tiled=True): same elements, tile-aligned shape
                delta = delta.reshape(self.storage_shape)
            elems = int(np.prod(self.logical_shape)) \
                if self.logical_shape else 1
            self._record_op("add", elems, elems * self.dtype.itemsize)
            _health.observe_update(self, delta)
            opt = self._resolve_option(option)
            self.param, self.state = self._apply(self.param, self.state,
                                                 delta, opt)
            _health.observe_param(self)
            handle = Handle(table=self, generation=self._bump_step())
            if sync:
                handle.wait()
        self._h_add.observe(time.monotonic() - t0)
        return handle

    add_async = add

    def wait(self) -> None:
        """Block until all outstanding updates on this table are applied."""
        jax.block_until_ready(self._live_buffers())

    def _live_buffers(self) -> Any:
        """The buffers an add-handle's wait() blocks on (KVTable adds its
        key store)."""
        return (self.param, self.state)

    def _live_value(self) -> Any:
        """What an add-handle's wait() returns: the current param array."""
        return self.param

    # -- checkpoint (ServerTable::Store/Load) ------------------------------

    def _manifest(self) -> Dict[str, Any]:
        return {
            "magic": CHECKPOINT_MAGIC,
            "kind": type(self).__name__,
            "name": self.name,
            "logical_shape": list(self.logical_shape),
            "padded_shape": list(self.padded_shape),
            "dtype": self.dtype.name,
            "updater": self.updater.name,
            "step": self.default_option.step,
        }

    def _install_param(self, host_padded: np.ndarray) -> None:
        """Place a host array of the padded shape into table storage."""
        self.param = jax.device_put(
            host_padded.reshape(self.storage_shape), self.sharding)

    def export_checkpoint_async(self):
        """The checkpoint export, split along the thread-safety line
        (the :class:`~multiverso_tpu.ft.checkpoint.RunCheckpointManager`
        overlap contract, same split as ``client/cache.py``):

        - the DISPATCH half runs here, on the caller's (table dispatch)
          thread: flush attached coalescers, then launch one jitted
          copy of param + state into fresh buffers — the copies survive
          the next add's donation, and under ``shard_update`` the state
          gathers to the model-only sharding (per-process addressable),
        - the returned ``finish()`` closure is the BLOCKING half, safe
          on a worker thread: D2H waits, payload assembly, accounting.

        ``finish()`` returns ``(manifest, payload)`` ready for
        :func:`savez_stream`.
        """
        # a checkpoint must contain every delta the worker has issued,
        # including ones still parked in attached coalescing buffers
        self.flush_coalesced()
        manifest = self._manifest()
        if self._export_copy is None:
            state_sh = jax.tree.map(lambda _: self.sharding, self.state)
            self._export_copy = jax.jit(
                lambda p, s: (jnp.copy(p),
                              jax.tree.map(jnp.copy, s)),
                out_shardings=(self.sharding, state_sh))
        param_fut, state_fut = self._export_copy(self.param, self.state)

        def finish():
            payload = {"param": np.asarray(param_fut)
                       .reshape(self.padded_shape)}
            manifest["n_state_leaves"] = pack_state(state_fut, payload)
            self._record_op("store", payload["param"].size,
                            sum(a.nbytes for a in payload.values()))
            return manifest, payload
        return finish

    def store(self, uri: str) -> None:
        """Serialize param + updater state through the stream layer.

        Multi-process: COLLECTIVE — every rank runs the export fetch (a
        device collective) and every rank writes, so per-process targets
        (mem://, per-host local disks) each get a copy; on a shared
        filesystem the identical payloads land via the stream layer's
        atomic rename, so same-path writers never interleave."""
        manifest, payload = self.export_checkpoint_async()()
        savez_stream(uri, manifest, payload)

    def load(self, uri: str) -> None:
        # buffered deltas refer to the PRE-load state — flush them into
        # it before the restore replaces param/state (dropping them
        # silently, or applying them onto restored state, would both be
        # wrong orders)
        self.flush_coalesced()
        manifest, data = loadz_stream(uri, CHECKPOINT_MAGIC)
        if tuple(manifest["logical_shape"]) != self.logical_shape:
            raise ValueError(
                f"checkpoint shape {manifest['logical_shape']} != table "
                f"shape {list(self.logical_shape)}")
        if manifest["updater"] != self.updater.name:
            raise ValueError(
                f"checkpoint updater {manifest['updater']!r} != table "
                f"updater {self.updater.name!r}")
        def repad(arr: np.ndarray, want_shape, want_dtype):
            # slice to the logical region, then pad to the current padded
            # shape — the checkpoint may come from a different shard count
            if arr.shape != want_shape:
                arr = arr[tuple(slice(0, l) for l in self.logical_shape)]
                pad = [(0, p - l) for p, l in zip(want_shape, arr.shape)]
                arr = np.pad(arr, pad)
            return arr.astype(want_dtype)

        n_leaves = int(manifest["n_state_leaves"])
        self._record_op("load", data["param"].size,
                        data["param"].nbytes + sum(
                            data[f"state_{i}"].nbytes
                            for i in range(n_leaves)))
        self._install_param(repad(data["param"], self.padded_shape,
                                  self.dtype))
        self.state = unpack_state(
            data, n_leaves, self.state,
            lambda leaf, tmpl: jax.device_put(
                repad(leaf, tmpl.shape, tmpl.dtype), self.state_sharding))
        self.default_option.step = int(manifest.get("step", 0))
        # load replaces live state: outstanding add-handles must read as
        # superseded (generation contract: bumped on every applied
        # update/load)
        with self._option_lock:
            self.generation += 1
        self._notify_views()


# -- process-wide table registry (TableFactory / table ids) ---------------

_TABLES: List[Table] = []
_REG_LOCK = threading.Lock()


def _register(table: Table) -> int:
    with _REG_LOCK:
        _TABLES.append(table)
        return len(_TABLES) - 1


def get_table(table_id: int) -> Table:
    with _REG_LOCK:
        return _TABLES[table_id]


def num_tables() -> int:
    with _REG_LOCK:
        return len(_TABLES)


def reset_tables() -> None:
    """Drop all registered tables (tests / shutdown)."""
    with _REG_LOCK:
        _TABLES.clear()
