"""Tiered KV storage: billion-key tables across device HBM, pinned
host RAM, and disk (ROADMAP Open item 3).

The capacity analogue of arXiv:2004.13336's optimizer-state sharding:
put each bucket where it fits, move only what the step touches. See
``tiered_kv.py`` for the table, ``manager.py`` for placement policy,
``tiers.py`` for the host arena + CRC-stamped disk spill file, and
the README "Tiered storage" section for the knobs.
"""

from multiverso_tpu.storage.manager import (TIER_DEVICE, TIER_DISK,
                                            TIER_HOST, TIER_VIRGIN,
                                            TierConfig, TierManager,
                                            status_all)
from multiverso_tpu.storage.tiered_kv import TieredKVTable
from multiverso_tpu.storage.tiers import (BucketRecord, DiskTier,
                                          HostTier, RecordSpec)

__all__ = [
    "BucketRecord", "DiskTier", "HostTier", "RecordSpec",
    "TIER_DEVICE", "TIER_DISK", "TIER_HOST", "TIER_VIRGIN",
    "TierConfig", "TierManager", "TieredKVTable", "status_all",
]
