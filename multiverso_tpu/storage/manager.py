"""TierManager: placement bookkeeping + promotion/demotion policy for
one tiered KV table.

The manager owns WHERE every logical bucket lives — device slot, host
arena row, disk slot, or nowhere yet ("virgin": a bucket no add ever
touched is all-empty by construction and costs no IO to materialize —
cold start is free). It never touches device memory itself: the
owning :class:`~multiverso_tpu.storage.tiered_kv.TieredKVTable` runs
the gathers/scatters on its single dispatch thread and drives the
manager through ``plan → demote* → fetch/assign*`` (see
``ensure_resident`` there), so placement mutations inherit the table's
threading contract for free.

Victim selection is telemetry-driven: each bucket carries an access
EWMA (the shared :func:`multiverso_tpu.telemetry.health.ewma_step`
window rule, decayed lazily — idle buckets pay nothing per op) and the
coldest resident bucket outside the current batch is demoted first;
the same scores pick which warm bucket spills when the host arena
fills.

Telemetry (all labeled ``table=<name>``):
``storage.hits{tier=device}``, ``storage.misses{tier=host|disk|virgin}``,
``storage.fills{tier=...}``/``storage.promotions{tier=...}`` (same
event, both names), ``storage.demotions{tier=host|disk}``,
``storage.spills`` and ``storage.bytes{dir=spill|fill,tier=disk}``
(from the disk tier), plus the /statusz tier table via
:func:`status_all`.
"""

from __future__ import annotations

import dataclasses
import os
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from multiverso_tpu.control import knobs as _knobs
from multiverso_tpu.storage.tiers import (BucketRecord, DiskTier,
                                          HostTier, RecordSpec)
from multiverso_tpu.telemetry import metrics as telemetry
from multiverso_tpu.telemetry.health import ewma_step
from multiverso_tpu.utils import log

# tier codes, also what tiered checkpoints record per bucket
TIER_DEVICE = 0
TIER_HOST = 1
TIER_DISK = 2
TIER_VIRGIN = 3

TIER_NAMES = {TIER_DEVICE: "device", TIER_HOST: "host",
              TIER_DISK: "disk", TIER_VIRGIN: "virgin"}

# env knobs (see README "Tiered storage")
TIER_DEVICE_ENV = "MVTPU_TIER_DEVICE_BUCKETS"
TIER_HOST_ENV = "MVTPU_TIER_HOST_BUCKETS"
TIER_DIR_ENV = "MVTPU_TIER_DIR"
TIER_ALPHA_ENV = "MVTPU_TIER_ALPHA"

_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()


def _knob_int(name: str, default: int) -> int:
    """Env-seeded knob read with the tier layer's forgiving error
    handling (a malformed env var degrades to the default, it does
    not kill table construction)."""
    try:
        return int(_knobs.initial(name, default))
    except ValueError as e:
        log.warn("%s; using %d", e, default)
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warn("ignoring non-float %s=%r", name, raw)
        return default


@dataclasses.dataclass
class TierConfig:
    """Budgets + policy knobs for one tiered table. ``from_env`` reads
    the ``MVTPU_TIER_*`` environment, with explicit arguments taking
    precedence (the benchmark passes budgets directly)."""
    device_buckets: int
    host_buckets: int
    spill_dir: str
    alpha: float = 0.25

    @classmethod
    def from_env(cls, total_buckets: int,
                 device_buckets: Optional[int] = None,
                 host_buckets: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 alpha: Optional[float] = None) -> "TierConfig":
        if device_buckets is None:
            device_buckets = _knob_int("storage.device_buckets",
                                       total_buckets)
        if host_buckets is None:
            host_buckets = _knob_int("storage.host_buckets",
                                     max(total_buckets // 4, 1))
        if spill_dir is None:
            spill_dir = os.environ.get(TIER_DIR_ENV, "").strip() \
                or os.path.join("/tmp", "mvtpu_tiers")
        if alpha is None:
            alpha = _env_float(TIER_ALPHA_ENV, 0.25)
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"tier EWMA alpha {alpha} outside (0, 1]")
        return cls(device_buckets=int(device_buckets),
                   host_buckets=int(host_buckets),
                   spill_dir=spill_dir, alpha=float(alpha))


@dataclasses.dataclass
class ResidencyPlan:
    """What one batch needs moved: demote ``victims`` (device →
    host/disk cascade), then fill ``fills`` into the freed/free
    slots."""
    victims: np.ndarray   # logical bucket ids currently device-resident
    fills: np.ndarray     # logical bucket ids to fault in


class TierManager:
    """Placement state machine for ``total_buckets`` logical buckets
    over a ``device_buckets``-slot device tier, a host arena, and a
    disk spill file."""

    def __init__(self, name: str, total_buckets: int,
                 config: TierConfig, spec: RecordSpec) -> None:
        if config.device_buckets <= 0:
            raise ValueError(
                f"device budget {config.device_buckets} buckets <= 0")
        self.name = name
        self.total_buckets = int(total_buckets)
        self.device_buckets = min(int(config.device_buckets),
                                  self.total_buckets)
        # the physical slot count above is frozen at construction
        # (arrays below are sized by it); the control plane moves a
        # soft BUDGET underneath it — plan() evicts down to the
        # budget, never past the batch's own working set
        self.device_budget = self.device_buckets
        _knobs.bind("storage.device_buckets", self, "device_budget",
                    label=name)
        self.config = config
        self.spec = spec
        self.tier = np.full(self.total_buckets, TIER_VIRGIN, np.int8)
        self.slot_of = np.full(self.total_buckets, -1, np.int32)
        self.bucket_at = np.full(self.device_buckets, -1, np.int64)
        self._slot_used = np.zeros(self.device_buckets, bool)
        self._free_slots: List[int] = list(
            range(self.device_buckets - 1, -1, -1))
        self.host = HostTier(config.host_buckets, spec)
        spill_path = os.path.join(config.spill_dir, f"{name}.spill")
        for other in list(_MANAGERS):
            if getattr(other.disk, "path", None) == spill_path:
                # two LIVE tables writing one spill file silently
                # corrupt each other; a restart reusing the dead
                # table's path is fine (load() rewrites the file)
                log.warn(
                    "tier manager %r: spill path %s is already in use "
                    "by a live manager — give one table a distinct "
                    "name or spill_dir", name, spill_path)
        self.disk = DiskTier(spill_path, spec)
        self.alpha = config.alpha
        # per-bucket access EWMA, decayed lazily: score[b] is exact as
        # of stamp[b]; the effective score at clock t is
        # score * (1-alpha)^(t-stamp) — dt stacked ewma_step(·, 0, α)
        # updates without ever sweeping all total_buckets entries
        self._score = np.zeros(self.total_buckets, np.float32)
        self._stamp = np.zeros(self.total_buckets, np.int64)
        self._clock = 0
        # live-key counts of demoted buckets, recorded at demote time
        # (lanes are immutable off-device) — lets __len__ avoid
        # re-reading spilled records
        self._live: Dict[int, int] = {}
        self._c_hit = telemetry.counter("storage.hits", tier="device",
                                        table=name)
        self._c_miss = {
            t: telemetry.counter("storage.misses", tier=TIER_NAMES[t],
                                 table=name)
            for t in (TIER_HOST, TIER_DISK, TIER_VIRGIN)}
        _MANAGERS.add(self)

    # -- access scores -----------------------------------------------------

    def touch(self, buckets: np.ndarray) -> None:
        """Bump the access EWMA of (unique) logical buckets — one clock
        tick per batch, so scores order buckets by recency-weighted
        batch frequency."""
        self._clock += 1
        b = np.asarray(buckets, np.int64)
        decay = (1.0 - self.alpha) ** (
            self._clock - self._stamp[b]).astype(np.float32)
        self._score[b] = ewma_step(self._score[b] * decay, 1.0,
                                   self.alpha)
        self._stamp[b] = self._clock

    def scores(self, buckets: np.ndarray) -> np.ndarray:
        """Effective (lazily-decayed) scores at the current clock."""
        b = np.asarray(buckets, np.int64)
        decay = (1.0 - self.alpha) ** (
            self._clock - self._stamp[b]).astype(np.float32)
        return self._score[b] * decay

    # -- planning ----------------------------------------------------------

    def plan(self, needed: np.ndarray) -> ResidencyPlan:
        """Decide which resident buckets to demote so every bucket in
        ``needed`` (unique logical ids) can be device-resident at once.
        Pure bookkeeping — commits nothing."""
        needed = np.asarray(needed, np.int64)
        if len(needed) > self.device_buckets:
            raise ValueError(
                f"batch touches {len(needed)} distinct buckets but the "
                f"device tier holds {self.device_buckets}; chunk the "
                "batch (TieredKVTable does)")
        t = self.tier[needed]
        missing = needed[t != TIER_DEVICE]
        hits = len(needed) - len(missing)
        if hits:
            self._c_hit.inc(hits)
        for code in (TIER_HOST, TIER_DISK, TIER_VIRGIN):
            n = int((self.tier[missing] == code).sum())
            if n:
                self._c_miss[code].inc(n)
        # budget-capped headroom: free slots count only up to the
        # control plane's device budget (clamped so one batch's
        # working set always fits — the physical bound above rules)
        cap = max(min(int(self.device_budget), self.device_buckets),
                  len(needed), 1)
        in_use = self.device_buckets - len(self._free_slots)
        headroom = min(len(self._free_slots), max(cap - in_use, 0))
        shortfall = len(missing) - headroom
        if shortfall <= 0:
            victims = np.zeros(0, np.int64)
        else:
            resident = self.bucket_at[self.bucket_at >= 0]
            evictable = resident[~np.isin(resident, needed)]
            order = np.argsort(self.scores(evictable), kind="stable")
            victims = evictable[order[:shortfall]]
        return ResidencyPlan(victims=victims, fills=missing)

    # -- placement transitions (caller moves the device bytes) -------------

    def demote(self, bucket: int, rec: BucketRecord) -> None:
        """Device → host (spilling the coldest warm bucket to disk if
        the arena is full). ``rec`` is the bucket's gathered device
        content; the caller has already pulled it D2H."""
        bucket = int(bucket)
        slot = int(self.slot_of[bucket])
        if slot < 0:
            raise ValueError(f"bucket {bucket} is not device-resident")
        if self.host.capacity == 0:
            self._spill(bucket, rec)
        else:
            if self.host.full:
                warm = np.fromiter(self.host.buckets(), np.int64,
                                   len(self.host))
                coldest = int(warm[np.argmin(self.scores(warm))])
                self._spill(coldest, self.host.take(coldest))
            self.host.put(bucket, rec)
            self.tier[bucket] = TIER_HOST
            telemetry.counter("storage.demotions", tier="host",
                              table=self.name).inc()
        self._live[bucket] = rec.live()
        self.slot_of[bucket] = -1
        self.bucket_at[slot] = -1
        self._free_slots.append(slot)

    def _spill(self, bucket: int, rec: BucketRecord) -> None:
        self.disk.spill(bucket, rec)
        self.tier[bucket] = TIER_DISK
        self._live[bucket] = rec.live()
        telemetry.counter("storage.demotions", tier="disk",
                          table=self.name).inc()
        telemetry.counter("storage.spills", table=self.name).inc()

    def fetch(self, bucket: int) -> Tuple[Optional[BucketRecord], str]:
        """Pull a non-resident bucket's record out of its tier (host
        take / disk fill / ``None`` for virgin) ahead of the device
        scatter. Pair with :meth:`assign_slot`."""
        bucket = int(bucket)
        code = int(self.tier[bucket])
        if code == TIER_HOST:
            rec: Optional[BucketRecord] = self.host.take(bucket)
        elif code == TIER_DISK:
            rec = self.disk.fill(bucket)
        elif code == TIER_VIRGIN:
            rec = None
        else:
            raise ValueError(
                f"bucket {bucket} already device-resident")
        src = TIER_NAMES[code] if code != TIER_VIRGIN else "virgin"
        telemetry.counter("storage.fills", tier=src,
                          table=self.name).inc()
        telemetry.counter("storage.promotions", tier=src,
                          table=self.name).inc()
        self._live.pop(bucket, None)
        return rec, src

    def assign_slot(self, bucket: int) -> Tuple[int, bool]:
        """Bind a fetched bucket to a free device slot. Returns
        ``(slot, needs_scatter)``: a virgin bucket landing on a
        never-used slot needs NO device write (the construction-time
        EMPTY rows already represent it)."""
        bucket = int(bucket)
        slot = self._free_slots.pop()
        was_used = bool(self._slot_used[slot])
        self._slot_used[slot] = True
        self.slot_of[bucket] = slot
        self.bucket_at[slot] = bucket
        self.tier[bucket] = TIER_DEVICE
        return slot, was_used

    def retire(self) -> None:
        """Drop this manager from the /statusz + alias-warning sets
        (a table replacing its manager — load() — calls this so the
        successor doesn't false-positive the shared-spill-path warn)."""
        _MANAGERS.discard(self)

    # -- introspection -----------------------------------------------------

    def offdevice_live_keys(self) -> int:
        return sum(self._live.values())

    def counts(self) -> Dict[str, int]:
        return {TIER_NAMES[c]: int((self.tier == c).sum())
                for c in (TIER_DEVICE, TIER_HOST, TIER_DISK,
                          TIER_VIRGIN)}

    def status(self) -> Dict[str, object]:
        """One /statusz tier-table row."""
        c = self.counts()
        return {
            "table": self.name,
            "total_buckets": self.total_buckets,
            "device_buckets": self.device_buckets,
            "host_buckets": self.host.capacity,
            "resident": c["device"],
            "host_used": len(self.host),
            "disk_records": len(self.disk),
            "virgin": c["virgin"],
            "disk_bytes": self.disk.nbytes(),
            "spill_path": self.disk.path,
            "clock": self._clock,
        }


def status_all() -> List[Dict[str, object]]:
    """Live tier-manager rows for the /statusz storage section,
    jax-free (``telemetry/statusz.py`` discipline)."""
    rows = []
    for m in list(_MANAGERS):
        try:
            rows.append(m.status())
        except Exception:   # a half-constructed manager must not
            continue        # take the status page down
    return sorted(rows, key=lambda r: str(r.get("table", "")))
