"""Host and disk tiers for the tiered KV store.

A tier holds whole BUCKETS (the KVTable unit of placement: one row of
``slots`` key/value/state lanes) as :class:`BucketRecord`s. The device
tier is the live ``KVTable`` triple itself (``storage/tiered_kv.py``);
this module supplies the two backing tiers under it:

- :class:`HostTier` — a preallocated numpy arena (the pinned-host-RAM
  analog on a TPU VM: page-locked allocations amortize H2D DMA setup;
  on CPU backends it is plain RAM). Fixed bucket budget, O(1)
  put/take through a free list.
- :class:`DiskTier` — a fixed-stride spill file written through
  ``io/stream.py``: every record is CRC-stamped on disk and verified
  on fill, writes/reads are retry-wrapped (``ft/retry.py``), and the
  ``storage.spill`` / ``storage.fill`` chaos fault points make the
  movement paths fault-injectable like the rest of the IO stack.
  Ranged reads (:func:`multiverso_tpu.io.stream.pread`) fetch ONE
  record per fill — a miss never pages the whole spill file in.

Records have a fixed byte size (the table's geometry is static), so
the spill file is a slot array: offset = slot * record_nbytes, freed
slots are reused, and the file never needs compaction.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Dict, Iterable, List

import numpy as np

from multiverso_tpu.ft.chaos import chaos_point
from multiverso_tpu.ft.retry import io_retry_policy
from multiverso_tpu.io.stream import open_stream, pread
from multiverso_tpu.telemetry import metrics as telemetry


@dataclasses.dataclass
class BucketRecord:
    """One logical bucket's content, host-side: the unit every tier
    stores and the device scatter/gather moves."""
    keys: np.ndarray      # (S, 2) uint32 — EMPTY sentinel = 0xFFFFFFFF
    values: np.ndarray    # (S[, D]) table dtype
    state: List[np.ndarray]   # updater state leaves, (S[, D]) each

    def live(self) -> int:
        return int((~(self.keys == np.uint32(0xFFFFFFFF)).all(-1)).sum())


class RecordSpec:
    """Fixed shapes/dtypes of one bucket record for a given table
    geometry, plus the byte codec the disk tier stores them with."""

    def __init__(self, slots: int, value_dim: int, dtype,
                 state_dtypes: Iterable, default_value: float) -> None:
        self.slots = int(slots)
        self.value_dim = int(value_dim)
        self.dtype = np.dtype(dtype)
        self.default_value = default_value
        vshape = (self.slots, self.value_dim) if self.value_dim \
            else (self.slots,)
        self.key_shape = (self.slots, 2)
        self.val_shape = vshape
        self.state_dtypes = [np.dtype(d) for d in state_dtypes]
        self.payload_nbytes = (
            self.slots * 2 * 4
            + int(np.prod(vshape)) * self.dtype.itemsize
            + sum(int(np.prod(vshape)) * d.itemsize
                  for d in self.state_dtypes))

    def empty(self) -> BucketRecord:
        """A never-touched bucket: every lane empty — what a virgin
        fill scatters (and what demoting an all-empty bucket stores)."""
        return BucketRecord(
            keys=np.full(self.key_shape, 0xFFFFFFFF, np.uint32),
            values=np.full(self.val_shape, self.default_value,
                           self.dtype),
            state=[np.zeros(self.val_shape, d)
                   for d in self.state_dtypes])

    def pack(self, rec: BucketRecord) -> bytes:
        parts = [np.ascontiguousarray(rec.keys, np.uint32).tobytes(),
                 np.ascontiguousarray(rec.values, self.dtype).tobytes()]
        parts += [np.ascontiguousarray(leaf, d).tobytes()
                  for leaf, d in zip(rec.state, self.state_dtypes)]
        raw = b"".join(parts)
        if len(raw) != self.payload_nbytes:
            raise ValueError(
                f"bucket record packed to {len(raw)} bytes, spec says "
                f"{self.payload_nbytes}")
        return raw

    def unpack(self, raw: bytes) -> BucketRecord:
        if len(raw) != self.payload_nbytes:
            raise ValueError(
                f"bucket record payload is {len(raw)} bytes, spec says "
                f"{self.payload_nbytes}")
        off = self.slots * 2 * 4
        keys = np.frombuffer(raw, np.uint32, count=self.slots * 2) \
            .reshape(self.key_shape).copy()
        nval = int(np.prod(self.val_shape))
        values = np.frombuffer(raw, self.dtype, count=nval,
                               offset=off).reshape(self.val_shape).copy()
        off += nval * self.dtype.itemsize
        state = []
        for d in self.state_dtypes:
            state.append(np.frombuffer(raw, d, count=nval, offset=off)
                         .reshape(self.val_shape).copy())
            off += nval * d.itemsize
        return BucketRecord(keys=keys, values=values, state=state)


class HostTier:
    """Warm tier: a preallocated host arena of ``capacity`` bucket
    records. Preallocation (rather than per-bucket dicts of arrays)
    keeps the warm set in a handful of large contiguous buffers — the
    layout pinned-host allocators want, and what lets a future bulk
    refill hand a whole arena slice to ``jax.device_put``."""

    def __init__(self, capacity: int, spec: RecordSpec) -> None:
        if capacity < 0:
            raise ValueError(f"host tier capacity {capacity} < 0")
        self.capacity = int(capacity)
        self._spec = spec
        n = self.capacity
        self._keys = np.empty((n,) + spec.key_shape, np.uint32)
        self._values = np.empty((n,) + spec.val_shape, spec.dtype)
        self._state = [np.empty((n,) + spec.val_shape, d)
                       for d in spec.state_dtypes]
        self._row_of: Dict[int, int] = {}
        self._free = list(range(n - 1, -1, -1))

    def __len__(self) -> int:
        return len(self._row_of)

    def __contains__(self, bucket: int) -> bool:
        return bucket in self._row_of

    @property
    def full(self) -> bool:
        return not self._free

    def buckets(self):
        return self._row_of.keys()

    def put(self, bucket: int, rec: BucketRecord) -> None:
        if bucket in self._row_of:
            raise ValueError(f"bucket {bucket} already host-resident")
        if not self._free:
            raise RuntimeError(
                f"host tier full ({self.capacity} buckets); spill a "
                "victim first")
        row = self._free.pop()
        self._keys[row] = rec.keys
        self._values[row] = rec.values
        for arena, leaf in zip(self._state, rec.state):
            arena[row] = leaf
        self._row_of[bucket] = row

    def _read(self, row: int) -> BucketRecord:
        return BucketRecord(
            keys=self._keys[row].copy(),
            values=self._values[row].copy(),
            state=[a[row].copy() for a in self._state])

    def peek(self, bucket: int) -> BucketRecord:
        """Copy a record out WITHOUT freeing its row (checkpoint
        export snapshots the warm set in place)."""
        return self._read(self._row_of[bucket])

    def take(self, bucket: int) -> BucketRecord:
        row = self._row_of.pop(bucket)
        rec = self._read(row)
        self._free.append(row)
        return rec

    def live_keys(self) -> int:
        if not self._row_of:
            return 0
        rows = np.fromiter(self._row_of.values(), np.int64,
                           len(self._row_of))
        return int((~(self._keys[rows] == np.uint32(0xFFFFFFFF))
                    .all(-1)).sum())


class DiskTier:
    """Cold tier: fixed-stride spill file of CRC-stamped records.

    On-disk record = 16-byte header (``<QII``: logical bucket id,
    crc32 of the payload, payload length) + the packed payload. The
    header pins the record to its bucket, so a fill that lands on a
    stale or torn slot fails loudly (id or CRC mismatch) instead of
    silently restoring foreign rows — the same stamp-and-verify
    contract as ``savez_stream``.

    All IO goes through ``io/stream.py`` (scheme dispatch, per-scheme
    ``io.{read,write}.bytes`` counters, ``io.read``/``io.write`` chaos
    points) wrapped in the env-configured retry policy; the
    ``storage.spill``/``storage.fill`` chaos points guard the tier
    operations themselves.
    """

    _HEADER = struct.Struct("<QII")

    def __init__(self, path: str, spec: RecordSpec) -> None:
        self.path = path
        self._spec = spec
        self.record_nbytes = self._HEADER.size + spec.payload_nbytes
        self._slot_of: Dict[int, int] = {}
        self._free: List[int] = []
        self._nslots = 0
        self._created = False

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, bucket: int) -> bool:
        return bucket in self._slot_of

    def buckets(self):
        return self._slot_of.keys()

    def _ensure_file(self) -> None:
        if not self._created:
            open_stream(self.path, "wb").close()
            self._created = True

    def spill(self, bucket: int, rec: BucketRecord) -> None:
        if bucket in self._slot_of:
            # a re-spilled bucket overwrites its old slot in place
            slot = self._slot_of[bucket]
        elif self._free:
            slot = self._free.pop()
        else:
            slot = self._nslots
        payload = self._spec.pack(rec)
        head = self._HEADER.pack(bucket, zlib.crc32(payload),
                                 len(payload))
        self._ensure_file()

        def write() -> None:
            # inside the retried closure: an injected transient fault
            # here is re-attempted exactly like a real IO error
            chaos_point("storage.spill")
            f = open_stream(self.path, "r+b")
            try:
                f.seek(slot * self.record_nbytes)
                f.write(head + payload)
            finally:
                f.close()

        io_retry_policy("storage.spill").call(write)
        telemetry.counter("storage.bytes", dir="spill",
                          tier="disk").inc(self.record_nbytes)
        # commit the slot bookkeeping only after the bytes landed
        self._slot_of[bucket] = slot
        self._nslots = max(self._nslots, slot + 1)

    def _read_slot(self, bucket: int, slot: int) -> BucketRecord:
        def read() -> bytes:
            chaos_point("storage.fill")
            return pread(self.path, slot * self.record_nbytes,
                         self.record_nbytes)

        raw = io_retry_policy("storage.fill").call(read)
        got_bucket, crc, nbytes = self._HEADER.unpack(
            raw[:self._HEADER.size])
        payload = raw[self._HEADER.size:]
        if got_bucket != bucket or nbytes != len(payload):
            raise IOError(
                f"spill file {self.path!r} slot {slot}: expected "
                f"bucket {bucket}, found bucket {got_bucket} "
                f"({nbytes} bytes)")
        if zlib.crc32(payload) != crc:
            raise IOError(
                f"spill file {self.path!r} slot {slot} (bucket "
                f"{bucket}): CRC mismatch — record is torn or stale")
        telemetry.counter("storage.bytes", dir="fill",
                          tier="disk").inc(self.record_nbytes)
        return self._spec.unpack(payload)

    def peek(self, bucket: int) -> BucketRecord:
        """Read a record WITHOUT freeing its slot (checkpoint export)."""
        return self._read_slot(bucket, self._slot_of[bucket])

    def fill(self, bucket: int) -> BucketRecord:
        rec = self._read_slot(bucket, self._slot_of[bucket])
        self._free.append(self._slot_of.pop(bucket))
        return rec

    def nbytes(self) -> int:
        return self._nslots * self.record_nbytes

