"""TieredKVTable: a KVTable whose capacity ceiling is disk, not HBM.

The table keeps the KVTable contract (get/add/store/load, deferred
overflow, the prepare/dispatch staging split) over a LOGICAL geometry
of ``total_buckets × slots`` while the device arrays hold only
``device_buckets`` bucket rows — the hot set. A host-side injective
map (``TierManager.slot_of``) translates logical bucket ids to device
slots; a miss on a get/add transparently faults the bucket in ON THE
DISPATCH THREAD (the single thread that owns the table's buffers —
the same contract every other dispatch rides):

1. ``plan``: the tier manager picks the coldest resident buckets
   outside the batch (per-bucket access EWMAs, lazily decayed) as
   victims,
2. demote: one jitted gather pulls the victims' rows D2H into the
   host arena (the warm tier; its own coldest bucket cascades to the
   disk spill file when the arena is full),
3. fill: missing buckets come back from the host arena or a ranged
   ``pread`` of the spill file (never-touched buckets are "virgin" —
   all-empty by construction, no IO), and one jitted scatter lands
   them in the freed slots.

Batches touching more distinct buckets than the device tier holds are
CHUNKED: each chunk faults its working set in and dispatches
separately — bucket-capacity pressure becomes demotion + retry
instead of a dropped batch. (Per-bucket slot overflow — more than
``slots`` live keys hashing to one logical bucket — still raises with
the named buckets; size ``capacity`` for the key population as usual,
just without a device-HBM ceiling.)

The kernel path: lanes must be re-sorted by device slot AFTER the
fault-in (placement is decided at dispatch, not prepare), so the
table keeps the plain XLA probe/lookup closures (``ALLOW_PALLAS =
False``) — the non-tiered hot path and its Pallas engines are
untouched. The prepare half (:meth:`prepare_add`) stays thread-safe
for the ``KVStagingWriter`` split: it validates/hashes/sorts on the
worker thread and defers packing + H2D to :meth:`add_prepared`.

Checkpoints: the export gathers EVERY tier into logical bucket order
— content is a pure function of op history, independent of placement
— and records each bucket's tier in the payload (``tier_of``), so a
resume restores bit-identical content AND re-establishes the
placement. ``RunCheckpointManager`` covers the table automatically
(duck-typed on ``export_checkpoint_async``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from multiverso_tpu import core
from multiverso_tpu.storage.manager import (TIER_DEVICE, TIER_DISK,
                                            TIER_HOST, TIER_NAMES,
                                            TierConfig, TierManager)
from multiverso_tpu.storage.tiers import BucketRecord, RecordSpec
from multiverso_tpu.tables.base import (loadz_stream, pack_state,
                                        unpack_state)
from multiverso_tpu.tables.hashing import _bucket, _hash_u64
from multiverso_tpu.tables.kv_table import KVTable
from multiverso_tpu.updaters import AddOption
from multiverso_tpu.utils import log


class _TieredPreparedAdd:
    """Prepare-half product of a tiered Add: host arrays sorted by
    LOGICAL bucket. Packing (and the H2D) waits for the dispatch
    thread — lane→slot translation needs the fault-in that only the
    buffer-owning thread may run."""

    __slots__ = ("keys", "deltas", "logical", "option", "elems",
                 "nbytes")

    def __init__(self, keys, deltas, logical, option, elems, nbytes):
        self.keys = keys
        self.deltas = deltas
        self.logical = logical
        self.option = option
        self.elems = elems
        self.nbytes = nbytes


class TieredKVTable(KVTable):
    """KVTable over HBM + host RAM + disk. See the module docstring.

    Extra constructor knobs (budgets; ``MVTPU_TIER_*`` env supplies
    defaults — see ``storage/manager.py``):

    - ``device_buckets`` — hot-set size in buckets (the HBM budget);
      rounded up to the mesh model-axis multiple like every KVTable
      geometry.
    - ``host_buckets`` — warm-arena size in buckets.
    - ``spill_dir`` — directory for the cold tier's spill file.
    - ``tier_alpha`` — access-EWMA smoothing for victim selection.
    """

    ALLOW_PALLAS = False

    def __init__(self, capacity: int, value_dim: int = 0,
                 dtype: Any = "float32", *, slots_per_bucket: int = 8,
                 updater: Optional[str] = None, mesh=None,
                 name: str = "tiered_kv_table",
                 default_value: float = 0.0,
                 default_option: Optional[AddOption] = None,
                 shard_update: bool = False,
                 device_buckets: Optional[int] = None,
                 host_buckets: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 tier_alpha: Optional[float] = None) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        total = -(-capacity // slots_per_bucket)
        cfg = TierConfig.from_env(total, device_buckets=device_buckets,
                                  host_buckets=host_buckets,
                                  spill_dir=spill_dir,
                                  alpha=tier_alpha)
        dev_buckets = min(max(int(cfg.device_buckets), 1), total)
        # the parent builds the DEVICE tier: arrays sized to the hot
        # set, geometry rounded to the mesh like any KVTable
        super().__init__(dev_buckets * slots_per_bucket, value_dim,
                         dtype, slots_per_bucket=slots_per_bucket,
                         updater=updater, mesh=mesh, name=name,
                         default_value=default_value,
                         default_option=default_option,
                         shard_update=shard_update)
        # ... and this subclass re-points the LOGICAL geometry at the
        # full capacity: hashing is mod total_buckets, device bucket
        # ids exist only between fault-in and dispatch
        self.total_buckets = max(int(total), self.num_buckets)
        self.capacity = self.total_buckets * self.slots
        state_leaves = jax.tree.leaves(self.state)
        self.spec = RecordSpec(
            self.slots, self.value_dim, self.dtype,
            [np.dtype(leaf.dtype) for leaf in state_leaves],
            default_value)
        self.tiers = TierManager(self.name, self.total_buckets, cfg,
                                 self.spec)
        self._n_state = len(state_leaves)
        self._build_tier_jits()
        log.debug(
            "tiered kv table %r: %d logical buckets over %d device + "
            "%d host (+disk at %s)", name, self.total_buckets,
            self.tiers.device_buckets, self.tiers.host.capacity,
            self.tiers.disk.path)

    def _build_tier_jits(self) -> None:
        repl = NamedSharding(self.mesh, P())
        state_sh = jax.tree.map(lambda _: self._state_sharding,
                                self.state)
        repl_state = jax.tree.map(lambda _: repl, self.state)

        def gather_rows(k, v, s, idx):
            return (jnp.take(k, idx, axis=0), jnp.take(v, idx, axis=0),
                    jax.tree.map(lambda a: jnp.take(a, idx, axis=0), s))

        # victims come back replicated so every process reads the same
        # bytes (multihost demotion decisions stay in SPMD lockstep)
        self._gather_rows = jax.jit(
            gather_rows, out_shardings=(repl, repl, repl_state))

        def scatter_rows(k, v, s, idx, nk, nv, ns):
            return (k.at[idx].set(nk), v.at[idx].set(nv),
                    jax.tree.map(
                        lambda a, na: a.at[idx].set(na.astype(a.dtype)),
                        s, ns))

        self._scatter_rows = jax.jit(
            scatter_rows, donate_argnums=(0, 1, 2),
            out_shardings=(self._key_sharding, self._val_sharding,
                           state_sh))

    # logical hashing: mod the FULL geometry
    def _buckets_of(self, keys: np.ndarray) -> np.ndarray:
        return (_hash_u64(keys)
                % np.uint64(self.total_buckets)).astype(np.int64)

    # -- fault-in (dispatch thread only) -----------------------------------

    def _ensure_resident(self, needed: np.ndarray) -> None:
        """Make every (unique) logical bucket in ``needed`` device
        resident: demote the plan's victims, then fill the misses.
        Runs on the dispatch thread — it swaps the live buffers."""
        mgr = self.tiers
        mgr.touch(needed)
        plan = mgr.plan(needed)
        if plan.victims.size:
            m = len(plan.victims)
            idx = np.full(_bucket(m), 0, np.int32)
            idx[:m] = mgr.slot_of[plan.victims]
            k_f, v_f, s_f = self._gather_rows(
                self.keys, self.values, self.state,
                core.place(idx, mesh=self.mesh))
            hk = np.asarray(k_f)
            hv = np.asarray(v_f)
            hs = [np.asarray(leaf) for leaf in jax.tree.leaves(s_f)]
            for i, b in enumerate(plan.victims):
                mgr.demote(int(b), BucketRecord(
                    keys=hk[i], values=hv[i],
                    state=[leaf[i] for leaf in hs]))
        if not plan.fills.size:
            return
        slots: List[int] = []
        recs: List[BucketRecord] = []
        for b in plan.fills:
            rec, _src = mgr.fetch(int(b))
            slot, was_used = mgr.assign_slot(int(b))
            if rec is None and not was_used:
                continue    # virgin bucket on a never-written slot:
            slots.append(slot)  # the EMPTY rows already represent it
            recs.append(rec if rec is not None else self.spec.empty())
        if not slots:
            return
        m = len(slots)
        p = _bucket(m)
        idx = np.empty(p, np.int32)
        idx[:m] = slots
        idx[m:] = slots[-1]    # pad lanes rewrite the last row in place
        nk = np.stack([r.keys for r in recs]
                      + [recs[-1].keys] * (p - m))
        nv = np.stack([r.values for r in recs]
                      + [recs[-1].values] * (p - m))
        ns = [np.stack([r.state[j] for r in recs]
                       + [recs[-1].state[j]] * (p - m))
              for j in range(self._n_state)]
        ns_tree = jax.tree.unflatten(
            jax.tree.structure(self.state), ns)
        put = lambda a: core.place(a, mesh=self.mesh)
        self.keys, self.values, self.state = self._scatter_rows(
            self.keys, self.values, self.state, put(idx), put(nk),
            put(nv), jax.tree.map(put, ns_tree))

    def _chunk_spans(self, sorted_logical: np.ndarray) -> List[Tuple[int, int]]:
        """Split a bucket-sorted lane array into [lo, hi) spans, each
        touching at most ``device_buckets`` distinct buckets."""
        n = len(sorted_logical)
        budget = self.tiers.device_buckets
        starts = np.flatnonzero(np.concatenate(
            [[True], sorted_logical[1:] != sorted_logical[:-1]]))
        if len(starts) <= budget:
            return [(0, n)]
        spans = []
        for i in range(0, len(starts), budget):
            lo = int(starts[i])
            hi = int(starts[i + budget]) if i + budget < len(starts) \
                else n
            spans.append((lo, hi))
        return spans

    # -- get ---------------------------------------------------------------

    def get_jax(self, keys) -> Tuple[jax.Array, jax.Array]:
        self._check_overflow()
        keys = self._check_keys(keys)
        logical = self._buckets_of(keys)
        uniq = np.unique(logical)
        if len(uniq) <= self.tiers.device_buckets:
            self._ensure_resident(uniq)
            slots = self.tiers.slot_of[logical].astype(np.int32)
            return self._get_with_buckets(keys, slots)
        # miss storm wider than the device tier: sort lanes by logical
        # bucket, fault in + look up chunk by chunk, unpermute at the
        # end so callers still see their own key order
        order = np.argsort(logical, kind="stable")
        sk, sl = keys[order], logical[order]
        vals_parts, found_parts = [], []
        for lo, hi in self._chunk_spans(sl):
            self._ensure_resident(np.unique(sl[lo:hi]))
            slots = self.tiers.slot_of[sl[lo:hi]].astype(np.int32)
            v, f = self._get_with_buckets(sk[lo:hi], slots)
            vals_parts.append(v)
            found_parts.append(f)
        inv = np.empty(len(keys), np.int64)
        inv[order] = np.arange(len(keys))
        inv_dev = core.place(inv, mesh=self.mesh)
        return (jnp.take(jnp.concatenate(vals_parts), inv_dev, axis=0),
                jnp.take(jnp.concatenate(found_parts), inv_dev,
                         axis=0))

    # -- add ---------------------------------------------------------------

    def prepare_add(self, keys, deltas,
                    option: Optional[AddOption] = None):
        """Thread-safe host half (the ``KVStagingWriter`` seam):
        validate/hash/sort by LOGICAL bucket. No H2D here — operand
        order depends on slot placement, which is decided at dispatch
        (after the fault-in)."""
        keys, deltas, logical, opt = self._prep_host_add(keys, deltas,
                                                         option)
        return _TieredPreparedAdd(
            keys=keys, deltas=deltas, logical=logical, option=opt,
            elems=int(deltas.size),
            nbytes=int(deltas.size) * self.dtype.itemsize)

    def add_prepared(self, prepared, sync: bool = False):
        if not isinstance(prepared, _TieredPreparedAdd):
            # a parent-layout batch (e.g. hand-built in tests) rides
            # the parent path untouched — its bucket ids are already
            # device-geometry
            return super().add_prepared(prepared, sync=sync)
        self._poll_overflow()
        handle = None
        for lo, hi in self._chunk_spans(prepared.logical):
            lk = prepared.logical[lo:hi]
            self._ensure_resident(np.unique(lk))
            slots = self.tiers.slot_of[lk].astype(np.int32)
            # stable re-sort by slot: per-bucket batch order survives
            # (slot↔bucket is injective), and the packed lanes meet the
            # engine's sorted-by-bucket operand contract
            order = np.argsort(slots, kind="stable")
            packed = self._pack_prepared(
                prepared.keys[lo:hi][order],
                prepared.deltas[lo:hi][order], slots[order],
                prepared.option)
            handle = super().add_prepared(packed, sync=False)
        if sync:
            handle.wait()
            self._check_overflow()
        return handle

    def _overflowing_buckets(self, host_buckets) -> list:
        """The parent stashes DEVICE slot ids with the overflow flag;
        translate back to logical bucket ids (best effort — a slot
        may have been re-assigned since) so the raise names buckets
        the caller can recognize."""
        slots = super()._overflowing_buckets(host_buckets)
        out = []
        for s in slots:
            if 0 <= s < len(self.tiers.bucket_at) \
                    and self.tiers.bucket_at[s] >= 0:
                out.append(int(self.tiers.bucket_at[s]))
            else:
                out.append(int(s))
        return out

    def __len__(self) -> int:
        """Live keys across ALL tiers."""
        self._check_overflow()
        on_device = int(np.asarray(self._count_live(self.keys)))
        return on_device + self.tiers.offdevice_live_keys()

    # -- checkpoint --------------------------------------------------------

    def export_checkpoint_async(self):
        """Export the FULL logical table, placement-independent.

        Dispatch half: jitted copy of the device triple (survives the
        next add's donation) + host-arena copies + disk reads of the
        cold records — synchronous IO, acceptable at checkpoint
        cadence — plus a snapshot of the placement (``tier_of``).
        Blocking half (``finish``): D2H the device copy and merge every
        tier into ``total_buckets``-major arrays. Content is a pure
        function of the op history, so two runs with different
        placements (different budgets, different access order inside a
        step) export byte-identical payloads."""
        self.flush_coalesced()
        self._check_overflow()
        mgr = self.tiers
        if self._export_copy is None:
            state_sh = jax.tree.map(lambda _: self._state_sharding,
                                    self.state)
            self._export_copy = jax.jit(
                lambda k, v, s: (jnp.copy(k), jnp.copy(v),
                                 jax.tree.map(jnp.copy, s)),
                out_shardings=(self._key_sharding, self._val_sharding,
                               state_sh))
        keys_fut, vals_fut, state_fut = self._export_copy(
            self.keys, self.values, self.state)
        bucket_at = mgr.bucket_at.copy()
        tier_of = mgr.tier.copy()
        offdev = {int(b): mgr.host.peek(int(b))
                  for b in mgr.host.buckets()}
        offdev.update({int(b): mgr.disk.peek(int(b))
                       for b in mgr.disk.buckets()})
        manifest = {"magic": self.KV_MAGIC, "name": self.name,
                    "capacity": self.capacity,
                    "value_dim": self.value_dim, "slots": self.slots,
                    "num_buckets": self.total_buckets,
                    "dtype": self.dtype.name,
                    "updater": self.updater.name,
                    "step": self.default_option.step,
                    "tiered": True,
                    "device_buckets": mgr.device_buckets}

        def finish():
            dk = np.asarray(keys_fut)
            dv = np.asarray(vals_fut)
            ds = [np.asarray(leaf)
                  for leaf in jax.tree.leaves(state_fut)]
            T = self.total_buckets
            full_k = np.full((T,) + self.spec.key_shape, 0xFFFFFFFF,
                             np.uint32)
            full_v = np.full((T,) + self.spec.val_shape,
                             self.default_value, self.dtype)
            full_s = [np.zeros((T,) + self.spec.val_shape, d)
                      for d in self.spec.state_dtypes]
            live_slots = np.flatnonzero(bucket_at >= 0)
            dst = bucket_at[live_slots]
            full_k[dst] = dk[live_slots]
            full_v[dst] = dv[live_slots]
            for fs, leaf in zip(full_s, ds):
                fs[dst] = leaf[live_slots]
            for b, rec in offdev.items():
                full_k[b] = rec.keys
                full_v[b] = rec.values
                for fs, leaf in zip(full_s, rec.state):
                    fs[b] = leaf
            fill = (~(full_k == 0xFFFFFFFF).all(-1)).sum(-1)
            payload = {"keys": full_k, "values": full_v,
                       "bucket_fill": fill.astype(np.int32),
                       "tier_of": tier_of}
            manifest["n_state_leaves"] = pack_state(
                jax.tree.unflatten(jax.tree.structure(self.state),
                                   full_s), payload)
            self._record_op("store", full_v.size,
                            sum(a.nbytes for a in payload.values()))
            return manifest, payload
        return finish

    def load(self, uri: str) -> None:
        """Restore a tiered checkpoint: bit-identical logical content,
        placement re-established from the recorded ``tier_of`` (capped
        by the CURRENT budgets — a bucket that no longer fits its
        recorded tier cascades down; never-touched buckets stay
        virgin)."""
        self.flush_coalesced()
        self._check_overflow()
        manifest, data = loadz_stream(uri, self.KV_MAGIC)
        for field, mine in (("value_dim", self.value_dim),
                            ("dtype", self.dtype.name),
                            ("slots", self.slots),
                            ("num_buckets", self.total_buckets)):
            if manifest[field] != mine:
                raise ValueError(
                    f"tiered kv table {field} mismatch: checkpoint "
                    f"{manifest[field]!r} != table {mine!r} (tiered "
                    "restores require identical logical geometry)")
        if manifest["updater"] != self.updater.name:
            raise ValueError(
                f"checkpoint updater {manifest['updater']!r} != "
                f"{self.updater.name!r}")
        full_k = data["keys"]
        full_v = data["values"]
        full_s = unpack_state(data, manifest["n_state_leaves"],
                              self.state, lambda leaf, tmpl:
                              np.asarray(leaf, tmpl.dtype))
        full_s_leaves = jax.tree.leaves(full_s)
        tier_of = np.asarray(
            data.get("tier_of",
                     np.full(self.total_buckets, TIER_DEVICE,
                             np.int8)), np.int8)
        # fresh placement state (the old spill file is abandoned; the
        # first new spill atomically replaces it)
        self.tiers.retire()
        mgr = TierManager(self.name, self.total_buckets, self.tiers.config,
                          self.spec)
        dev_shape = (self.num_buckets,) + self.spec.key_shape
        new_k = np.full(dev_shape, 0xFFFFFFFF, np.uint32)
        new_v = np.full((self.num_buckets,) + self.spec.val_shape,
                        self.default_value, self.dtype)
        new_s = [np.zeros((self.num_buckets,) + self.spec.val_shape, d)
                 for d in self.spec.state_dtypes]

        def rec_of(b: int) -> BucketRecord:
            return BucketRecord(
                keys=full_k[b], values=full_v[b],
                state=[leaf[b] for leaf in full_s_leaves])

        for code in (TIER_DEVICE, TIER_HOST, TIER_DISK):
            for b in np.flatnonzero(tier_of == code):
                b = int(b)
                rec = rec_of(b)
                want = code
                if want == TIER_DEVICE and not mgr._free_slots:
                    want = TIER_HOST
                if want == TIER_HOST and mgr.host.full:
                    want = TIER_DISK
                if want == TIER_DEVICE:
                    slot, _ = mgr.assign_slot(b)
                    new_k[slot] = rec.keys
                    new_v[slot] = rec.values
                    for arr, leaf in zip(new_s, rec.state):
                        arr[slot] = leaf
                elif want == TIER_HOST:
                    mgr.host.put(b, rec)
                    mgr.tier[b] = TIER_HOST
                    mgr._live[b] = rec.live()
                else:
                    mgr.disk.spill(b, rec)
                    mgr.tier[b] = TIER_DISK
                    mgr._live[b] = rec.live()
        keys_dev = jax.device_put(new_k, self._key_sharding)
        vals_dev = jax.device_put(new_v, self._val_sharding)
        state_dev = jax.tree.unflatten(
            jax.tree.structure(self.state),
            [jax.device_put(arr, self._state_sharding)
             for arr in new_s])
        self._record_op("load", full_v.size,
                        full_k.nbytes + full_v.nbytes)
        self.keys, self.values, self.state = keys_dev, vals_dev, state_dev
        self.tiers = mgr
        self.default_option.step = int(manifest.get("step", 0))
        with self._option_lock:
            self.generation += 1
        self._notify_views()


# referenced for the /statusz storage section + README
_ = (TIER_DEVICE, TIER_HOST, TIER_DISK, TIER_NAMES)
